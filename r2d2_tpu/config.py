"""Typed, hierarchical configuration for the TPU-native R2D2 framework.

Replaces the reference's flat module of ~40 globals (/root/reference/config.py:1-62)
with an immutable dataclass tree. Every field keeps the reference default so the
stock Atari-Boxing / ViZDoom-Basic runs are a config-file change, not a code
change. Unlike the reference — where cross-module constants made the module the
single source of truth (/root/reference/worker.py:151-152) — components here take
their whole sub-config, so two differently-configured stacks can coexist in one
process (needed for multiplayer population training, /root/reference/train.py:28-45).

CLI overriding uses dotted paths (``--replay.capacity=100000``), covering the
genetic-search hook: the reference tags searchable fields ``<-- GEN``
(/root/reference/config.py:12-57); here they are enumerated in GENETIC_SEARCH_SPACE.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


@dataclass(frozen=True)
class EnvConfig:
    """Environment selection and preprocessing (ref config.py:2-13)."""

    # Composed gym id, e.g. "VizdoomBasic-v0", "FakeR2D2-v0", "ALE/Boxing-v5".
    game_name: str = "Fake"
    env_type: str = "R2D2-v0"
    frame_stack: int = 4
    frame_height: int = 84
    frame_width: int = 84
    frame_skip: int = 1
    # Fixed episode length of the synthetic envs (Fake and the jitted
    # Grid/JaxFake backends — envs/jax_env.py); engine-backed envs ignore
    # it. The on-device acting path requires episode_len to be a multiple
    # of replay.block_length so episode boundaries coincide with block
    # boundaries (validated when actor.on_device is set).
    episode_len: int = 120
    # Grid side length of the jitted gridworld (env kind "Grid").
    grid_size: int = 6
    # The reference's factory defaults clip_rewards=True (environment.py:82)
    # but every call site passes False — actors (worker.py:507) and eval
    # (test.py:97) — relying on invertible value rescaling for reward
    # magnitudes instead. Match the effective behavior, not the dead default.
    clip_rewards: bool = False
    # Shaped multiplayer reward constants (ref base_gym_env.py:199-211).
    reward_hurt: float = -20.0
    reward_death: float = -100.0
    reward_ammo: float = -5.0
    reward_hit: float = 25.0
    reward_frag: float = 100.0

    @property
    def env_id(self) -> str:
        return self.game_name + self.env_type

    @property
    def obs_shape(self) -> Tuple[int, int, int]:
        return (self.frame_stack, self.frame_height, self.frame_width)


@dataclass(frozen=True)
class NetworkConfig:
    """Recurrent dueling/double DQN architecture (ref config.py:54-57, model.py:22-46)."""

    hidden_dim: int = 512
    cnn_out_dim: int = 1024
    use_dueling: bool = True
    use_double: bool = False
    # Conv torso: (out_channels, kernel, stride) triples — Nature DQN.
    conv_layers: Tuple[Tuple[int, int, int], ...] = ((32, 8, 4), (64, 4, 2), (64, 3, 1))
    # bf16 activation/compute policy (replaces torch.cuda.amp, ref
    # config.py:35; f32 params and f32 Q outputs either way). Tri-state:
    # "auto" (default) = bf16 iff the backend is TPU — the measured winner
    # there (+28% once the obs decode emits bf16 natively, PERF.md) —
    # while CPU keeps f32 (bf16 is emulated and slower). "on"/"off" force.
    # The MXU already multiplies in bf16 under f32 (default precision);
    # the policy additionally halves activation bytes, which is where the
    # win comes from. Loss parity vs f32 is tolerance-tested. Typed str
    # like the sibling pallas tri-states so --network.bf16=off works from
    # the CLI (resolve_pallas_setting still accepts legacy bools from old
    # serialized configs).
    bf16: str = "auto"
    # lax.scan unroll factor for the LSTM time scan (identical math; >1
    # trades compile time for fewer sequential loop boundaries on the
    # 55-step serial chain). Set from measurement — see PERF.md.
    scan_unroll: int = 1
    # Rewrite the first conv as the EXACT conv over a 2x2 space-to-depth
    # input (kernel/stride halved, channels x4): the frame stack's 4
    # channels waste most of the MXU's input lanes otherwise. "on"/"off"
    # ONLY — no "auto": the setting changes the parameter layout, so a
    # backend-dependent resolution would build incompatible param trees on
    # heterogeneous hosts (TPU learner vs CPU actors/eval). Checkpoints
    # are per-setting. Default off pending TPU measurement — see PERF.md.
    space_to_depth: str = "off"
    # Run the LSTM time scan as ONE fused pallas kernel (ops/pallas_lstm.py):
    # Wh resident in VMEM across all T steps, h/c carried in f32 scratch,
    # custom-VJP backward kernel — attacks the per-iteration while-loop
    # overhead on the serial recurrent chain (the profiled wall, PERF.md).
    # Tri-state like the sibling pallas knobs; compute-only (no parameter
    # layout change), tolerance-parity-tested vs the lax.scan path.
    # Default "off" pending the TPU A/B (bench cell bf16_spd16_plstm).
    pallas_lstm: str = "off"
    # Timesteps per grid iteration of the fused LSTM kernel (must divide
    # the unroll length; 55 -> 1, 5, 11). >1 amortizes per-iteration
    # grid/DMA bookkeeping against bigger VMEM blocks — a chip
    # measurement (bench.py sweeps the plstm cells).
    pallas_lstm_block: int = 1
    # Debug/dryrun only: run the fused-LSTM kernel in pallas interpret
    # mode (works on any backend, slow) — how the driver's multichip
    # dryrun executes the kernel's exact semantics without a TPU.
    pallas_lstm_interpret: bool = False
    # -- quantized inference plane (ISSUE 14) --
    # Dtype of the ACTING/SERVING forward only (local scalar/vector
    # actors, the policy server's micro-batched dispatch, and the anakin
    # acting scan — all through the ONE shared forward); the learner's
    # training math is untouched. "f32" (default) = every existing
    # program byte-identical. "bf16" publishes a bf16 weight twin (2x
    # weight-bytes cut); "int8" publishes a per-channel symmetric int8
    # twin of every matmul kernel (~4x kernel-bytes cut), dequantized
    # per-channel into the compute-dtype matmul at apply time — the
    # acting forward is weight-streaming-bound at acting batch sizes
    # (costmodel tables; Podracer, arXiv 2104.06272), so cutting weight
    # bytes is the direct multiplier on env-steps/s and requests/s.
    # Quantization happens ONCE at weight publish (a quantized twin
    # rides the existing publish plumbing — no hot-path requantization);
    # the LSTM carry stays f32 so recurrent state never accumulates
    # quantization drift. Quality is guarded in-graph: a per-interval
    # probe runs the f32 twin on the live batch and feeds the record's
    # 'quant' block + the quant_divergence alert rule.
    inference_dtype: str = "f32"


@dataclass(frozen=True)
class SequenceConfig:
    """R2D2 sequence windowing (ref config.py:48-51)."""

    burn_in_steps: int = 40
    learning_steps: int = 10
    forward_steps: int = 5  # n-step return horizon

    @property
    def seq_len(self) -> int:
        return self.burn_in_steps + self.learning_steps + self.forward_steps


@dataclass(frozen=True)
class ReplayConfig:
    """Prioritized sequence replay (ref config.py:26-33, worker.py:38-78)."""

    capacity: int = 500_000          # env steps
    block_length: int = 400          # steps per actor-produced block
    prio_exponent: float = 0.9       # alpha; 0 disables prioritization
    importance_sampling_exponent: float = 0.6  # beta
    batch_size: int = 128            # sequences per training batch
    learning_starts: int = 1_000     # min buffer steps before training
    # Where replay lives: "device" = HBM-resident jitted path (the TPU-native
    # design), "host" = numpy + native C++ sum-tree feeder (reference-style).
    placement: str = "device"
    # Gather sampled obs windows with the pallas scalar-prefetch kernel
    # (ops/pallas_kernels.py gather_rows_pallas): "on", "off", or "auto"
    # (pallas iff the backend is TPU — 2.6x the XLA gather there, BENCH_r03).
    pallas_sample_gather: str = "auto"
    # EXACT-read window gather (device placement): pad the stored frame to
    # the uint8 tile (84x84 -> 96x128) and DMA only each sampled window via
    # async copy instead of the whole ring row (7.7x read amplification at
    # the reference shape -> 1.74x). Measured WINNER on v5e: +4.2% on the
    # full fused step (90.7 vs 87.0 steps/s, BENCH r4) — hence "auto"
    # (= on iff TPU, like the sibling knobs). THE TRADE: storage also grows
    # 1.74x (5.7 vs 3.3 GiB obs ring at the default 500k capacity), so a
    # ring sized near the HBM limit (~>1M frames on a 16 GiB chip) can OOM
    # at replay_init — set "off" there and keep the row-gather's 2.6x win.
    # Requires pallas_sample_gather; the stored obs layout changes with it.
    pallas_exact_gather: str = "auto"
    # Batched + pipelined ingestion (device placement): the learner's
    # stager thread coalesces up to this many actor blocks per drain into
    # ONE stacked host→device transfer + ONE jitted replay_add_many
    # dispatch, staged in the background so the transfer overlaps the
    # running train dispatch. -1 = auto (8 on TPU, where per-block dispatch
    # over the tunnel dominates the learner loop — PERF.md "Experience
    # ingestion"; 1 on CPU). 1 = the legacy synchronous per-block path.
    # Capped by num_blocks (scatter rows must not alias).
    ingest_batch_blocks: int = -1
    # Max blocks the learner pops from the feeder queue per drain call —
    # ONE knob for both the training loop and the orchestrator's warm-up
    # loop (they used to hardcode 32 and 16 respectively).
    drain_max_blocks: int = 32
    # Reverb-style rate limiter: pause block ingestion (back-pressuring
    # actors through the bounded feeder queue) once
    # env_steps > learning_starts + ratio * train_steps. Pins the
    # data-collection : learning ratio so training dynamics do not depend
    # on the actors/learner scheduling balance of the host. 0 = unthrottled
    # (the reference's behavior: actors free-run, worker.py:528).
    max_env_steps_per_train_step: float = 0.0

    def resolved_ingest_batch_blocks(self) -> int:
        """-1 auto: batched ingestion (8 blocks/dispatch) iff the backend
        is TPU — there the per-block python dispatch + tunnel transfer is
        the measured learner-loop cost; on CPU dispatch is cheap and the
        legacy per-block path stays the default."""
        if self.ingest_batch_blocks > 0:
            return self.ingest_batch_blocks
        import jax
        return 8 if jax.default_backend() == "tpu" else 1


@dataclass(frozen=True)
class OptimConfig:
    """Learner optimization (ref config.py:16-23, worker.py:268-269,341-346)."""

    lr: float = 1e-4
    adam_eps: float = 1e-3
    grad_norm: float = 40.0
    gamma: float = 0.997
    target_net_update_interval: int = 2_000
    training_steps: int = 500_000
    value_rescale_eps: float = 1e-2
    # Mixed-priority weights: eta*max + (1-eta)*mean (ref worker.py:246).
    priority_eta: float = 0.9
    # Decode uint8 obs windows with the fused pallas kernel
    # (ops/pallas_kernels.py): "on", "off", or "auto" (pallas iff the
    # backend is TPU — the measured winner there, BENCH_r03; the XLA
    # gather path is the correct-everywhere fallback).
    pallas_obs_decode: str = "auto"
    # Pallas decode output layout: "planar" emits (B,T,K,H,W) + an outer
    # transpose (the measured round-3 design; the transpose is a ~1.6
    # ms/step HBM layout copy in the profile); "nhwc" interleaves K into
    # the lane dim in-kernel so the (B,T,H,W,K) contract is a free
    # reshape. Default planar pending the TPU A/B (bench.py measures an
    # nhwc-decode cell).
    pallas_decode_layout: str = "planar"
    # Double-DQN only: run the online and target unrolls interleaved in ONE
    # lax.scan instead of two sequential while-loops (which XLA cannot
    # overlap) — models/network.py dual_sequence_q. "on"/"off"/"auto"
    # (auto = TPU). Default off pending the TPU A/B (bench.py measures a
    # double/double_fused cell pair each round).
    fused_double_unroll: str = "off"


@dataclass(frozen=True)
class ActorConfig:
    """Ape-X actor fan-out (ref config.py:37-40, train.py:16-18)."""

    num_actors: int = 2
    base_eps: float = 0.4
    eps_alpha: float = 7.0
    actor_update_interval: int = 400   # steps between weight pulls (ref worker.py:568)
    max_episode_steps: int = 27_000
    near_greedy_eps: float = 0.02      # episode-return logging threshold (ref worker.py:555)
    # Env lanes per actor worker (envs/vector.py). 1 (default) = the legacy
    # single-env loop, byte-identical behavior. N>1 steps N envs through ONE
    # jitted (N, 1) policy forward per tick (actor/policy.py
    # BatchedActorPolicy) — the Podracer/GPU-emulation batching win (arxiv
    # 2104.06272, 1907.08467): actor cost goes from N interpreter+dispatch
    # round-trips per env step to one. The Ape-X ε ladder spreads over
    # num_actors * envs_per_actor total lanes (vector_lane_epsilons), so the
    # exploration schedule matches an equally-sized scalar-actor fleet.
    envs_per_actor: int = 1
    # -- Anakin-style fully on-device acting (runtime/anakin_loop.py) --
    # True routes training through the fused act+train loop: a jitted
    # lax.scan steps anakin_lanes batched PURE-JAX envs (envs/jax_env.py)
    # through the policy forward for block_length steps, assembles the
    # burn-in/learning blocks ON DEVICE, and ring-writes them straight
    # into device replay via replay_add_many — zero host transfers on the
    # acting hot path, weights read by reference from the colocated
    # learner's train state (Podracer "Anakin", arxiv 2104.06272). False
    # (default) = the legacy host actor fleet, byte-identical to pre-PR6.
    on_device: bool = False
    # Batched env lanes inside the fused acting scan — the GLOBAL count:
    # under a dp-wide mesh (mesh.dp > 1) the lanes partition into dp
    # equal per-shard groups (anakin_lanes % dp == 0), each acting into
    # its shard's local replay. Each segment emits one block per lane,
    # so the PER-SHARD group (lanes/dp) must be <= num_blocks (the
    # replay_add_many scatter-alias bound). The Ape-X ε ladder spreads
    # over the global lanes exactly like an equally-sized scalar-actor
    # fleet, regardless of dp.
    anakin_lanes: int = 64
    # Acting segments dispatched per train dispatch once training has
    # started (before learning_starts the loop acts continuously). >1
    # tilts the interleave toward collection — the fused loop is
    # synchronous, so this IS the collect:learn scheduling knob (the
    # replay rate limiter still applies on top).
    anakin_scans_per_train: int = 1
    # Initial priority of device-assembled sequences. A positive float
    # (default) stamps every sequence with that constant
    # (max-priority-style seeding) and lets the learner's first
    # write-back set the real priority. "td" computes the host path's
    # seeding IN-GRAPH instead: per-step n-step TD errors from the
    # acting policy's own Q-values (recorded along the scan + one extra
    # bootstrap forward per segment), mixed per sequence with
    # optim.priority_eta — fresh experience enters the tree already
    # ranked, at ~1/block_length extra acting compute.
    anakin_priority: Any = 1.0
    # Deterministic fault injection (tools/chaos.py): ';'-joined
    # ``slot:kind`` entries, e.g. "1:crash@block=3;2:hang@block=5;0:slowx4".
    # ``crash@block=N`` raises on the worker's N-th block emit (1-based),
    # ``hang@block=N`` wedges it there forever, ``slow@factor=F`` (or
    # ``slowxF``) stretches the interval between emits by F. Slots are
    # fleet-local worker indices (one fleet per host). "" (default) = no
    # faults. Exists so every health behavior — watchdog kill, backoff,
    # breaker, ring reclamation — is exercised by real misbehaving workers
    # in tests and in the soak's chaos phase, not just hoped for.
    # With inference="server" two CLIENT-side kinds join (ISSUE 13):
    # ``disconnect@req=N`` drops the worker's serve connection every N-th
    # request (exercising lease release + reconnect-with-state), and
    # ``slow``/``slowxF`` moves from the block sink to the request path
    # (stretching the worker's request cadence — a laggy client against
    # the micro-batcher). crash/hang stay at the block sink either way.
    fault_spec: str = ""
    # Where the acting forward runs (ISSUE 13): "local" (default) = the
    # policy + its recurrent state live in the actor worker (pre-PR13
    # behavior, byte-identical); "server" = the worker holds a thin
    # RemotePolicy and the central policy server (r2d2_tpu/serve/) owns
    # params + per-client state, micro-batching all workers' requests
    # into one device forward — the SEED placement (arXiv 1910.03552).
    # Action parity at equal seeds/ε is test-asserted.
    inference: str = "local"


@dataclass(frozen=True)
class ServeConfig:
    """Central policy inference service (ISSUE 13; r2d2_tpu/serve/):
    a SEED-style batched policy server — thin clients submit raw
    observation frames, one server loop owns the device-resident params
    and a sharded per-client LSTM-state + frame-stack cache, and
    micro-batches pending requests into one jitted forward under a
    latency deadline. ``actor.inference="server"`` routes the existing
    actor loops through it; ``cli/serve.py`` runs it standalone;
    ``cli/evaluate.py --serve`` is evaluation-as-a-service."""

    # Micro-batch dispatch bound: a batch dispatches when it holds this
    # many requests OR when the oldest pending request is deadline_ms
    # old, whichever first. Dispatch widths pad to power-of-two buckets
    # (all pre-compiled at server start) so fill jitter never retraces.
    max_batch: int = 32
    deadline_ms: float = 5.0
    # Serving fleet width (ISSUE 17): 1 (default) = the single PR-12
    # server loop, byte-identical. >= 2 = N server loops behind the
    # client-side router (serve/router.py), each owning a contiguous
    # slice of the state cache's shard groups; a request routes by
    # client_id % state_shards and never crosses servers. Thread-mode
    # actors ride in-proc endpoints; process-mode actors and cli/serve.py
    # ride one socket listener per server (the shm rung stays
    # single-server).
    servers: int = 1
    # Maximum fleet width (grow_server headroom): 0 (default) = servers
    # (no spare server slots). Spare slots pre-create their endpoints/
    # listeners so remote clients know every address up front; a grown
    # server attaches to its persistent endpoint (the PR-12 restart
    # pattern, now per slot).
    max_servers: int = 0
    # Admission control / brownout (ISSUE 17): a server whose inbox
    # backlog exceeds this many requests AFTER filling a dispatch sheds
    # the excess with STATUS_RETRY (+ retry_after hint) instead of
    # letting batch_wait run away — shed clients back off on the
    # WorkerHealth ladder and resend (the op was NOT applied). 0
    # (default) = no admission control, byte-identical records.
    queue_depth_bound: int = 0
    # State cache geometry: total per-client slots (each holds one packed
    # LSTM hidden + rolling frame stack + last action) partitioned into
    # ``state_shards`` independently-leased shard groups (client ids hash
    # onto shards; the layout a multi-device server pins per device).
    state_slots: int = 1024
    state_shards: int = 4
    # A DISCONNECTED client's state survives this long before eviction —
    # the reconnect window (a bouncing client resumes mid-episode); an
    # evicted slot resets to the episode-initial zero state.
    lease_timeout_s: float = 120.0
    # Client-side request timeout: past it the client backs off on the
    # PR-3 WorkerHealth ladder, reconnects, and resends; after
    # ``max_retry_s`` of failures it raises (worker supervision takes
    # over: respawn with backoff).
    request_timeout_s: float = 5.0
    max_retry_s: float = 60.0
    # Server-side request TTL: requests older than this at dispatch are
    # dropped unapplied (a restarted server must not replay its dead
    # predecessor's backlog — the client already timed out and will
    # resend its current state). 0 disables.
    request_ttl_s: float = 10.0
    # Transport rung for PROCESS-mode actors: "shm" (the shm_feeder ring
    # discipline — native MPMC request ring + per-client reply rings),
    # "socket" (TCP, the cross-host rung), or "auto" (shm when the
    # native toolchain is available, else socket). Thread-mode actors
    # always ride the in-proc queue; cli/serve.py listens on socket
    # (and shm with --shm).
    transport: str = "auto"
    host: str = "127.0.0.1"
    port: int = 0                   # 0 = ephemeral (socket transport)
    # Ring geometries (shm transport).
    request_ring_slots: int = 256
    reply_ring_slots: int = 16
    # Seconds between the server's weight-service polls (the reader side
    # of runtime/weights.py; every reply stamps the adopted publish
    # count so block staleness accounting stays live in served mode).
    weight_poll_interval_s: float = 1.0
    # Pre-compile every pow2 dispatch bucket at server start (the ingest
    # stager's AOT recipe — a lazy mid-run compile parks every client).
    warmup: bool = True
    # Shadow mirroring (ISSUE 20): fraction of live OK step replies the
    # client-side router copies to a candidate server for divergence
    # scoring (fleet/promotion.py ShadowScorer — mirrored replies are
    # never returned to clients). 0 (default) = no mirror sink is ever
    # attached; the routing path is byte-identical to PR-17.
    shadow_sample_rate: float = 0.0


@dataclass(frozen=True)
class FleetConfig:
    """Elastic fleet control plane (ISSUE 15; r2d2_tpu/fleet/): the
    disaggregated replay service with its host-RAM spill tier, the
    weight fan-out relay tree, and live actor join/leave. Every field's
    default leaves the pre-PR15 plumbing byte-identical (no service, no
    relays, frozen fleet)."""

    # Replay service (fleet/replay_service.py): 0 (default) = the legacy
    # in-mesh replay (single ring or dp-sharded, byte-identical). >= 1 =
    # the learner routes ingestion through a ReplayService of this many
    # addressable shards (device capacity num_blocks/replay_shards rows
    # each) and trains through the external-batch step on
    # service-sampled batches — the disaggregated plane any producer
    # (local feeder, remote socket rung) can route blocks into.
    replay_shards: int = 0
    # Host-RAM spill tier, PER SHARD, in blocks: a device ring-write
    # that overwrites a live block demotes its host page into an LRU
    # page store of this capacity instead of destroying it; pages
    # rotate back into the samplable ring at sample time. Total
    # effective capacity = device rings + spill (the >= 2x-HBM-budget
    # acceptance). 0 = no spill (overwrite semantics unchanged).
    spill_blocks: int = 0
    # Spilled pages rotated back into the device ring per sample call
    # (the promote-on-sample-hit cycle). 0 disables re-promotion (the
    # spill tier becomes a pure archive until it evicts).
    spill_promote_per_sample: int = 1
    # Block -> shard routing: "round_robin" (the dp-sharded path's
    # feeding order — what the service-vs-in-mesh parity test pins) or
    # "lane" (shard = lane-provenance stamp % shards: a producer's
    # blocks land by lane identity, so shard contents are
    # provenance-checkable and a joiner adopting a slot's lanes adopts
    # its routing — the churn drill's setting).
    replay_route: str = "round_robin"
    # Expose the service to REMOTE producers over the socket rung
    # (fleet/replay_service.py ReplayServiceServer): "" (default) = off;
    # "socket" = listen on service_host:service_port.
    service_transport: str = ""
    service_host: str = "127.0.0.1"
    service_port: int = 0           # 0 = ephemeral
    # Weight fan-out tree (fleet/fanout.py): 0 (default) = every actor
    # polls the one publisher/store directly (pre-PR15). >= 2 = relay
    # tree of this degree — the learner publishes once, relay nodes
    # re-publish, actors read leaf relays (thread mode: in-proc relays;
    # process mode + multihost hosts: shm relay segments). The stamped
    # quant bundle rides through relays unchanged.
    fanout_degree: int = 0
    # In-proc relays pull upstream on this interval instead of being
    # pushed per publish; 0 (default) = push-through on every publish
    # (zero steady-state lag). Nonzero makes relay lag real — the
    # fanout_lag alert's test hook and the cadence knob for
    # pull-through deployments.
    fanout_pull_interval_s: float = 0.0
    # Maximum fleet width for elastic membership: 0 (default) =
    # actor.num_actors (no spare slots). > num_actors reserves
    # (max_slots - num_actors) FREE spare slots joiners can lease
    # mid-training; the ε ladder and lane ranges span max_slots so the
    # exploration schedule is fixed as the fleet churns.
    max_slots: int = 0
    # Elastic supervision policy: False (default) = a dead actor is
    # respawned in place on the PR-3 backoff ladder (pre-PR15). True = a
    # dead/left actor's slot PARKS for re-adoption (membership.park) and
    # training continues on the remaining fleet — the join/leave drill's
    # setting; re-admission goes through PlayerStack.join_actor.
    elastic: bool = False
    # -- batched/pipelined service data plane (ISSUE 16) --
    # Blocks the service commits per jitted dispatch: 1 (default) = the
    # PR-15 per-block replay_add path, byte-identical. K > 1 = the
    # learner's service drain stacks up to K queued blocks and
    # ReplayService.add_blocks groups them by routed shard, committing
    # each group through the donated replay_add_many program
    # (pow2-bucketed, AOT-precompiled at service start) — bit-identical
    # contents to K sequential adds, one dispatch instead of K.
    ingest_batch_blocks: int = 1
    # In-flight frame window for the socket rung's producer: 1 (default)
    # = PR-15's one-frame-one-ack lockstep (a full RTT per frame). W > 1
    # = RemoteReplayProducer keeps up to W unacked frames in flight
    # (cumulative acks, back-pressure at the window bound) so remote
    # producers stop paying a blocking round-trip per block.
    socket_window: int = 1
    # Priority-aware async spill promotion: False (default) = PR-15's
    # inline LRU rotation inside the sample call. True = spilled pages
    # promote by STORED priority (max-heap over each page's leaf
    # priorities) and promotion is kicked asynchronously at write-back
    # time, so the sample path stops paying promotion latency inline.
    spill_prefetch: bool = False
    # Service-mode sample staging: False (default) = the fully
    # synchronous PR-15 service step (sample -> train -> write-back on
    # one thread). True = the PR-2 stager treatment for the service
    # path: a staging thread drains the next per-shard sample batch
    # while the train dispatch runs, and priority write-backs batch per
    # sampled shard on a writeback thread (the PR-14 staleness guard
    # applies per entry, now reaching spilled pages too).
    sample_staging: bool = False
    # Fleet lease API (ISSUE 17, ROADMAP 2c): "" (default) = joins are
    # in-process only (PlayerStack.join_actor). "socket" = the
    # orchestrator listens on lease_host:lease_port
    # (fleet/membership.py MembershipServer) and a FRESH process joins
    # the running fleet through cli/join.py — it leases a slot over the
    # wire, adopts the slot's identity, routes blocks in via the replay
    # service's socket rung, and reaches served inference through the
    # serve fleet's socket listeners.
    lease_transport: str = ""
    lease_host: str = "127.0.0.1"
    lease_port: int = 0             # 0 = ephemeral
    # -- gated canary promotion (ISSUE 20; fleet/promotion.py) --
    # Eval-return gate: a candidate promotes only if its per-scenario
    # mean return >= the live policy's minus this tolerance (absolute,
    # in return units — returns are env-scale, not normalized).
    promotion_return_tolerance: float = 0.05
    # Calibration gate: |mean (predicted max-Q − realized n-step
    # return)| of the candidate's stream must stay under this bound
    # (fail-open when no calibration stream exists — process fleets).
    promotion_calibration_bound: float = 10.0
    # Shadow gate: greedy-disagreement fraction on mirrored traffic
    # must stay under this bound, measured over at least
    # promotion_min_shadow scored requests (fail-closed below the
    # minimum — a promotion must earn its evidence).
    promotion_divergence_bound: float = 0.25
    promotion_min_shadow: int = 32
    # Fraction of fan-out consumers a staged candidate canary-publishes
    # to (leaf-relay granularity; 0 disables the canary slice — the
    # candidate proves itself on shadow + eval alone).
    promotion_canary_frac: float = 0.25

    def resolved_max_slots(self, num_actors: int) -> int:
        return self.max_slots if self.max_slots > 0 else num_actors

    @property
    def active(self) -> bool:
        """Any fleet plane configured on — gates the record's
        replay_service block so legacy runs keep a byte-identical
        schema."""
        return (self.replay_shards > 0 or self.fanout_degree > 0
                or self.max_slots > 0 or self.elastic)


@dataclass(frozen=True)
class MultiplayerConfig:
    """Population self-play (ref config.py:43-45, train.py:28-45)."""

    enabled: bool = False
    num_players: int = 2
    base_port: int = 5060
    # -1 (default): this process trains the WHOLE population in one job
    # (the reference's train.py model; single-host orchestrator only).
    # >= 0: this job trains exactly ONE player of the population — the
    # per-player-job composition that scales multiplayer to pods (one
    # multihost job per player; players interact only through the game
    # engine's host/join sockets, never through collectives — README
    # "Multiplayer at pod scale"). Player 0's actors host the games on
    # port(actor_idx); every other player's actor i joins game i.
    player_id: int = -1

    def port(self, actor_idx: int) -> int:
        return self.base_port + actor_idx

    def env_args(self, player_idx: int, actor_idx: int) -> dict:
        """Host/join wiring for one actor's env (ref train.py:33-38) —
        shared by the single-host orchestrator and the per-player-job
        multihost trainer so the two paths cannot drift."""
        if not self.enabled:
            return dict(is_host=False, port=self.base_port)
        return dict(is_host=player_idx == 0, port=self.port(actor_idx))


@dataclass(frozen=True)
class MeshConfig:
    """TPU device-mesh layout for the learner.

    The reference has no learner parallelism (one process on half a GPU,
    ref worker.py:251); here data-parallel over the 'dp' axis (batch sharded,
    gradient psum over ICI) and model-parallel over 'mp' (hidden/cnn feature
    sharding) are first-class. A 1x1 mesh degrades to single-chip.
    """

    # 1 = single-chip (default); N>1 = dp-shard the learner over N chips;
    # -1 = all available devices. The runtime Learner builds the shard_map
    # step + sharded replay whenever the resolved mesh is wider than one
    # device (runtime/learner_loop.py).
    dp: int = 1
    mp: int = 1

    def resolved_dp(self, n_devices: int) -> int:
        mp = max(self.mp, 1)
        return self.dp if self.dp > 0 else max(n_devices // mp, 1)
    # Multi-host: initialize jax.distributed (DCN) before mesh construction.
    multihost: bool = False
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0


@dataclass(frozen=True)
class TelemetryConfig:
    """Unified runtime telemetry (r2d2_tpu/telemetry/): percentile stage
    timers, span tracing, cross-process aggregation. On by default — the
    benched overhead budget is < 2% env-steps/s (tools/e2e_bench.py
    --telemetry-ab; PERF.md "Telemetry overhead")."""

    # Master kill-switch: false turns every telemetry entry point into a
    # cheap no-op (stage observes, span records, board publication, the
    # aggregated 'stages' block in the periodic record).
    enabled: bool = True
    # Span ring capacity PER THREAD (spans.py). When a drain interval
    # overflows it the oldest spans drop (counted, surfaced as
    # telemetry_dropped_spans in the periodic record) — sized for block
    # cadence, not per-env-step events.
    ring_size: int = 4096
    # Drain cadence: spans ring -> spans_*.jsonl, and worker histogram
    # counts -> the shared-memory board.
    flush_interval_s: float = 5.0
    # Span tracing sub-switch: histograms stay on (they are the
    # aggregated record's source); spans cost a JSONL file per process.
    spans: bool = True
    # -- learning-dynamics diagnostics (telemetry/learning.py, ISSUE 5) --
    # Kill switch for the learner-side LEARNING diagnostics fused into the
    # jitted train step: |TD|/priority/Q histograms, per-group gradient
    # norms, target-network parameter distance, the stored-state ΔQ
    # check, sample-age staleness, and NaN forensics. Off (or with the
    # master `enabled` off) the train step compiles WITHOUT any
    # diagnostic outputs — the hot path is byte-identical to pre-PR5.
    learning_enabled: bool = True
    # Learner steps between ΔQ / target-distance evaluations (lax.cond
    # inside the jitted step: the extra unrolls only execute on interval
    # steps, so the steady-state cost is amortized to ~nothing).
    learning_interval: int = 200
    # Sequences per ΔQ evaluation (the full-context reference unroll runs
    # over the whole stored block row — ~8x the window length — so this
    # sub-batch bounds its transient activation memory; 16 ≈ one training
    # batch's activation footprint at the reference shape).
    learning_dq_batch: int = 16
    # What to do when the train step's loss/grad-norm first goes
    # non-finite (detected at the metrics flush): both policies write a
    # one-shot nan_dump_player{p}.json forensic record; "warn" logs and
    # continues (the reference's silent-NaN failure mode, made loud),
    # "halt" raises after the dump so the run stops at the poisoned step.
    nan_policy: str = "warn"
    # -- resource & compilation observability (ISSUE 7) --
    # Pillar kill switch: per-device memory_stats sampling, buffer
    # attribution, host/actor RSS+CPU, the compile/retrace telemetry, and
    # the record's 'resources' + 'alerts' blocks. False (or the master
    # `enabled` off) yields periodic records byte-identical to the
    # pre-PR7 schema (stability-tested).
    resources_enabled: bool = True
    # Seconds between resource samples (a handful of dict reads and one
    # /proc line — benched within noise at this cadence, PERF.md).
    resources_interval_s: float = 10.0
    # One-shot OOM forensics floor: the first sample seeing any device's
    # HBM headroom below this fraction writes resource_dump_player{p}.json
    # (the nan_dump pattern — the attribution picture an OOM kill would
    # destroy). 0 disables the dump.
    resources_headroom_warn_frac: float = 0.05
    # XLA compilation telemetry sub-switch (telemetry/compile.py):
    # per-function compile counts + wall time, post-warm-up retrace
    # detection with the offending avals, and the stager's AOT coverage
    # report, nested under the record's resources block.
    compile_enabled: bool = True
    # Alert engine sub-switch (telemetry/alerts.py): the declarative rule
    # set evaluated per periodic record, emitting the record's 'alerts'
    # block + alerts_player{p}.jsonl. Requires resources_enabled (the
    # machine-side rules read the resources block; tools/sentinel.py
    # re-evaluates offline regardless).
    alerts_enabled: bool = True
    # Rolling-median window (records) for the drop/growth rules; a rule
    # arms only once its metric has been healthy for a full window.
    alerts_window: int = 8
    # env/learner throughput below this fraction of its rolling median
    # fires *_throughput_drop.
    alerts_throughput_drop_frac: float = 0.5
    # Max heartbeat age (seconds) before heartbeat_stale fires.
    alerts_heartbeat_age_s: float = 120.0
    # sample_age p50 above this multiple of its rolling median fires
    # staleness_growth.
    alerts_staleness_growth_factor: float = 4.0
    # Minimum per-device HBM headroom fraction before hbm_headroom fires.
    alerts_hbm_headroom_frac: float = 0.05
    # Post-warm-up retraces within one log interval at/above this count
    # fire retrace_storm.
    alerts_retrace_storm: int = 3
    # -- cost model & roofline (ISSUE 9) --
    # Kill switch for the periodic record's one-shot 'costs' block: the
    # analytic per-component (torso/lstm/head/sum-tree/replay) FLOPs +
    # bytes summary of the configured train step, attached by the
    # Learner at its first metrics flush (pure config math — no compile,
    # no device work). Off (or with the master `enabled` off) the record
    # schema is byte-identical to pre-PR9. The offline XLA cost tools
    # (`make costs` / `make roofline` / the `make regress` costs gate —
    # telemetry/costmodel.py, tools/roofline.py) are unaffected: they
    # run out-of-process against the config, not the live run.
    costmodel_enabled: bool = True
    # Sharded-anakin balance: max/min per-shard ingested env-steps over
    # the log interval (the record's anakin.shard_imbalance) at/above
    # this ratio fires shard_imbalance. Today's lockstep fused program
    # keeps the ratio at exactly 1.0 (full blocks on every shard every
    # segment) — the rule is the standing guard for compositions that
    # can skew it (ragged per-shard emission, elastic meshes), where a
    # lagging shard drags the whole lockstep program to its pace.
    alerts_shard_imbalance: float = 1.5
    # -- replay & data-pathology observability (ISSUE 10) --
    # Pillar kill switch for the replay diagnostics fused into the jitted
    # sample/update path (telemetry/replaydiag.py): sum-tree / priority
    # health (leaf histogram, effective sample size, collapse
    # indicators), per-slot sample-lifetime accounting (the
    # never-sampled-before-eviction fraction), and the per-ε-lane
    # composition of sampled batches. Off (or with the master `enabled`
    # off) the step factories compile WITHOUT the diagnostic state and
    # outputs, and the periodic record carries no 'replay_diag' block —
    # byte-identical to the PR9 schema (stability-tested).
    replay_diag_enabled: bool = True
    # Learner steps between sum-tree health snapshots (lax.cond inside
    # the fused step: the leaf-histogram scatter and eviction-counter
    # reads execute only on interval steps; the every-step residue is
    # one (B,)-scatter sample-count increment and a (lanes,)-bincount).
    replay_diag_interval: int = 50
    # Effective-sample-size fraction (ESS / active leaves) of the
    # sampling distribution below which priority_collapse fires: the
    # tree's mass has concentrated on this few of its live sequences.
    alerts_replay_ess_frac: float = 0.05
    # Fraction of live leaves sitting at the tree's max priority at/above
    # which priority_saturation fires (a mass of ties at max means
    # prioritization has stopped discriminating).
    alerts_priority_saturation: float = 0.5
    # never_sampled_frac above this multiple of its own rolling median
    # fires never_sampled_growth (replay sized/prioritized wrong: an
    # increasing share of experience is evicted unseen).
    alerts_never_sampled_growth: float = 2.0
    # Fraction of the global ε-ladder lanes contributing ZERO sequences
    # to the interval's sampled batches at/above which lane_starvation
    # fires.
    alerts_lane_starved_frac: float = 0.5
    # -- fleet observability (ISSUE 12; telemetry/fleet.py) --
    # Pillar kill switch for the multihost fleet plane: the lockstep
    # psum row widened with per-rank step-time gauges (sum/max/min +
    # one-hot straggler argmax + the all-gathered per-row tables),
    # per-iteration compute-vs-blocked lockstep timing, the rank-0
    # FleetAggregator's 'fleet' block on the periodic record, per-rank
    # AlertEngines on ranks > 0 (firings -> alerts_host{r}.jsonl), and
    # the clock-anchored host rows the cross-host trace merge aligns
    # on. False (or the master `enabled` off) compiles the exact PR-10
    # lockstep programs and leaves records and host rows byte-identical
    # to the PR-10 schema (stability-tested). Single-controller
    # (non-multihost) runs are unaffected either way.
    fleet_enabled: bool = True
    # Size cap (bytes) on each telemetry_host{r}.jsonl before it rotates
    # to telemetry_host{r}.jsonl.1 (one generation kept — a pod run
    # holds at most ~2x this per rank). 0 = unbounded (pre-PR12).
    fleet_host_row_max_bytes: int = 16 * 2**20
    # Max/min per-rank mean step time (the fleet block's
    # step_time.skew — the shard_imbalance convention) at/above which
    # rank_straggler fires; 1.0 = perfectly balanced.
    alerts_rank_straggler: float = 2.0
    # Fraction of loop time this rank spent blocked in the lockstep
    # collective (fleet.lockstep.wait_frac) at/above which
    # lockstep_wait_frac fires — the DCN barrier is eating step time.
    alerts_lockstep_wait_frac: float = 0.75
    # Max/min per-rank ingested env-steps over the interval
    # (fleet.env_steps.divergence; a zero-rank reads against a floor of
    # 1) at/above which fleet_desync fires.
    alerts_fleet_desync: float = 4.0
    # Stalest other-rank host-row age (seconds, fleet.host_rows.max_age_s
    # on rank 0) at/above which missing_rank fires — a rank stopped
    # writing its row (wedged or dead past the heartbeat horizon).
    alerts_missing_rank_age_s: float = 120.0
    # -- serving plane (ISSUE 13; the record's 'serving' block) --
    # Client-visible request-latency P99 (serving.latency.p99_ms —
    # includes queueing, retries, and timed-out attempts) at/above which
    # serve_latency_slo fires: the SLO ceiling. Inactive on records
    # without a serving block (every non-served run).
    alerts_serve_p99_ms: float = 1000.0
    # Fraction of the interval's dispatched batches that went out with
    # fill == 1 while >1 clients were connected (serving.batch.
    # starved_frac) at/above which serve_batch_starvation fires — the
    # micro-batcher is not coalescing despite load (deadline too tight,
    # or clients serialized behind something).
    alerts_serve_starved_frac: float = 0.95
    # Cumulative client disconnects (serving.clients.disconnects)
    # growing by at least this much within one interval fires
    # serve_client_churn (counter semantics — one burst, one alert).
    alerts_serve_churn: float = 3.0
    # Interval shed fraction (serving.admission.shed_frac — requests
    # rejected at the queue-depth bound over shed+replied) at/above
    # which serve_brownout fires: the fleet is actively shedding load to
    # hold the latency SLO — capacity is the problem, not the server.
    # Inactive when admission control is off (no admission sub-block).
    alerts_serve_shed_frac: float = 0.2
    # -- quantized inference plane (ISSUE 14; the record's 'quant' block) --
    # Forward calls between accuracy probes when network.inference_dtype
    # != "f32": every probe_interval-th acting forward also runs the f32
    # twin on the SAME live batch (a lax.cond inside the jitted forward —
    # steady-state cost amortizes to ~nothing) and feeds max |Q_f32 −
    # Q_quant| + the greedy-action agreement fraction into the periodic
    # record's 'quant' block. 0 disables probing (the block still carries
    # the active dtype). The anakin path probes once per acting segment
    # (already ~1/block_length of the scan's cost).
    quant_probe_interval: int = 256
    # Interval greedy-action agreement fraction (quant.agree_frac, the
    # lane-weighted mean over the interval's probes) at/below which
    # quant_divergence fires — the quantized policy is no longer acting
    # like its f32 twin. Inactive on records without a quant block
    # (every inference_dtype="f32" run).
    alerts_quant_agreement: float = 0.95
    # -- elastic fleet / replay service (ISSUE 15; the record's
    # 'replay_service' block, r2d2_tpu/fleet/) --
    # Interval spill-tier eviction/demotion ratio
    # (replay_service.spill.thrash_frac) at/above which spill_thrash
    # fires: demoted pages are falling off the LRU end before ever
    # being re-promoted — the device ring is turning over faster than
    # the spill tier can cycle experience back, so the tier is a pure
    # write-through loss (grow spill_blocks or slow collection).
    alerts_spill_thrash_frac: float = 0.5
    # Max fan-out relay lag in publications
    # (replay_service.fanout.max_lag: root publish count minus the
    # slowest relay's adopted count) at/above which fanout_lag fires —
    # a tier of the weight tree has stopped propagating and its
    # subtree's actors act on stale params.
    alerts_fanout_lag: float = 8.0
    # Leased-but-silent slot count (replay_service.membership.orphaned:
    # ACTIVE slots whose heartbeat is stale past the orphan horizon) at/
    # above which orphaned_slot fires — a worker vanished without its
    # lease being parked or re-adopted.
    alerts_orphaned_slots: float = 1.0
    # Service ingest backlog (replay_service.ingest.backlog: blocks
    # queued behind the service's grouped commit at the last drain) at/
    # above which ingest_backlog fires — producers are bursting faster
    # than the service's dispatch plane drains, so blocks age in the
    # queue before ever becoming samplable (raise
    # fleet.ingest_batch_blocks or slow collection).
    alerts_ingest_backlog: float = 64.0
    # -- crash-recovery plane (ISSUE 18; the record's 'recovery' block) --
    # Age (seconds) of the newest durable replay snapshot
    # (recovery.snapshot.age_s) at/above which snapshot_stale fires —
    # the writer has stopped committing cuts, so a crash now loses more
    # than one runtime.snapshot_interval of experience. Inactive on
    # records without a recovery block (snapshot_interval = 0).
    alerts_snapshot_stale_s: float = 600.0
    # Supervisor relaunches of the learner (recovery.supervisor.restarts,
    # cumulative within the supervised run) at/above which recovery_loop
    # fires — the learner is crash-looping through auto-resume instead
    # of making progress (the breaker parks it one rung later).
    alerts_recovery_loop: float = 2.0
    # -- cross-plane distributed tracing (ISSUE 19; telemetry/tracing.py) --
    # Kill switch for causal trace propagation on BOTH data paths:
    # serving requests carry a trace dict (per-hop wall stamps client ->
    # router -> server micro-batch -> reply; two gated fields on the shm
    # request layout) and every Nth experience block carries the
    # Block.trace_ms lineage stamp from emission through ingest / spill /
    # sample to train consumption — the record's 'trace' block with the
    # end-to-end env-step->gradient latency histogram. Default OFF: the
    # stamp is a trailing pytree leaf and two wire fields, and the
    # kill-switch contract (records, wire frames, and block schemas
    # byte-identical when off) means an opt-in plane, like
    # snapshot_interval and spill_prefetch before it.
    tracing_enabled: bool = False
    # Every Nth emitted block gets a lineage stamp / every Nth serve
    # exchange gets a trace dict (1 = trace everything; the benched <= 2%
    # overhead budget holds at the default).
    trace_sample_every: int = 16
    # Control-tower collector sub-switch (telemetry/tower.py +
    # tools/tower.py): gates the process-identity header + clock anchor
    # on the serve-fleet / ReplayService periodic rows the tower join
    # and the cross-process Perfetto merge align on. Pull-based (the
    # tower tails files) — on by default; rows gain only the '_proc'
    # header key.
    tower_enabled: bool = True
    # -- per-tier replay telemetry (ISSUE 19 satellite; ROADMAP 4d) --
    # Adds promotion-latency + bytes-per-tier sub-blocks to the record's
    # replay_service.spill block. Off => the block is byte-identical to
    # the PR-18 schema.
    replay_tiers_enabled: bool = False
    # Spill promotion latency p95 (replay_service.spill.
    # promotion_latency.p95_ms — time-in-tier of pages promoted this
    # interval) at/above which spill_promotion_latency fires: demoted
    # experience is sitting so long in the host tier that it returns
    # stale (grow promote_per_sample / spill_prefetch, or shrink the
    # tier).
    alerts_spill_promotion_ms: float = 60_000.0
    # Tower alert rule: e2e_experience_latency p50 (the record trace
    # block's env-step->gradient latency) above this multiple of its own
    # rolling median fires e2e_latency_growth — experience is aging
    # somewhere between emission and the gradient.
    alerts_e2e_latency_growth: float = 4.0
    # -- policy-quality pillar (ISSUE 20; telemetry/quality.py) --
    # Master switch: continuous eval + Q-calibration + the record's
    # 'quality' block + the quality_player{p}.jsonl ledger stream. Off
    # (default) => nothing is constructed and records are byte-identical
    # to the PR-19 schema (the kill-switch contract).
    quality_enabled: bool = False
    # Background evaluator cadence / work: seconds between checkpoint
    # polls, eval episodes per scenario, served eval clients (the eval
    # rollouts ride cli/evaluate's --serve machinery when serving is on).
    quality_eval_interval_s: float = 60.0
    quality_eval_rounds: int = 2
    quality_eval_clients: int = 2
    # Every Nth finished actor block feeds the Q-calibration join
    # (1 = every block; the tap is one convolution per 400-step block).
    quality_calib_sample_every: int = 1
    # quality_regression: eval mean_return dropping below this fraction
    # of its own rolling median fires (drop rule — return scales are
    # env-relative, so the rule is too).
    alerts_quality_regression: float = 0.5
    # canary_divergence: shadow greedy-disagreement fraction at/above
    # this fires (crit — the candidate disagrees with live on mirrored
    # traffic beyond the promotion gate's own bound).
    alerts_canary_divergence: float = 0.25
    # promotion_stall: a canary staged longer than this many seconds
    # without a promote/refuse/rollback verdict fires.
    alerts_promotion_stall_s: float = 600.0


@dataclass(frozen=True)
class RuntimeConfig:
    """Process orchestration, logging, checkpointing (ref config.py:8-10,20-21,40)."""

    save_dir: str = "models"
    pretrain: str = ""               # warm-start checkpoint path ("" = none)
    # Full-resume checkpoint path: restores params, target_params, opt_state,
    # step, and env_steps into the learner (the reference can only warm-start
    # weights, worker.py:260-261; SURVEY §5.4 sets the full-state bar).
    resume: str = ""
    save_interval: int = 1_000       # learner steps between checkpoints
    log_interval: float = 20.0       # seconds between metric log lines
    weight_publish_interval: int = 2  # learner steps between weight publications
    # Fused train steps per device dispatch (lax.scan). >1 amortizes host
    # dispatch latency; weight publish / checkpoint cadence coarsens to
    # dispatch boundaries. 1 = reference-faithful per-step cadence.
    # -1 = auto: 16 on TPU (the measured winner of the BENCH_r03 matrix,
    # +28% over per-step dispatch on v5e; identical math — same RNG chain
    # and target-sync schedule), 1 elsewhere (the XLA:CPU lowering of the
    # scanned step runs ~12x slower per step than the unrolled jit —
    # measured round 3, PERF.md). Publishes still land every
    # ceil(interval/k)*k steps, far fresher than the reference actors'
    # 400-step pull cadence (worker.py:568).
    steps_per_dispatch: int = -1

    def resolved_steps_per_dispatch(self) -> int:
        if self.steps_per_dispatch > 0:
            return self.steps_per_dispatch
        import jax
        return 16 if jax.default_backend() == "tpu" else 1
    prefetch_batches: int = 4        # learner-side batch prefetch depth (ref worker.py:302)
    # Process-mode experience transport: native shared-memory MPMC ring
    # (one memcpy per side — the plasma-store equivalent, shm_feeder.py);
    # falls back to mp.Queue (pickle through a pipe) if the C++ toolchain
    # is unavailable or the flag is off.
    shm_transport: bool = True
    test_epsilon: float = 0.01
    seed: int = 0
    profile_dir: str = ""            # non-empty: write jax.profiler traces here
    # Mid-run xprof trigger: > 0 arms a ONE-SHOT jax.profiler capture that
    # starts when the learner step counter first reaches this value and
    # runs for min(log_interval, 30)s — profiling the steady state instead
    # of (or in addition to) the first-interval capture profile_dir
    # enables. Traces land in profile_dir, or {save_dir}/xprof when
    # profile_dir is unset. SIGUSR2 triggers the same capture on demand.
    profile_at_step: int = 0
    restart_dead_actors: bool = True  # supervisor (the reference has none, SURVEY §5.3)
    # -- worker health (heartbeats / watchdog / backoff / breaker) --
    # Seconds between supervision passes (dead-worker scan, hang watchdog,
    # ring reclamation, stall detector) — decoupled from log_interval so
    # hang detection latency does not ride the logging cadence.
    supervise_interval_s: float = 5.0
    # Hang watchdog: a worker that is alive but whose heartbeat (published
    # per block emit, and while parked under feeder back-pressure) is older
    # than this is killed (process) or flagged+abandoned (thread) and
    # routed through the normal respawn path. 0 disables hang detection.
    hang_timeout_s: float = 120.0
    # Grace before a worker's FIRST heartbeat (process spawn + jax import +
    # env construction + first block can far exceed hang_timeout_s); a
    # worker wedged during bring-up — the classic stuck ViZDoom multiplayer
    # join — is still detected, just on this slower clock.
    hang_spawn_grace_s: float = 300.0
    # Per-slot exponential restart backoff: the first respawn is
    # immediate; each further failure inside restart_window_s doubles the
    # wait, starting at base for the second (k-th failure waits
    # base * 2^(k-2), capped at max). Stops a crash-looping actor from
    # burning a CPU respawning every supervision tick.
    restart_backoff_base_s: float = 1.0
    restart_backoff_max_s: float = 60.0
    # Crash-loop circuit breaker: after this many failures inside
    # restart_window_s the slot is PARKED (no further respawns; training
    # continues degraded; surfaced in metrics as actor_parked_slots /
    # actor_breaker_trips). 0 disables the breaker.
    max_restarts_per_window: int = 5
    restart_window_s: float = 300.0
    # Learner-side stall detector: when ingestion sits at zero new blocks
    # for this long while workers are nominally alive and the rate limiter
    # is not deliberately pausing, emit a one-shot diagnostic dump
    # (per-slot heartbeat ages, queue/ring occupancy, limiter state)
    # instead of starving silently. 0 disables.
    ingest_stall_timeout_s: float = 300.0
    # -- crash-recovery plane (ISSUE 18) --
    # Learner steps between durable replay snapshots: at each interval
    # boundary the learner captures a consistent cut of the replay plane
    # (every shard's ReplayState + ring accounting + spill pages + rr
    # cursors) at the commit boundary between train dispatches, and a
    # background writer serializes it to {save_dir}/replay_player{p}.npz
    # with an atomic tmp+rename manifest (replay/snapshot.py). 0 = off
    # (no snapshot files, no 'recovery' record block — records stay
    # byte-identical to the pre-PR18 schema).
    snapshot_interval: int = 0
    # Restore replay contents on resume: when runtime.resume is set and a
    # replay snapshot manifest exists next to the checkpoint, the learner
    # reloads every shard's ring/tree/stamps/spill bit-exactly before
    # training continues. Off restores params/opt-state only (the
    # pre-PR18 resume).
    restore_replay: bool = True
    # Supervisor rung (runtime/supervisor.py, wired in cli/train.py): run
    # training in a supervised child process; on learner death (or
    # SIGKILL preemption of the child) the supervisor relaunches it with
    # runtime.resume pointed at the newest checkpoint + replay snapshot.
    # The relaunch ladder reuses the PR-3 worker-health knobs above
    # (restart_backoff_*, max_restarts_per_window, restart_window_s) as
    # the crash-loop breaker.
    auto_resume: bool = False
    # Checkpoint retention: keep only the newest K checkpoint dirs per
    # player (plus their .config.json sidecars and any per-checkpoint
    # snapshot sets) after each save — disk growth was unbounded before.
    # 0 = keep everything.
    keep_checkpoints: int = 0


@dataclass(frozen=True)
class Config:
    """Root config. Construction validates cross-section size invariants the
    replay layout depends on (block/sequence divisibility), so a bad genetic-
    search sample fails here rather than corrupting buffer indexing later."""

    env: EnvConfig = field(default_factory=EnvConfig)
    network: NetworkConfig = field(default_factory=NetworkConfig)
    sequence: SequenceConfig = field(default_factory=SequenceConfig)
    replay: ReplayConfig = field(default_factory=ReplayConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    actor: ActorConfig = field(default_factory=ActorConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    multiplayer: MultiplayerConfig = field(default_factory=MultiplayerConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)

    def __post_init__(self):
        if self.replay.block_length % self.sequence.learning_steps != 0:
            raise ValueError(
                f"replay.block_length ({self.replay.block_length}) must be a "
                f"multiple of sequence.learning_steps ({self.sequence.learning_steps})"
            )
        if self.replay.capacity % self.replay.block_length != 0:
            raise ValueError(
                f"replay.capacity ({self.replay.capacity}) must be a multiple "
                f"of replay.block_length ({self.replay.block_length})"
            )
        if self.sequence.forward_steps < 1:
            raise ValueError("sequence.forward_steps must be >= 1")
        if self.replay.ingest_batch_blocks == 0 or \
                self.replay.ingest_batch_blocks < -1:
            raise ValueError(
                f"replay.ingest_batch_blocks ({self.replay.ingest_batch_blocks})"
                " must be -1 (auto) or >= 1")
        if self.replay.ingest_batch_blocks > self.num_blocks:
            raise ValueError(
                f"replay.ingest_batch_blocks ({self.replay.ingest_batch_blocks})"
                f" must be <= num_blocks ({self.num_blocks}): replay_add_many"
                " scatter rows would alias in the ring")
        if self.replay.drain_max_blocks < 1:
            raise ValueError(
                f"replay.drain_max_blocks ({self.replay.drain_max_blocks}) "
                "must be >= 1")
        if self.actor.envs_per_actor < 1:
            raise ValueError(
                f"actor.envs_per_actor ({self.actor.envs_per_actor}) must be "
                ">= 1")
        if self.actor.envs_per_actor > 100:
            raise ValueError(
                f"actor.envs_per_actor ({self.actor.envs_per_actor}) must be "
                "<= 100: per-lane seeds fill the worker's 100-wide seed "
                "window (runtime.seed + 100*actor_idx + lane); more lanes "
                "would duplicate the next worker's env/RNG streams — scale "
                "actor.num_actors instead")
        if self.env.episode_len < 1:
            raise ValueError(
                f"env.episode_len ({self.env.episode_len}) must be >= 1")
        if self.env.grid_size < 2:
            raise ValueError(
                f"env.grid_size ({self.env.grid_size}) must be >= 2")
        if self.env.grid_size > min(self.env.frame_height,
                                    self.env.frame_width):
            raise ValueError(
                f"env.grid_size ({self.env.grid_size}) must be <= the frame "
                f"size ({self.env.frame_height}x{self.env.frame_width}): a "
                "grid cell needs at least one pixel, or the gridworld "
                "renders a uniform background (zero-information obs)")
        if self.actor.anakin_lanes < 1:
            raise ValueError(
                f"actor.anakin_lanes ({self.actor.anakin_lanes}) must be "
                ">= 1")
        if self.actor.anakin_scans_per_train < 1:
            raise ValueError(
                f"actor.anakin_scans_per_train "
                f"({self.actor.anakin_scans_per_train}) must be >= 1")
        if isinstance(self.actor.anakin_priority, str):
            if self.actor.anakin_priority != "td":
                raise ValueError(
                    f"actor.anakin_priority ({self.actor.anakin_priority!r})"
                    " must be 'td' (in-graph n-step TD seeding from the "
                    "acting policy's Q-values) or a positive constant stamp")
        elif self.actor.anakin_priority <= 0:
            raise ValueError(
                f"actor.anakin_priority ({self.actor.anakin_priority}) must "
                "be > 0: zero-priority sequences are unsamplable, so a "
                "freshly emitted block could never be trained on")
        if self.actor.on_device:
            # the fused acting path's structural preconditions fail HERE,
            # at config construction, with the fix spelled out — not as an
            # opaque shape error inside the jitted scan
            if self.replay.placement != "device":
                raise ValueError(
                    "actor.on_device requires replay.placement='device': "
                    "the acting scan ring-writes blocks straight into the "
                    "HBM-resident replay (host placement would re-introduce "
                    "the host round-trip the path exists to remove)")
            if self.env.episode_len % self.replay.block_length != 0:
                raise ValueError(
                    f"actor.on_device requires env.episode_len "
                    f"({self.env.episode_len}) to be a multiple of "
                    f"replay.block_length ({self.replay.block_length}): the "
                    "fused scan emits fixed block_length-step blocks, so "
                    "episode ends must land on block boundaries (the host "
                    "path's emit-on-done semantics)")
            if self.mesh.mp > 1:
                raise ValueError(
                    "actor.on_device composes with data-parallel meshes "
                    "only: the fused acting scan runs per-shard lane "
                    "groups over mesh.dp, but model parallelism (mesh.mp "
                    f"= {self.mesh.mp}) shards the network's feature dims "
                    "through the GSPMD learner step, which the acting "
                    "scan does not run under — set mesh.mp=1 (mesh.dp > 1 "
                    "is fine) or actor.on_device=false")
            if self.mesh.dp > 1 and \
                    self.actor.anakin_lanes % self.mesh.dp != 0:
                # the lane/shard divisibility contract, enforced HERE so
                # a bad pairing fails at config construction, not as a
                # reshape error inside the traced shard_map program
                raise ValueError(
                    f"actor.anakin_lanes ({self.actor.anakin_lanes}) must "
                    f"be divisible by mesh.dp ({self.mesh.dp}): the fused "
                    "acting scan partitions the lanes into equal "
                    "per-shard groups (anakin_lanes % dp == 0) — adjust "
                    "actor.anakin_lanes or mesh.dp")
            # mesh.dp=-1 (all devices) resolves at runtime; the loop
            # re-checks both contracts against the resolved dp there
            per_shard = (self.actor.anakin_lanes // self.mesh.dp
                         if self.mesh.dp > 1 else self.actor.anakin_lanes)
            if self.mesh.dp >= 1 and per_shard > self.num_blocks:
                raise ValueError(
                    f"actor.anakin_lanes ({self.actor.anakin_lanes}) must "
                    f"leave each shard's lane group ({per_shard}) <= "
                    f"num_blocks ({self.num_blocks}): each segment "
                    "ring-writes one block per lane in a single "
                    "replay_add_many dispatch, whose scatter rows must not "
                    "alias — grow replay.capacity or lower the lane count")
            if self.multiplayer.enabled:
                raise ValueError(
                    "actor.on_device is not supported with multiplayer "
                    "(the jitted envs have no host/join engine wiring)")
            if self.mesh.multihost:
                raise ValueError(
                    "actor.on_device is single-controller only (the fused "
                    "loop is not integrated with the lockstep multihost "
                    "trainer yet) — unset mesh.multihost")
            if self.actor.fault_spec:
                raise ValueError(
                    "actor.fault_spec requires the host actor fleet: fault "
                    "injection lives at the worker block sink "
                    "(runtime/actor_loop.py), which the fused on-device "
                    "loop never runs — a chaos run with actor.on_device "
                    "would inject nothing and report vacuously healthy")
        if self.actor.fault_spec:
            from r2d2_tpu.tools.chaos import (parse_fault_spec,
                                              parse_join_spec)
            faults = parse_fault_spec(self.actor.fault_spec)
            joins = parse_join_spec(self.actor.fault_spec)
            # membership faults may target spare slots (joiners lease
            # them), so the bound is the elastic fleet's MAX width
            width = self.fleet.resolved_max_slots(self.actor.num_actors)
            bad = sorted(s for s in set(faults) | set(joins) if s >= width)
            if bad:
                raise ValueError(
                    f"actor.fault_spec targets slot(s) {bad} outside the "
                    f"fleet of {width} slot(s) (actor.num_actors workers "
                    "+ fleet.max_slots spares)")
            membership_kinds = sorted(
                s for s, f in faults.items() if f.kind == "leave")
            if (joins or membership_kinds) and not self.fleet.elastic:
                raise ValueError(
                    "actor.fault_spec 'join'/'leave' entries require "
                    "fleet.elastic=true: they are MEMBERSHIP faults — a "
                    "leave parks the slot for re-adoption and a join "
                    "adopts it, semantics the frozen fleet's "
                    "respawn-in-place supervision does not have (a "
                    "non-elastic leave would just crash-loop the "
                    "worker)")
            if self.actor.inference != "server":
                disc = [s for s, f in faults.items()
                        if f.kind == "disconnect"]
                if disc:
                    raise ValueError(
                        f"actor.fault_spec slot(s) {disc} use the "
                        "'disconnect' kind, which injects at the serve "
                        "client — it requires actor.inference='server' "
                        "(with local inference there is no connection to "
                        "drop, so the run would report vacuously healthy)")
        if self.actor.inference not in ("local", "server"):
            raise ValueError(
                f"actor.inference ({self.actor.inference!r}) must be "
                "'local' or 'server'")
        if self.actor.inference == "server":
            if self.actor.on_device:
                raise ValueError(
                    "actor.inference='server' requires the host actor "
                    "fleet: the fused on-device loop (actor.on_device) "
                    "has no per-step policy client — its acting forward "
                    "is already device-resident")
            if self.mesh.multihost:
                raise ValueError(
                    "actor.inference='server' is single-host for now: the "
                    "multihost lockstep fleet wires its own weight "
                    "distribution — route its actors through a serve "
                    "transport in the elastic-fleet arc (ROADMAP item 4)")
            lanes = self.actor.num_actors * self.actor.envs_per_actor
            if lanes > self.serve.state_slots:
                raise ValueError(
                    f"actor fleet has {lanes} lanes but serve.state_slots "
                    f"is {self.serve.state_slots}: every lane leases a "
                    "server-side state slot, so an undersized cache would "
                    "thrash (evict live episodes) — raise "
                    "serve.state_slots")
        if self.serve.max_batch < 1:
            raise ValueError(
                f"serve.max_batch ({self.serve.max_batch}) must be >= 1")
        if self.serve.deadline_ms < 0:
            raise ValueError(
                f"serve.deadline_ms ({self.serve.deadline_ms}) must be "
                ">= 0")
        if self.serve.state_slots < 1 or self.serve.state_shards < 1:
            raise ValueError(
                "serve.state_slots and serve.state_shards must be >= 1")
        if self.serve.state_slots % self.serve.state_shards != 0:
            raise ValueError(
                f"serve.state_slots ({self.serve.state_slots}) must be "
                f"divisible by serve.state_shards "
                f"({self.serve.state_shards}): shards are equal slot "
                "groups")
        for fname in ("lease_timeout_s", "request_timeout_s",
                      "max_retry_s", "weight_poll_interval_s"):
            if getattr(self.serve, fname) <= 0:
                raise ValueError(f"serve.{fname} must be > 0")
        if self.serve.request_ttl_s < 0:
            raise ValueError(
                f"serve.request_ttl_s ({self.serve.request_ttl_s}) must "
                "be >= 0 (0 disables expiry)")
        if self.serve.transport not in ("auto", "shm", "socket"):
            raise ValueError(
                f"serve.transport ({self.serve.transport!r}) must be "
                "'auto', 'shm', or 'socket'")
        if self.serve.request_ring_slots < 2 or \
                self.serve.reply_ring_slots < 2:
            raise ValueError(
                "serve.request_ring_slots and serve.reply_ring_slots "
                "must be >= 2")
        if self.telemetry.alerts_serve_p99_ms <= 0:
            raise ValueError(
                f"telemetry.alerts_serve_p99_ms "
                f"({self.telemetry.alerts_serve_p99_ms}) must be > 0")
        if not 0 < self.telemetry.alerts_serve_starved_frac <= 1:
            raise ValueError(
                f"telemetry.alerts_serve_starved_frac "
                f"({self.telemetry.alerts_serve_starved_frac}) must be in "
                "(0, 1]")
        if self.telemetry.alerts_serve_churn < 1:
            raise ValueError(
                f"telemetry.alerts_serve_churn "
                f"({self.telemetry.alerts_serve_churn}) must be >= 1")
        # -- serving fleet (ISSUE 17): the router partitions whole
        # client-hash shard groups, so the server count is bounded by
        # the shard count and shm (single-ring) cannot host N loops --
        if self.serve.servers < 1:
            raise ValueError(
                f"serve.servers ({self.serve.servers}) must be >= 1")
        if self.serve.servers > self.serve.state_shards:
            raise ValueError(
                f"serve.servers ({self.serve.servers}) must be <= "
                f"serve.state_shards ({self.serve.state_shards}): each "
                "server owns at least one whole client-hash shard group "
                "— raise state_shards or lower servers")
        if self.serve.max_servers != 0 and not (
                self.serve.servers <= self.serve.max_servers
                <= self.serve.state_shards):
            raise ValueError(
                f"serve.max_servers ({self.serve.max_servers}) must be 0 "
                f"(= serve.servers) or in [serve.servers, "
                f"serve.state_shards] — it is the elastic fleet's slot "
                "board width and every server needs >= 1 shard")
        if self.serve.queue_depth_bound < 0:
            raise ValueError(
                f"serve.queue_depth_bound ({self.serve.queue_depth_bound})"
                " must be >= 0 (0 disables admission control)")
        if self.serve.servers > 1 and self.serve.transport == "shm":
            raise ValueError(
                "serve.servers > 1 requires transport 'auto' or "
                "'socket': the shm rung is a single request ring with "
                "one server-side consumer — multi-server routing rides "
                "per-server sockets (process mode) or in-proc endpoints "
                "(thread mode)")
        if not 0 < self.telemetry.alerts_serve_shed_frac <= 1:
            raise ValueError(
                f"telemetry.alerts_serve_shed_frac "
                f"({self.telemetry.alerts_serve_shed_frac}) must be in "
                "(0, 1]")
        if self.fleet.lease_transport not in ("", "socket"):
            raise ValueError(
                f"fleet.lease_transport ({self.fleet.lease_transport!r}) "
                "must be '' (in-proc only) or 'socket' (serve the lease "
                "API for cli/join.py)")
        if self.fleet.lease_port < 0:
            raise ValueError(
                f"fleet.lease_port ({self.fleet.lease_port}) must be "
                ">= 0 (0 = ephemeral)")
        # -- elastic fleet (ISSUE 15): structural preconditions fail at
        # config construction with the fix spelled out --
        fl = self.fleet
        if fl.replay_shards < 0:
            raise ValueError(
                f"fleet.replay_shards ({fl.replay_shards}) must be >= 0 "
                "(0 = legacy in-mesh replay)")
        if fl.replay_shards > 0:
            if self.replay.placement != "device":
                raise ValueError(
                    "fleet.replay_shards requires replay.placement="
                    "'device': the service's shards are the jitted "
                    "HBM-resident rings (host placement already has its "
                    "own CPU tree — disaggregate the device plane)")
            if self.mesh.dp != 1 or self.mesh.mp != 1:
                raise ValueError(
                    "fleet.replay_shards composes with a 1x1 mesh only: "
                    "the service IS the replay sharding layer (it "
                    "generalizes the dp-sharded rings into addressable "
                    "shards) — set mesh.dp=1/mesh.mp=1 or use the "
                    "in-mesh dp sharding without the service")
            if self.actor.on_device:
                raise ValueError(
                    "fleet.replay_shards requires the host actor fleet: "
                    "the fused on-device loop ring-writes straight into "
                    "its colocated replay (actor.on_device) — the "
                    "service exists for producers that do NOT share the "
                    "learner's program")
            if self.mesh.multihost:
                raise ValueError(
                    "fleet.replay_shards is single-controller for now — "
                    "the lockstep multihost trainer keeps its per-rank "
                    "in-mesh shards (routing its ranks through the "
                    "service is the ROADMAP item-1 composition)")
            if self.num_blocks % fl.replay_shards != 0:
                raise ValueError(
                    f"fleet.replay_shards ({fl.replay_shards}) must "
                    f"divide num_blocks ({self.num_blocks}): shards are "
                    "equal device-ring slices — adjust replay.capacity "
                    "or the shard count")
            if fl.replay_route == "lane":
                # lanes are contiguous [0, max_slots * envs_per_actor):
                # residues mod replay_shards cover every shard iff there
                # are at least as many lanes as shards — otherwise some
                # shard can never receive a block and the per-shard
                # training gate stays closed FOREVER (errorless stall)
                lanes = (fl.resolved_max_slots(self.actor.num_actors)
                         * self.actor.envs_per_actor)
                if lanes < fl.replay_shards:
                    raise ValueError(
                        f"fleet.replay_route='lane' with "
                        f"{fl.replay_shards} shards needs at least that "
                        f"many ε-ladder lanes (fleet has {lanes}): shard "
                        "s only receives lanes with lane % shards == s, "
                        "so an uncovered shard would hold the training "
                        "gate closed forever — grow the fleet or use "
                        "replay_route='round_robin'")
        if fl.spill_blocks < 0:
            raise ValueError(
                f"fleet.spill_blocks ({fl.spill_blocks}) must be >= 0")
        if fl.spill_blocks > 0 and fl.replay_shards < 1:
            raise ValueError(
                "fleet.spill_blocks requires fleet.replay_shards >= 1: "
                "the spill tier is the replay service's demotion target "
                "(the in-mesh rings overwrite in place)")
        if fl.spill_promote_per_sample < 0:
            raise ValueError(
                f"fleet.spill_promote_per_sample "
                f"({fl.spill_promote_per_sample}) must be >= 0")
        if fl.replay_route not in ("round_robin", "lane"):
            raise ValueError(
                f"fleet.replay_route ({fl.replay_route!r}) must be "
                "'round_robin' or 'lane'")
        if fl.service_transport not in ("", "socket"):
            raise ValueError(
                f"fleet.service_transport ({fl.service_transport!r}) "
                "must be '' (in-proc producers only) or 'socket'")
        if fl.service_transport and fl.replay_shards < 1:
            raise ValueError(
                "fleet.service_transport requires fleet.replay_shards "
                ">= 1 (there is no service to listen for)")
        # -- batched/pipelined service data plane (ISSUE 16) --
        if fl.ingest_batch_blocks < 1:
            raise ValueError(
                f"fleet.ingest_batch_blocks ({fl.ingest_batch_blocks}) "
                "must be >= 1 (1 = the per-block replay_add path)")
        if fl.ingest_batch_blocks > 1 and fl.replay_shards < 1:
            raise ValueError(
                "fleet.ingest_batch_blocks > 1 requires "
                "fleet.replay_shards >= 1: grouped ingest is the "
                "service's commit plane (the in-mesh path already has "
                "replay.ingest_batch_blocks) — a run without the "
                "service would silently ignore the knob")
        if fl.socket_window < 1:
            raise ValueError(
                f"fleet.socket_window ({fl.socket_window}) must be >= 1 "
                "(1 = one-frame-one-ack lockstep)")
        if fl.socket_window > 1 and fl.service_transport != "socket":
            raise ValueError(
                "fleet.socket_window > 1 requires "
                "fleet.service_transport='socket': the in-flight window "
                "is the socket rung's ack pipeline — in-proc producers "
                "have no frames to window")
        if fl.spill_prefetch and fl.spill_blocks < 1:
            raise ValueError(
                "fleet.spill_prefetch requires fleet.spill_blocks >= 1: "
                "priority-aware prefetch promotes from the spill tier — "
                "with no tier the knob would be silently ignored")
        if fl.sample_staging and fl.replay_shards < 1:
            raise ValueError(
                "fleet.sample_staging requires fleet.replay_shards >= 1:"
                " the stager pipelines the SERVICE sample path (the "
                "in-mesh learner already pipelines via the PR-2 ingest "
                "stager)")
        if fl.fanout_degree < 0 or fl.fanout_degree == 1:
            raise ValueError(
                f"fleet.fanout_degree ({fl.fanout_degree}) must be 0 "
                "(direct polling) or >= 2 (relay tree degree)")
        if fl.fanout_pull_interval_s < 0:
            raise ValueError(
                f"fleet.fanout_pull_interval_s "
                f"({fl.fanout_pull_interval_s}) must be >= 0")
        if fl.max_slots < 0:
            raise ValueError(
                f"fleet.max_slots ({fl.max_slots}) must be >= 0 "
                "(0 = actor.num_actors, no spares)")
        if 0 < fl.max_slots < self.actor.num_actors:
            raise ValueError(
                f"fleet.max_slots ({fl.max_slots}) must be >= "
                f"actor.num_actors ({self.actor.num_actors}): the "
                "startup fleet occupies the first num_actors slots")
        if self.actor.on_device and (fl.fanout_degree > 0 or fl.elastic
                                     or fl.max_slots > 0):
            raise ValueError(
                "fleet fan-out / elastic membership require the host "
                "actor fleet: the fused on-device loop (actor.on_device) "
                "has no weight service and no worker slots to lease")
        if self.mesh.multihost and (fl.elastic or fl.max_slots > 0):
            raise ValueError(
                "fleet.elastic / fleet.max_slots are single-controller "
                "for now: the lockstep multihost trainer's per-rank "
                "fleets have no membership plane (its supervision "
                "respawns in place) — a multihost run would silently "
                "ignore the knobs, so they are rejected instead "
                "(ROADMAP item 4 names the composition)")
        if not 0 < self.telemetry.alerts_spill_thrash_frac <= 1:
            raise ValueError(
                f"telemetry.alerts_spill_thrash_frac "
                f"({self.telemetry.alerts_spill_thrash_frac}) must be "
                "in (0, 1]")
        if self.telemetry.alerts_fanout_lag < 1:
            raise ValueError(
                f"telemetry.alerts_fanout_lag "
                f"({self.telemetry.alerts_fanout_lag}) must be >= 1 "
                "(publications behind the root)")
        if self.telemetry.alerts_orphaned_slots < 1:
            raise ValueError(
                f"telemetry.alerts_orphaned_slots "
                f"({self.telemetry.alerts_orphaned_slots}) must be >= 1")
        if self.telemetry.alerts_ingest_backlog < 1:
            raise ValueError(
                f"telemetry.alerts_ingest_backlog "
                f"({self.telemetry.alerts_ingest_backlog}) must be >= 1 "
                "(blocks queued behind the service drain)")
        if self.network.inference_dtype not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"network.inference_dtype "
                f"({self.network.inference_dtype!r}) must be 'f32', "
                "'bf16', or 'int8' — the acting/serving forward's weight "
                "dtype (the learner always trains in the network.bf16 "
                "policy regardless)")
        if self.telemetry.quant_probe_interval < 0:
            raise ValueError(
                f"telemetry.quant_probe_interval "
                f"({self.telemetry.quant_probe_interval}) must be >= 0 "
                "(0 disables the in-graph accuracy probe)")
        if not 0 < self.telemetry.alerts_quant_agreement <= 1:
            raise ValueError(
                f"telemetry.alerts_quant_agreement "
                f"({self.telemetry.alerts_quant_agreement}) must be in "
                "(0, 1]")
        for fname, lo in (("supervise_interval_s", 0.0),
                          ("restart_window_s", 0.0)):
            if getattr(self.runtime, fname) <= lo:
                raise ValueError(f"runtime.{fname} must be > {lo}")
        for fname in ("hang_timeout_s", "hang_spawn_grace_s",
                      "restart_backoff_base_s", "restart_backoff_max_s",
                      "ingest_stall_timeout_s"):
            if getattr(self.runtime, fname) < 0:
                raise ValueError(f"runtime.{fname} must be >= 0")
        if self.runtime.max_restarts_per_window < 0:
            raise ValueError("runtime.max_restarts_per_window must be >= 0")
        if self.runtime.profile_at_step < 0:
            raise ValueError("runtime.profile_at_step must be >= 0")
        if self.runtime.snapshot_interval < 0:
            raise ValueError(
                f"runtime.snapshot_interval "
                f"({self.runtime.snapshot_interval}) must be >= 0 "
                "(learner steps between replay snapshots; 0 disables)")
        if self.runtime.keep_checkpoints < 0:
            raise ValueError(
                f"runtime.keep_checkpoints "
                f"({self.runtime.keep_checkpoints}) must be >= 0 "
                "(newest checkpoints retained; 0 keeps everything)")
        if (self.runtime.snapshot_interval
                and self.replay.placement == "host"):
            raise ValueError(
                "runtime.snapshot_interval requires the device replay "
                "(replay.placement='device'): the host-replay numpy twin "
                "has no snapshot plane yet — set snapshot_interval=0 or "
                "switch placement")
        if self.telemetry.alerts_snapshot_stale_s <= 0:
            raise ValueError(
                f"telemetry.alerts_snapshot_stale_s "
                f"({self.telemetry.alerts_snapshot_stale_s}) must be > 0")
        if self.telemetry.alerts_recovery_loop < 1:
            raise ValueError(
                f"telemetry.alerts_recovery_loop "
                f"({self.telemetry.alerts_recovery_loop}) must be >= 1 "
                "(supervisor relaunches before the alert fires)")
        if self.telemetry.trace_sample_every < 1:
            raise ValueError(
                f"telemetry.trace_sample_every "
                f"({self.telemetry.trace_sample_every}) must be >= 1 "
                "(1 = trace every block/exchange)")
        if self.telemetry.alerts_spill_promotion_ms <= 0:
            raise ValueError(
                f"telemetry.alerts_spill_promotion_ms "
                f"({self.telemetry.alerts_spill_promotion_ms}) must be > 0")
        if self.telemetry.alerts_e2e_latency_growth <= 1:
            raise ValueError(
                f"telemetry.alerts_e2e_latency_growth "
                f"({self.telemetry.alerts_e2e_latency_growth}) must be > 1 "
                "(a multiple of the p50's rolling median)")
        if self.telemetry.ring_size < 16:
            raise ValueError(
                f"telemetry.ring_size ({self.telemetry.ring_size}) must be "
                ">= 16")
        if self.telemetry.flush_interval_s <= 0:
            raise ValueError("telemetry.flush_interval_s must be > 0")
        if self.telemetry.learning_interval < 1:
            raise ValueError(
                f"telemetry.learning_interval "
                f"({self.telemetry.learning_interval}) must be >= 1")
        if self.telemetry.learning_dq_batch < 1:
            raise ValueError(
                f"telemetry.learning_dq_batch "
                f"({self.telemetry.learning_dq_batch}) must be >= 1")
        if self.telemetry.nan_policy not in ("warn", "halt"):
            raise ValueError(
                f"telemetry.nan_policy ({self.telemetry.nan_policy!r}) must "
                "be 'warn' or 'halt'")
        if self.telemetry.resources_interval_s <= 0:
            raise ValueError("telemetry.resources_interval_s must be > 0")
        if not 0 <= self.telemetry.resources_headroom_warn_frac < 1:
            raise ValueError(
                f"telemetry.resources_headroom_warn_frac "
                f"({self.telemetry.resources_headroom_warn_frac}) must be "
                "in [0, 1)")
        if self.telemetry.alerts_window < 2:
            raise ValueError(
                f"telemetry.alerts_window ({self.telemetry.alerts_window}) "
                "must be >= 2")
        if not 0 < self.telemetry.alerts_throughput_drop_frac <= 1:
            raise ValueError(
                f"telemetry.alerts_throughput_drop_frac "
                f"({self.telemetry.alerts_throughput_drop_frac}) must be "
                "in (0, 1]")
        if self.telemetry.alerts_heartbeat_age_s < 0:
            raise ValueError(
                "telemetry.alerts_heartbeat_age_s must be >= 0")
        if self.telemetry.alerts_staleness_growth_factor <= 1:
            raise ValueError(
                f"telemetry.alerts_staleness_growth_factor "
                f"({self.telemetry.alerts_staleness_growth_factor}) must "
                "be > 1")
        if not 0 <= self.telemetry.alerts_hbm_headroom_frac < 1:
            raise ValueError(
                f"telemetry.alerts_hbm_headroom_frac "
                f"({self.telemetry.alerts_hbm_headroom_frac}) must be in "
                "[0, 1)")
        if self.telemetry.alerts_retrace_storm < 1:
            raise ValueError(
                f"telemetry.alerts_retrace_storm "
                f"({self.telemetry.alerts_retrace_storm}) must be >= 1")
        if self.telemetry.alerts_shard_imbalance <= 1:
            raise ValueError(
                f"telemetry.alerts_shard_imbalance "
                f"({self.telemetry.alerts_shard_imbalance}) must be > 1 "
                "(a max/min per-shard env-steps ratio; 1.0 = perfectly "
                "balanced)")
        if self.telemetry.replay_diag_interval < 1:
            raise ValueError(
                f"telemetry.replay_diag_interval "
                f"({self.telemetry.replay_diag_interval}) must be >= 1")
        if not 0 < self.telemetry.alerts_replay_ess_frac < 1:
            raise ValueError(
                f"telemetry.alerts_replay_ess_frac "
                f"({self.telemetry.alerts_replay_ess_frac}) must be in "
                "(0, 1)")
        if not 0 < self.telemetry.alerts_priority_saturation <= 1:
            raise ValueError(
                f"telemetry.alerts_priority_saturation "
                f"({self.telemetry.alerts_priority_saturation}) must be in "
                "(0, 1]")
        if self.telemetry.alerts_never_sampled_growth <= 1:
            raise ValueError(
                f"telemetry.alerts_never_sampled_growth "
                f"({self.telemetry.alerts_never_sampled_growth}) must be "
                "> 1 (a multiple of the fraction's rolling median)")
        if not 0 < self.telemetry.alerts_lane_starved_frac <= 1:
            raise ValueError(
                f"telemetry.alerts_lane_starved_frac "
                f"({self.telemetry.alerts_lane_starved_frac}) must be in "
                "(0, 1]")
        if self.telemetry.fleet_host_row_max_bytes < 0:
            raise ValueError(
                f"telemetry.fleet_host_row_max_bytes "
                f"({self.telemetry.fleet_host_row_max_bytes}) must be >= 0 "
                "(0 = unbounded)")
        if self.telemetry.alerts_rank_straggler <= 1:
            raise ValueError(
                f"telemetry.alerts_rank_straggler "
                f"({self.telemetry.alerts_rank_straggler}) must be > 1 "
                "(a max/min per-rank step-time ratio; 1.0 = "
                "perfectly balanced)")
        if not 0 < self.telemetry.alerts_lockstep_wait_frac <= 1:
            raise ValueError(
                f"telemetry.alerts_lockstep_wait_frac "
                f"({self.telemetry.alerts_lockstep_wait_frac}) must be in "
                "(0, 1]")
        if self.telemetry.alerts_fleet_desync <= 1:
            raise ValueError(
                f"telemetry.alerts_fleet_desync "
                f"({self.telemetry.alerts_fleet_desync}) must be > 1 "
                "(a max/min per-rank env-steps ratio)")
        if self.telemetry.alerts_missing_rank_age_s <= 0:
            raise ValueError(
                f"telemetry.alerts_missing_rank_age_s "
                f"({self.telemetry.alerts_missing_rank_age_s}) must be > 0")
        if not 0 <= self.serve.shadow_sample_rate <= 1:
            raise ValueError(
                f"serve.shadow_sample_rate ({self.serve.shadow_sample_rate}) "
                "must be in [0, 1]")
        if self.telemetry.quality_eval_interval_s <= 0:
            raise ValueError(
                f"telemetry.quality_eval_interval_s "
                f"({self.telemetry.quality_eval_interval_s}) must be > 0")
        if self.telemetry.quality_eval_rounds < 1:
            raise ValueError(
                f"telemetry.quality_eval_rounds "
                f"({self.telemetry.quality_eval_rounds}) must be >= 1")
        if self.telemetry.quality_eval_clients < 1:
            raise ValueError(
                f"telemetry.quality_eval_clients "
                f"({self.telemetry.quality_eval_clients}) must be >= 1")
        if self.telemetry.quality_calib_sample_every < 1:
            raise ValueError(
                f"telemetry.quality_calib_sample_every "
                f"({self.telemetry.quality_calib_sample_every}) must be "
                ">= 1")
        if not 0 < self.telemetry.alerts_quality_regression < 1:
            raise ValueError(
                f"telemetry.alerts_quality_regression "
                f"({self.telemetry.alerts_quality_regression}) must be in "
                "(0, 1) (a fraction of the rolling-median eval return)")
        if not 0 < self.telemetry.alerts_canary_divergence <= 1:
            raise ValueError(
                f"telemetry.alerts_canary_divergence "
                f"({self.telemetry.alerts_canary_divergence}) must be in "
                "(0, 1] (a greedy-disagreement fraction)")
        if self.telemetry.alerts_promotion_stall_s <= 0:
            raise ValueError(
                f"telemetry.alerts_promotion_stall_s "
                f"({self.telemetry.alerts_promotion_stall_s}) must be > 0")
        if not 0 <= self.fleet.promotion_canary_frac <= 1:
            raise ValueError(
                f"fleet.promotion_canary_frac "
                f"({self.fleet.promotion_canary_frac}) must be in [0, 1]")
        if not 0 <= self.fleet.promotion_divergence_bound <= 1:
            raise ValueError(
                f"fleet.promotion_divergence_bound "
                f"({self.fleet.promotion_divergence_bound}) must be in "
                "[0, 1] (a greedy-disagreement fraction)")
        if self.fleet.promotion_min_shadow < 0:
            raise ValueError(
                f"fleet.promotion_min_shadow "
                f"({self.fleet.promotion_min_shadow}) must be >= 0")
        if self.multiplayer.enabled and self.actor.envs_per_actor > 1:
            raise ValueError(
                "actor.envs_per_actor > 1 is not supported with multiplayer "
                "(host/join port wiring is per actor worker; extra lanes in "
                "one worker would collide on the game sockets — scale "
                "actor.num_actors instead)")
        if self.multiplayer.enabled and not (
                -1 <= self.multiplayer.player_id
                < self.multiplayer.num_players):
            raise ValueError(
                f"multiplayer.player_id ({self.multiplayer.player_id}) must "
                f"be -1 (whole population in-process) or in [0, "
                f"num_players={self.multiplayer.num_players})")

    # ---- derived helpers ----

    @property
    def seqs_per_block(self) -> int:
        return self.replay.block_length // self.sequence.learning_steps

    @property
    def num_blocks(self) -> int:
        return self.replay.capacity // self.replay.block_length

    @property
    def num_sequences(self) -> int:
        return self.replay.capacity // self.sequence.learning_steps

    def replace(self, **dotted: Any) -> "Config":
        """Return a new Config with dotted-path overrides applied.

        cfg.replace(**{"replay.capacity": 1000, "actor.num_actors": 4})
        """
        updates: Dict[str, Dict[str, Any]] = {}
        for key, value in dotted.items():
            if "." not in key:
                raise KeyError(f"override key must be dotted (section.field): {key!r}")
            section, fname = key.split(".", 1)
            if "." in fname:
                raise KeyError(f"only one nesting level supported: {key!r}")
            updates.setdefault(section, {})[fname] = value
        replaced = {}
        for section, fields in updates.items():
            sub = getattr(self, section)
            replaced[section] = dataclasses.replace(sub, **fields)
        return dataclasses.replace(self, **replaced)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Config":
        """Inverse of to_dict (tuples round-trip through JSON lists)."""
        kwargs = {}
        for f in dataclasses.fields(cls):
            # sections absent from the dict take their defaults: configs
            # serialized before a section existed (checkpoint .config.json
            # files) must keep loading after the schema grows
            sub = dict(d.get(f.name) or {})
            for key, value in sub.items():
                if isinstance(value, list):
                    sub[key] = tuple(
                        tuple(x) if isinstance(x, list) else x for x in value)
            kwargs[f.name] = _SECTION_TYPES[f.name](**sub)
        return cls(**kwargs)

    @classmethod
    def from_json(cls, text: str) -> "Config":
        return cls.from_dict(json.loads(text))


_SECTION_TYPES = {
    "env": EnvConfig, "network": NetworkConfig, "sequence": SequenceConfig,
    "replay": ReplayConfig, "optim": OptimConfig, "actor": ActorConfig,
    "serve": ServeConfig, "fleet": FleetConfig,
    "multiplayer": MultiplayerConfig,
    "mesh": MeshConfig, "runtime": RuntimeConfig,
    "telemetry": TelemetryConfig,
}

# Field annotations are strings (PEP 563 via `from __future__ import
# annotations`); only scalar fields are CLI-settable.
_SCALAR_ANNOTATIONS = {"bool": bool, "int": int, "float": float, "str": str}


def _coerce(key: str, value: str, annotation: str) -> Any:
    if "Tuple[Tuple[int, int, int], ...]" in str(annotation):
        # conv-pyramid syntax: triples of out_channels,kernel,stride joined
        # by ';' — e.g. --network.conv_layers=8,4,2;16,3,1
        try:
            layers = tuple(
                tuple(int(x) for x in triple.split(","))
                for triple in value.split(";") if triple)
        except ValueError:
            layers = ()
        if not layers or any(len(t) != 3 for t in layers):
            raise SystemExit(
                f"invalid value {value!r} for {key!r}: expected "
                "';'-separated out_channels,kernel,stride triples, e.g. "
                "8,4,2;16,3,1")
        return layers
    if str(annotation) == "Any":
        # union knob (actor.anakin_priority: a float stamp or "td") —
        # numeric strings become floats, anything else stays a string
        # and Config.__post_init__ validates the allowed spellings
        try:
            return float(value)
        except ValueError:
            return value
    target_type = _SCALAR_ANNOTATIONS.get(str(annotation).replace("Optional[str]", "str"))
    if target_type is None:
        raise SystemExit(
            f"cannot set {key!r} from the command line (field type {annotation}); "
            "construct the Config in code instead"
        )
    if target_type is bool:
        lowered = value.lower()
        if lowered in ("1", "true", "yes", "on"):
            return True
        if lowered in ("0", "false", "no", "off"):
            return False
        raise SystemExit(
            f"invalid value {value!r} for {key!r} (expected a boolean: "
            "1/0, true/false, yes/no, on/off)")
    if target_type is str:
        return value
    try:
        return target_type(value)
    except ValueError:
        raise SystemExit(
            f"invalid value {value!r} for {key!r} (expected {target_type.__name__})"
        ) from None


def parse_overrides(cfg: Config, argv: List[str]) -> Config:
    """Apply ``--section.field=value`` CLI overrides, type-coerced from the
    dataclass field annotations. Unknown keys raise."""
    dotted: Dict[str, Any] = {}
    for arg in argv:
        if not arg.startswith("--") or "=" not in arg:
            raise SystemExit(f"unrecognized argument {arg!r}; expected --section.field=value")
        key, _, raw = arg[2:].partition("=")
        section, _, fname = key.partition(".")
        if section not in {f.name for f in dataclasses.fields(cfg)}:
            raise SystemExit(f"unknown config section {section!r}")
        sub = getattr(cfg, section)
        matching = {f.name: f for f in dataclasses.fields(sub)}
        if fname not in matching:
            raise SystemExit(f"unknown field {fname!r} in section {section!r}")
        dotted[key] = _coerce(key, raw, matching[fname].type)
    return cfg.replace(**dotted) if dotted else cfg


def apex_epsilon(actor_id: int, num_actors: int, base_eps: float,
                 alpha: float) -> float:
    """Ape-X per-actor epsilon ladder: eps_i = base ** (1 + i*alpha/(N-1))
    (ref train.py:16-18). Single-actor runs get base_eps. No defaults: the
    authoritative values live in ActorConfig (base_eps, eps_alpha)."""
    if num_actors <= 1:
        return base_eps
    return base_eps ** (1 + actor_id / (num_actors - 1) * alpha)


def vector_lane_epsilons(actor_idx: int, actor_cfg: ActorConfig,
                         total_actors: Optional[int] = None) -> List[float]:
    """Per-lane ε for one vectorized actor worker: the Ape-X ladder spread
    over ALL total_actors * envs_per_actor lanes in the fleet, with worker
    ``actor_idx`` owning the contiguous lane slice — so a fleet of vector
    actors explores exactly like the equally-sized scalar-actor fleet the
    reference runs (train.py:16-18). ``total_actors`` defaults to
    ``actor_cfg.num_actors`` (single-host); a multihost fleet passes its
    GLOBAL worker count (process_count * num_actors) alongside the global
    ``actor_idx``, mirroring the scalar path's global apex_epsilon."""
    if total_actors is None:
        total_actors = actor_cfg.num_actors
    if not 0 <= actor_idx < total_actors:
        raise ValueError(
            f"actor_idx {actor_idx} outside the fleet of {total_actors} "
            "workers — multihost callers must pass their global worker "
            "count as total_actors")
    k = actor_cfg.envs_per_actor
    total = total_actors * k
    return [apex_epsilon(actor_idx * k + lane, total, actor_cfg.base_eps,
                         actor_cfg.eps_alpha)
            for lane in range(k)]


# Fields eligible for population-based/genetic hyperparameter search, mirroring
# the reference's `<-- GEN` tags (ref config.py:12-57, README.md:28-32).
# Continuous fields carry a (lo, hi) range; fields constrained by the replay
# layout invariants (Config.__post_init__: learning_steps | block_length,
# block_length | capacity) or best kept hardware-friendly carry an explicit
# choice tuple, so samplers never draw layout-invalid configs.
GENETIC_SEARCH_SPACE: Dict[str, Dict[str, Any]] = {
    "optim.lr": {"range": (1e-5, 1e-3), "log": True},
    "optim.gamma": {"range": (0.99, 0.999)},
    "optim.target_net_update_interval": {"choices": (500, 1000, 2000, 2500, 5000)},
    "replay.batch_size": {"choices": (32, 64, 128, 256)},
    # multiples of block_length=400 (capacity % block_length == 0)
    "replay.capacity": {"choices": (50_000, 100_000, 200_000, 500_000, 1_000_000)},
    "replay.prio_exponent": {"range": (0.0, 1.0)},
    "replay.importance_sampling_exponent": {"range": (0.0, 1.0)},
    "sequence.burn_in_steps": {"choices": (0, 10, 20, 40, 80)},
    # divisors of block_length=400 (block_length % learning_steps == 0)
    "sequence.learning_steps": {"choices": (5, 8, 10, 16, 20)},
    "network.hidden_dim": {"choices": (128, 256, 512, 1024)},
    "network.cnn_out_dim": {"choices": (256, 512, 1024, 2048)},
    "network.use_dueling": {"choices": (False, True)},
}
