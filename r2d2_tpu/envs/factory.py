"""Environment factory (ref /root/reference/environment.py:82-93).

Resolves an env id to a backend:
  * "Fake*"       — the hermetic deterministic env (tests/benchmarks);
  * "Vizdoom*"    — the ViZDoom binding (r2d2_tpu.envs.vizdoom_env), gated on
                    the vizdoom package;
  * anything else — gymnasium (ALE Atari ids like "ALE/Boxing-v5"), gated on
                    gymnasium.

Then applies the reference's wrapper stack: WarpFrame always, ClipReward for
training only (ref environment.py:88-92).
"""

from typing import Optional

from r2d2_tpu.config import EnvConfig
from r2d2_tpu.envs.fake import FakeR2D2Env
from r2d2_tpu.envs.wrappers import ClipReward, GymnasiumAdapter, WarpFrame


def create_env(cfg: EnvConfig, *, clip_rewards: Optional[bool] = None,
               multi_conf: str = "", is_host: bool = False, testing: bool = False,
               port: int = 5060, num_players: int = 1, name: str = "",
               seed: int = 0):
    """Build + wrap one environment instance.

    Signature keeps the reference's parameter surface (environment.py:82-93)
    including the multiplayer wiring passed through to ViZDoom.
    """
    clip = cfg.clip_rewards if clip_rewards is None else clip_rewards
    env_id = cfg.env_id

    if env_id.startswith("Fake"):
        env = FakeR2D2Env(height=cfg.frame_height, width=cfg.frame_width,
                          seed=seed,
                          wiring=dict(is_host=is_host, port=port,
                                      num_players=num_players, name=name))
    elif env_id.startswith("Vizdoom"):
        from r2d2_tpu.envs.vizdoom_env import make_vizdoom
        env = make_vizdoom(
            env_id, frame_skip=cfg.frame_skip, multi_conf=multi_conf,
            is_host=is_host, testing=testing, port=port,
            num_players=num_players, name=name, reward_cfg=cfg, seed=seed)
        env = WarpFrame(env, cfg.frame_height, cfg.frame_width)
    else:
        try:
            import gymnasium
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                f"env id {env_id!r} requires gymnasium (not installed); "
                "use the Fake backend for hermetic runs") from e
        kwargs = {}
        if cfg.frame_skip > 1:
            kwargs["frameskip"] = cfg.frame_skip
        env = GymnasiumAdapter(gymnasium.make(env_id, **kwargs), seed=seed)
        env = WarpFrame(env, cfg.frame_height, cfg.frame_width)

    if clip:
        env = ClipReward(env)
    return env
