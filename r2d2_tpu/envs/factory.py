"""Environment factory (ref /root/reference/environment.py:82-93).

Resolves an env id to a backend:
  * "Fake*"       — the hermetic deterministic env (tests/benchmarks);
  * "JaxFake*" /
    "Grid",
    "JaxGrid*"    — the PURE-JAX envs (envs/jax_env.py) behind the host
                    adapter, so the jitted dynamics run under the legacy
                    actor loops too; ``create_jax_env`` resolves the same
                    kinds to the raw jitted env for the on-device acting
                    path (actor.on_device, runtime/anakin_loop.py);
  * "Vizdoom*"    — the ViZDoom binding (r2d2_tpu.envs.vizdoom_env), gated on
                    the vizdoom package;
  * anything else — gymnasium (ALE Atari ids like "ALE/Boxing-v5"), gated on
                    gymnasium.

Then applies the reference's wrapper stack: WarpFrame always, ClipReward for
training only (ref environment.py:88-92).
"""

from typing import Optional

from r2d2_tpu.config import EnvConfig
from r2d2_tpu.envs.fake import FakeR2D2Env
from r2d2_tpu.envs.wrappers import ClipReward, GymnasiumAdapter, WarpFrame


def _is_jax_grid(game_name: str) -> bool:
    from r2d2_tpu.envs.jax_env import is_jax_grid_id
    return is_jax_grid_id(game_name)


def create_jax_env(cfg: EnvConfig):
    """Resolve the env id to a PURE-JAX env (envs/jax_env.py protocol) for
    the fused on-device acting path. The plain "Fake" kind resolves too —
    JaxFakeEnv is its jitted port (parity-tested), so flipping
    actor.on_device needs no env rename."""
    from r2d2_tpu.envs.jax_env import (JaxFakeEnv, JaxGridWorld,
                                       is_jax_grid_id)
    env_id = cfg.env_id
    if env_id.startswith(("JaxFake", "Fake")):
        return JaxFakeEnv(episode_len=cfg.episode_len,
                          height=cfg.frame_height, width=cfg.frame_width)
    if is_jax_grid_id(cfg.game_name):
        return JaxGridWorld(size=cfg.grid_size, episode_len=cfg.episode_len,
                            height=cfg.frame_height, width=cfg.frame_width)
    raise ValueError(
        f"env id {env_id!r} has no pure-JAX implementation — the on-device "
        "acting path (actor.on_device) supports the 'Fake'/'JaxFake' and "
        "'Grid' kinds; engine-backed envs must use the host actor fleet")


def create_env(cfg: EnvConfig, *, clip_rewards: Optional[bool] = None,
               multi_conf: str = "", is_host: bool = False, testing: bool = False,
               port: int = 5060, num_players: int = 1, name: str = "",
               seed: int = 0):
    """Build + wrap one environment instance.

    Signature keeps the reference's parameter surface (environment.py:82-93)
    including the multiplayer wiring passed through to ViZDoom.
    """
    clip = cfg.clip_rewards if clip_rewards is None else clip_rewards
    env_id = cfg.env_id

    if env_id.startswith("Fake"):
        env = FakeR2D2Env(height=cfg.frame_height, width=cfg.frame_width,
                          episode_len=cfg.episode_len, seed=seed,
                          wiring=dict(is_host=is_host, port=port,
                                      num_players=num_players, name=name))
    elif env_id.startswith("JaxFake") or _is_jax_grid(cfg.game_name):
        # the jitted envs behind the host adapter: same dynamics as the
        # on-device acting path, reachable from the legacy actor loops
        from r2d2_tpu.envs.jax_env import HostJaxEnv
        env = HostJaxEnv(create_jax_env(cfg), seed=seed)
    elif env_id.startswith("Vizdoom"):
        from r2d2_tpu.envs.vizdoom_env import make_vizdoom
        env = make_vizdoom(
            env_id, frame_skip=cfg.frame_skip, multi_conf=multi_conf,
            is_host=is_host, testing=testing, port=port,
            num_players=num_players, name=name, reward_cfg=cfg, seed=seed)
        env = WarpFrame(env, cfg.frame_height, cfg.frame_width)
    else:
        try:
            import gymnasium
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                f"env id {env_id!r} requires gymnasium (not installed); "
                "use the Fake backend for hermetic runs") from e
        kwargs = {}
        if cfg.frame_skip > 1:
            kwargs["frameskip"] = cfg.frame_skip
        env = GymnasiumAdapter(gymnasium.make(env_id, **kwargs), seed=seed)
        env = WarpFrame(env, cfg.frame_height, cfg.frame_width)

    if clip:
        env = ClipReward(env)
    return env
