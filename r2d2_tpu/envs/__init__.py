"""Environment layer.

``create_env`` mirrors the reference factory (/root/reference/environment.py:82-93):
gym-style construction + Atari preprocessing wrappers, with ViZDoom and
Atari/ALE gated on availability. ``FakeR2D2Env`` is the hermetic deterministic
environment the reference lacks (SURVEY.md §4) — the test/CI backend.

Internal Env protocol is the reference's: ``reset() -> obs``,
``step(a) -> (obs, reward, done, info)``, ``action_space.n`` — gymnasium's
5-tuple API is adapted in wrappers.py.
"""

from r2d2_tpu.envs.fake import FakeR2D2Env
from r2d2_tpu.envs.factory import create_env
from r2d2_tpu.envs.vector import SyncVectorEnv, make_vector_env

__all__ = ["FakeR2D2Env", "create_env", "SyncVectorEnv", "make_vector_env"]
