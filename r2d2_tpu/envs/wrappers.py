"""Atari-style preprocessing wrappers (ref /root/reference/environment.py:10-79)
plus a gymnasium-API adapter, torch/cv2-optional.

WarpFrame: RGB→grayscale + resize to 84x84. Uses cv2 when present (same C++
path as the reference, environment.py:71-75); otherwise a numpy fallback
(ITU-R 601 luma + area resampling) so the wrapper stack is importable
everywhere.
"""

import functools
from typing import Any, Tuple

import numpy as np

try:  # pragma: no cover - exercised only when cv2 is installed
    import cv2
    _HAS_CV2 = True
except ImportError:
    _HAS_CV2 = False


def _to_gray(frame: np.ndarray) -> np.ndarray:
    if frame.ndim == 2:
        return frame
    if _HAS_CV2:
        return cv2.cvtColor(frame, cv2.COLOR_RGB2GRAY)
    return (frame @ np.array([0.299, 0.587, 0.114])).astype(np.uint8)


@functools.lru_cache(maxsize=8)
def _area_weights(n_src: int, n_dst: int) -> np.ndarray:
    """(n_dst, n_src) row-normalized coverage weights for 1-D area
    resampling: output cell i averages the source interval
    [i*s, (i+1)*s), s = n_src/n_dst, with fractional edge coverage —
    the pixel-area relation cv2's INTER_AREA computes for downscaling."""
    scale = n_src / n_dst
    w = np.zeros((n_dst, n_src), np.float64)
    for i in range(n_dst):
        a, b = i * scale, (i + 1) * scale
        for k in range(int(np.floor(a)), min(int(np.ceil(b)), n_src)):
            w[i, k] = min(k + 1.0, b) - max(float(k), a)
    return w / w.sum(axis=1, keepdims=True)


_warned_fallback = False


def _resize(frame: np.ndarray, height: int, width: int) -> np.ndarray:
    if frame.shape == (height, width):
        return frame
    if _HAS_CV2:
        return cv2.resize(frame, (width, height), interpolation=cv2.INTER_AREA)
    # numpy fallback: exact area resample (separable coverage-weighted
    # average, fractional ratios included — real Atari is 210x160 -> 84x84,
    # ratios 2.5 and 1.9). Matches cv2's INTER_AREA up to fixed-point
    # rounding (+-1 gray level, tested vs cv2 in CI); warn once anyway so a
    # cv2-less deployment knows its observations are not bit-identical to
    # the reference preprocessing (ref environment.py:71-75; VERDICT r4).
    global _warned_fallback
    if not _warned_fallback:
        import warnings
        warnings.warn(
            "cv2 is not installed: WarpFrame is using the numpy area-"
            f"resample fallback for {frame.shape} -> ({height}, {width}). "
            "It matches cv2 INTER_AREA only up to rounding (+-1 gray "
            "level) — install opencv-python for the reference's exact "
            "preprocessing.")
        _warned_fallback = True
    wy = _area_weights(frame.shape[0], height)
    wx = _area_weights(frame.shape[1], width)
    out = wy @ frame.astype(np.float64) @ wx.T
    return np.clip(np.floor(out + 0.5), 0, 255).astype(np.uint8)


class Wrapper:
    def __init__(self, env: Any):
        self.env = env

    @property
    def action_space(self):
        return self.env.action_space

    @property
    def unwrapped(self):
        """Innermost env (the gym surface tests/tools use to reach
        backend-specific attributes through the wrapper stack)."""
        inner = self.env
        return inner.unwrapped if hasattr(inner, "unwrapped") else inner

    def reset(self):
        return self.env.reset()

    def step(self, action):
        return self.env.step(action)

    def close(self):
        return self.env.close()


class GymnasiumAdapter(Wrapper):
    """gymnasium 5-tuple API → the reference's 4-tuple protocol.

    ``seed`` (optional) is forwarded to the FIRST gymnasium reset — how
    gymnasium seeds an env — so per-lane vector-env seeds (vector.py)
    reach the ALE backends; later resets continue the seeded stream."""

    def __init__(self, env, seed=None):
        super().__init__(env)
        self._pending_seed = seed

    def reset(self):
        if self._pending_seed is not None:
            out = self.env.reset(seed=int(self._pending_seed))
            self._pending_seed = None
        else:
            out = self.env.reset()
        return out[0] if isinstance(out, tuple) else out

    def step(self, action):
        out = self.env.step(action)
        if len(out) == 5:
            obs, reward, terminated, truncated, info = out
            return obs, reward, terminated or truncated, info
        return out


class WarpFrame(Wrapper):
    """Grayscale + resize (ref environment.py:48-79)."""

    def __init__(self, env, height: int = 84, width: int = 84):
        super().__init__(env)
        self.height, self.width = height, width

    def _warp(self, obs: np.ndarray) -> np.ndarray:
        return _resize(_to_gray(np.asarray(obs)), self.height, self.width)

    def reset(self):
        return self._warp(self.env.reset())

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        return self._warp(obs), reward, done, info


class ClipReward(Wrapper):
    """Clip rewards to [-1, 1], training-time only (ref environment.py:39-45;
    actors/eval construct with clip_rewards=False, ref worker.py:507)."""

    def step(self, action):
        obs, reward, done, info = self.env.step(action)
        return obs, float(np.clip(reward, -1.0, 1.0)), done, info


class NoopReset(Wrapper):
    """Random no-op burn after reset (ref environment.py:10-37; present but
    disabled in the reference factory, environment.py:90-91)."""

    def __init__(self, env, noop_max: int = 30, noop_action: int = 0, seed: int = 0):
        super().__init__(env)
        self.noop_max = noop_max
        self.noop_action = noop_action
        self.rng = np.random.default_rng(seed)

    def reset(self):
        obs = self.env.reset()
        for _ in range(int(self.rng.integers(1, self.noop_max + 1))):
            obs, _, done, _ = self.env.step(self.noop_action)
            if done:
                obs = self.env.reset()
        return obs
