"""ViZDoom pure logic — engine-free, hermetically testable.

Everything the reference's gym wrapper computes *around* the C++ engine
(/root/reference/vizdoom_gym_wrapper/base_gym_env.py) factored into pure
functions: scenario registry, DELTA-button expansion, discrete→engine action
vectors, multiplayer game-argument strings, and the shaped multiplayer reward
from game-variable deltas. The engine binding in vizdoom_env.py is a thin
shell over these.
"""

from typing import Dict, List, Sequence, Tuple

from r2d2_tpu.config import EnvConfig

# Scenario registry: 14 env ids → bundled scenario cfg files
# (ref vizdoom_gym_wrapper/__init__.py:3-85).
SCENARIOS: Dict[str, str] = {
    "VizdoomBasic-v0": "basic.cfg",
    "VizdoomCorridor-v0": "deadly_corridor.cfg",
    "VizdoomDefendCenter-v0": "defend_the_center.cfg",
    "VizdoomDefendLine-v0": "defend_the_line.cfg",
    "VizdoomHealthGathering-v0": "health_gathering.cfg",
    "VizdoomMyWayHome-v0": "my_way_home.cfg",
    "VizdoomPredictPosition-v0": "predict_position.cfg",
    "VizdoomTakeCover-v0": "take_cover.cfg",
    "VizdoomDeathmatch-v0": "deathmatch.cfg",
    "VizdoomHealthGatheringSupreme-v0": "health_gathering_supreme.cfg",
    "VizdoomBasicWithAttack-v0": "basic_with_attack.cfg",
    "VizdoomBasicWithAttackLessActions-v0": "basic_with_attack_less_actions.cfg",
    "VizdoomBasicDeathmatch-v0": "multi.cfg",
    "VizdoomSingleDeathmatch-v0": "multi_single.cfg",
}

# Scenarios whose reward comes from game-variable deltas even single-player
# (ref base_gym_env.py:157-159).
MULTI_REWARD_SCENARIOS = ("multi_single.cfg",)


def expand_buttons(button_names: Sequence[str]) -> Tuple[List[str], int]:
    """DELTA (continuous) buttons become two discrete actions _POS_i/_NEG_i so
    the action space stays Discrete (ref base_gym_env.py:114-127).

    Returns (expanded_names, num_delta_buttons)."""
    expanded: List[str] = []
    num_delta = 0
    for name in button_names:
        if "DELTA" in name:
            expanded.append(f"{name}_POS_{num_delta}")
            expanded.append(f"{name}_NEG_{num_delta}")
            num_delta += 1
        else:
            expanded.append(name)
    return expanded, num_delta


def build_action_vector(action: int, expanded_names: Sequence[str],
                        num_delta: int) -> List[int]:
    """Discrete action index → engine button vector (ref base_gym_env.py:146-154).

    The engine vector has one slot per *original* button; a DELTA button's
    slot receives +1/-1 depending on which expanded action was chosen.

    Note: the reference indexes ``act[action]`` for non-DELTA actions, which
    is out of bounds whenever a non-DELTA button follows a DELTA button in
    the config (latent because its scenarios list DELTA buttons last). Here
    each expanded entry is mapped to its true engine slot instead."""
    n_engine = len(expanded_names) - num_delta
    act = [0] * n_engine
    engine_slot = 0
    for i, name in enumerate(expanded_names):
        is_delta_pos = "DELTA" in name and name.rsplit("_", 2)[-2] == "POS"
        if i == action:
            act[engine_slot] = -1 if ("DELTA" in name and not is_delta_pos) else 1
            break
        # a _POS_ entry shares its engine slot with the _NEG_ that follows
        if not is_delta_pos:
            engine_slot += 1
    return act


def shaped_multiplayer_reward(old_vars: Sequence[float],
                              new_vars: Sequence[float],
                              cfg: EnvConfig) -> float:
    """Reward from (health, hitcount, ammo, frags) deltas, because the ACS
    script reward is global to the map (ref base_gym_env.py:190-214):
    hurt -20, death -100, ammo spent -5, hit +25, frag +100 (defaults in
    EnvConfig, overridable)."""
    old_health, old_hits, old_ammo, old_frags = old_vars
    new_health, new_hits, new_ammo, new_frags = new_vars
    reward = 0.0
    if old_health > new_health and new_health != 0:
        reward += cfg.reward_hurt
    elif old_health > new_health and new_health == 0:
        reward += cfg.reward_death
    if old_ammo > new_ammo:
        reward += cfg.reward_ammo
    if old_hits < new_hits:
        reward += cfg.reward_hit
    if old_frags < new_frags:
        reward += cfg.reward_frag
    return reward


def host_game_args(num_players: int, port: int) -> str:
    """Host-side engine args for a deathmatch game (ref base_gym_env.py:71-83)."""
    return (
        f"-host {num_players} "
        f"-port {port} "
        "+viz_connect_timeout 60 "
        "-deathmatch "
        "+timelimit 10.0 "
        "+sv_forcerespawn 1 "
        "+sv_noautoaim 1 "
        "+sv_respawnprotect 1 "
        "+sv_spawnfarthest 1 "
        "+viz_respawn_delay 10 "
        "+viz_nocheat 1")


def join_game_args(ip: str, port: int) -> str:
    """Client-side join args (ref base_gym_env.py:84-86)."""
    return f"-join {ip} -port {port}"


def player_args(player_name: str, colorset: int) -> str:
    return f"+name {player_name} +colorset {colorset}"


def compose_render_image(obs_shape, screen=None, depth=None,
                         labels_buffer=None, labels=(), automap=None,
                         label_colors=None, n_panels: int = 1):
    """Side-by-side composition of the engine's view buffers — pure numpy.

    The reference builds this image inline in its pygame render
    (ref base_gym_env.py:242-297): screen, then (when enabled) a
    3-channel-tiled depth buffer, a label mask recolored per object, and the
    automap, concatenated horizontally. ``labels`` is a sequence of
    ``(object_id, value)`` pairs; ``label_colors`` a (N, 3) uint8 palette.
    With no ``screen`` (terminal state) returns a black image sized for
    ``n_panels`` panels.
    """
    import numpy as np

    if screen is None:
        return np.zeros((obs_shape[0], obs_shape[1] * n_panels, 3), np.uint8)
    images = [screen]
    if depth is not None:
        images.append(np.repeat(depth[..., None], 3, axis=2))
    if labels_buffer is not None:
        labels_rgb = np.zeros_like(screen)
        for object_id, value in labels:
            color = label_colors[int(object_id) % len(label_colors)]
            labels_rgb[labels_buffer == value] = color
        images.append(labels_rgb)
    if automap is not None:
        images.append(automap)
    return np.concatenate(images, axis=1)
