"""Deterministic fake environment for hermetic tests and benchmarks.

The reference has no test backend at all — multi-process behavior is only
exercised live against the ViZDoom engine (SURVEY.md §4). This environment
replaces it: fully deterministic given (seed, actions), pure numpy, with a
*learnable* reward so end-to-end training tests can assert loss decrease and
return improvement.

Dynamics: the observation encodes a target action as a block pattern;
choosing the target yields +1, anything else 0. Episodes run a fixed number
of steps. The target follows a seeded periodic schedule, so a recurrent
policy can do strictly better than a reactive one (the next target is a
function of history, part of it shown only transiently).
"""

from typing import Optional, Tuple

import numpy as np


class _DiscreteSpace:
    def __init__(self, n: int, seed: int = 0):
        self.n = n
        self._rng = np.random.default_rng(seed)

    def sample(self) -> int:
        return int(self._rng.integers(self.n))


class FakeR2D2Env:
    def __init__(self, action_dim: int = 6, episode_len: int = 120,
                 height: int = 84, width: int = 84, seed: int = 0,
                 wiring: dict = None):
        self.action_space = _DiscreteSpace(action_dim, seed)
        self.episode_len = episode_len
        self.h, self.w = height, width
        self.seed = seed
        # multiplayer host/join args the factory resolved for this env —
        # a real engine would dial these sockets (vizdoom_env.py); the
        # fake records them so wiring is assertable hermetically
        self.multiplayer_wiring = dict(wiring or {})
        self._schedule = np.random.default_rng(seed).integers(
            action_dim, size=episode_len + 1)
        self.t = 0

    @property
    def unwrapped(self):
        """gym conformance: the innermost env is this env."""
        return self

    def _obs(self) -> np.ndarray:
        """84x84 uint8 frame encoding the current target action as a bright
        column band; deterministic in (seed, t)."""
        target = int(self._schedule[self.t])
        frame = np.full((self.h, self.w), 32, np.uint8)
        band = self.w // self.action_space.n
        frame[:, target * band : (target + 1) * band] = 224
        # time texture so consecutive frames differ (exercises frame stacking)
        frame[self.t % self.h, :] = 128
        return frame

    def reset(self) -> np.ndarray:
        self.t = 0
        return self._obs()

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        reward = 1.0 if int(action) == int(self._schedule[self.t]) else 0.0
        self.t += 1
        done = self.t >= self.episode_len
        return self._obs(), reward, done, {}

    def close(self) -> None:
        pass
