"""Pure-JAX environments: jit-safe pytree ``reset``/``step`` functions.

The host envs (envs/fake.py, the engine backends) are Python objects whose
``step`` crosses the host–device boundary every call — the structural wall
PERF.md quantifies (~1.8k env-steps/s for the whole CPU actor fleet vs 11k+
learner seq-updates/s/chip). Podracer's "Anakin" architecture (arxiv
2104.06272) and GPU Atari emulation (arxiv 1907.08467) remove it by making
the environment itself a compiled function, so batched env + policy +
experience-emit fuse into ONE device program (actor/anakin.py).

Protocol (duck-typed; both implementations are frozen dataclasses so they
are hashable and capture cleanly in jitted closures):

  * attributes ``action_dim``, ``episode_len``, ``height``, ``width``;
  * ``reset(key) -> (state, obs)`` — a fresh episode; ``state`` is any
    pytree of arrays, ``obs`` a (height, width) uint8 frame;
  * ``step(state, action, key) -> (state, obs, reward, done)`` — one
    transition; reward f32, done bool. ``done`` must be True exactly at
    step ``episode_len`` (fixed-length episodes: the fused acting scan
    relies on episode ends landing on block boundaries, validated via
    ``episode_len % block_length == 0``).

Both functions must be traceable (no Python side effects) and cheap to
``vmap`` — the acting scan calls ``reset`` speculatively once per segment
and selects it where the last step's ``done`` (auto-reset without control
flow; episode ends land only on segment boundaries by the alignment
contract above).

``HostJaxEnv`` adapts a JaxEnv to the host gym-style API so the SAME
dynamics run under the legacy actor loops (factory kinds "JaxFake"/"Grid")
— which is what makes host-vs-device parity directly testable.
"""

import dataclasses
from typing import Tuple


def is_jax_grid_id(game_name: str) -> bool:
    """True when ``EnvConfig.game_name`` names the built-in jitted
    gridworld: exactly "Grid" or the "JaxGrid*" prefix. Deliberately NOT
    a bare "Grid*" prefix — that would silently capture gymnasium games
    like "GridWorld" that must keep routing to the gymnasium backend."""
    return game_name == "Grid" or game_name.startswith("JaxGrid")

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class JaxFakeEnv:
    """Jitted port of envs/fake.py FakeR2D2Env — identical dynamics.

    The target action is encoded as a bright column band; choosing it
    yields +1. The host env draws its target schedule with
    ``np.random.default_rng(seed)``, which has no in-graph equivalent, so
    ``reset`` draws the schedule with ``jax.random`` instead (a different
    stream, same distribution). ``state_from_schedule`` accepts an
    explicit schedule — the parity tests feed it the HOST env's schedule
    and assert obs/reward/done equality step for step."""

    action_dim: int = 6
    episode_len: int = 120
    height: int = 84
    width: int = 84

    def _obs(self, schedule: jnp.ndarray, t: jnp.ndarray) -> jnp.ndarray:
        target = schedule[t]
        band = self.width // self.action_dim
        cols = jnp.arange(self.width, dtype=jnp.int32)
        in_band = (cols >= target * band) & (cols < (target + 1) * band)
        frame = jnp.where(in_band[None, :], jnp.uint8(224), jnp.uint8(32))
        frame = jnp.broadcast_to(frame, (self.height, self.width))
        # time texture row AFTER the band (host sets it last, overwriting)
        return frame.at[t % self.height].set(jnp.uint8(128))

    def state_from_schedule(self, schedule) -> dict:
        """Parity-test hook: a state whose target schedule is exactly
        ``schedule`` (e.g. a host FakeR2D2Env's ``_schedule``)."""
        schedule = jnp.asarray(schedule, jnp.int32)
        assert schedule.shape == (self.episode_len + 1,)
        return {"schedule": schedule, "t": jnp.zeros((), jnp.int32)}

    def reset(self, key: jax.Array) -> Tuple[dict, jnp.ndarray]:
        schedule = jax.random.randint(
            key, (self.episode_len + 1,), 0, self.action_dim, jnp.int32)
        state = {"schedule": schedule, "t": jnp.zeros((), jnp.int32)}
        return state, self._obs(schedule, state["t"])

    def step(self, state: dict, action: jnp.ndarray, key: jax.Array):
        del key  # deterministic given the schedule
        t = state["t"]
        reward = (action == state["schedule"][t]).astype(jnp.float32)
        t1 = t + 1
        state = {"schedule": state["schedule"], "t": t1}
        return (state, self._obs(state["schedule"], t1), reward,
                t1 >= self.episode_len)


@dataclasses.dataclass(frozen=True)
class JaxGridWorld:
    """Jitted gridworld with a REAL learning signal (the fake env's reward
    is reactive-oracle-solvable; this one needs navigation).

    A ``size`` x ``size`` grid rendered as a (height, width) frame: the
    agent cell is bright (255), the goal cell mid-bright (128), background
    dim (16). Actions: up/down/left/right/stay. Stepping onto the goal
    yields +1 and teleports the agent to a random cell (goal fixed for the
    episode), so return scales with how directly the policy navigates —
    random-walk return is a small fraction of greedy-navigation return,
    the gap the learnability tests assert."""

    size: int = 6
    episode_len: int = 120
    height: int = 84
    width: int = 84

    # up / down / left / right / stay — class-level constant
    action_dim: int = dataclasses.field(default=5, init=False)

    def _obs(self, pos: jnp.ndarray, goal: jnp.ndarray) -> jnp.ndarray:
        ch = self.height // self.size
        cw = self.width // self.size
        rows = jnp.arange(self.height, dtype=jnp.int32)
        cols = jnp.arange(self.width, dtype=jnp.int32)
        row_cell = rows // ch
        col_cell = cols // cw
        valid = (row_cell < self.size)[:, None] & (col_cell < self.size)[None, :]
        agent = ((row_cell == pos[0])[:, None]
                 & (col_cell == pos[1])[None, :] & valid)
        goal_m = ((row_cell == goal[0])[:, None]
                  & (col_cell == goal[1])[None, :] & valid)
        return jnp.where(agent, jnp.uint8(255),
                         jnp.where(goal_m, jnp.uint8(128),
                                   jnp.uint8(16)))

    def _nudge_off(self, cell: jnp.ndarray, other: jnp.ndarray) -> jnp.ndarray:
        """Deterministic fix-up: if ``cell`` coincides with ``other``,
        shift it one diagonal step (mod size) — avoids rejection loops in
        traced code while keeping the two distinguishable."""
        clash = jnp.all(cell == other)
        return jnp.where(clash, (cell + 1) % self.size, cell)

    def reset(self, key: jax.Array) -> Tuple[dict, jnp.ndarray]:
        kp, kg = jax.random.split(key)
        pos = jax.random.randint(kp, (2,), 0, self.size, jnp.int32)
        goal = self._nudge_off(
            jax.random.randint(kg, (2,), 0, self.size, jnp.int32), pos)
        state = {"pos": pos, "goal": goal, "t": jnp.zeros((), jnp.int32)}
        return state, self._obs(pos, goal)

    def step(self, state: dict, action: jnp.ndarray, key: jax.Array):
        deltas = jnp.array([[-1, 0], [1, 0], [0, -1], [0, 1], [0, 0]],
                           jnp.int32)
        pos = jnp.clip(state["pos"] + deltas[action], 0, self.size - 1)
        reached = jnp.all(pos == state["goal"])
        reward = reached.astype(jnp.float32)
        respawn = self._nudge_off(
            jax.random.randint(key, (2,), 0, self.size, jnp.int32),
            state["goal"])
        pos = jnp.where(reached, respawn, pos)
        t1 = state["t"] + 1
        new = {"pos": pos, "goal": state["goal"], "t": t1}
        return (new, self._obs(pos, state["goal"]), reward,
                t1 >= self.episode_len)


class HostJaxEnv:
    """Gym-style host adapter over a JaxEnv: the SAME compiled dynamics
    behind the legacy scalar/vector actor API (reset()/step(a)/close()),
    so the jitted envs are reachable from every existing path — and so
    device-vs-host runs of one env are directly comparable."""

    def __init__(self, env, seed: int = 0):
        from r2d2_tpu.envs.fake import _DiscreteSpace
        self.env = env
        self.action_space = _DiscreteSpace(env.action_dim, seed)
        self.episode_len = env.episode_len
        self._key = jax.random.PRNGKey(seed)
        self._state = None
        self._reset_j = jax.jit(env.reset)
        self._step_j = jax.jit(env.step)

    @property
    def unwrapped(self):
        return self

    def _split(self) -> jax.Array:
        self._key, k = jax.random.split(self._key)
        return k

    def reset(self) -> np.ndarray:
        self._state, obs = self._reset_j(self._split())
        return np.asarray(obs)

    def step(self, action: int):
        self._state, obs, reward, done = self._step_j(
            self._state, np.int32(action), self._split())
        return np.asarray(obs), float(reward), bool(done), {}

    def close(self) -> None:
        pass
