"""ViZDoom engine binding (ref /root/reference/vizdoom_gym_wrapper/base_gym_env.py).

Thin shell over the C++ engine: all decision logic lives in vizdoom_defs.py
(pure, tested without the engine). Importable only when the ``vizdoom``
package is installed; the factory gates on that.

Reference behaviors carried over: hidden window unless testing (testing also
forces ASYNC_PLAYER + no episode timeout, base_gym_env.py:59-65); multiplayer
host/join via engine args with a random player color; RGB24 screen format
forced; DELTA-button expansion keeping the action space Discrete; shaped
reward from game-variable deltas for multiplayer and for multi_single.cfg;
zero frame on the terminal step (base_gym_env.py:233-240); pygame render
stacking screen/depth/labels/automap buffers.
"""

import os
import random
import warnings
from typing import Optional

import numpy as np

from r2d2_tpu.config import EnvConfig
from r2d2_tpu.envs.vizdoom_defs import (
    MULTI_REWARD_SCENARIOS,
    SCENARIOS,
    build_action_vector,
    compose_render_image,
    expand_buttons,
    host_game_args,
    join_game_args,
    player_args,
    shaped_multiplayer_reward,
)


class _Discrete:
    def __init__(self, n: int, seed: int = 0):
        self.n = n
        self._rng = random.Random(seed)

    def sample(self) -> int:
        return self._rng.randrange(self.n)

    def contains(self, a) -> bool:
        return 0 <= int(a) < self.n


class VizdoomEnv:
    def __init__(self, level: str, frame_skip: int = 1, multi_conf: str = "",
                 is_host: bool = False, num_players: int = 1, port: int = 5060,
                 testing: bool = False, name: str = "AI",
                 reward_cfg: Optional[EnvConfig] = None, seed: int = 0):
        import vizdoom as vzd

        self._vzd = vzd
        self.level = level
        self.frame_skip = frame_skip
        self.reward_cfg = reward_cfg or EnvConfig()
        self.is_multiplayer = bool(multi_conf) or is_host

        self.game = vzd.DoomGame()
        self.game.load_config(level)
        self.game.set_window_visible(testing)
        if testing:
            self.game.set_mode(vzd.Mode.ASYNC_PLAYER)
            self.game.set_episode_timeout(0)

        if self.is_multiplayer:
            self.game.set_mode(vzd.Mode.ASYNC_PLAYER)
            if is_host:
                self.game.add_game_args(host_game_args(num_players, port))
            else:
                ip, join_port = (multi_conf.split(":") if ":" in multi_conf
                                 else ("127.0.0.1", port))
                self.game.add_game_args(join_game_args(ip, int(join_port)))
            self.game.add_game_args(player_args(name, random.choice(range(8))))

        if self.game.get_screen_format() != vzd.ScreenFormat.RGB24:
            warnings.warn("forcing RGB24 screen format")
            self.game.set_screen_format(vzd.ScreenFormat.RGB24)

        self.game.init()
        self._read_game_variables()

        self.all_button_names, self.num_delta_buttons = expand_buttons(
            [b.name for b in self.game.get_available_buttons()])
        self.action_space = _Discrete(len(self.all_button_names), seed)
        self.observation_shape = (self.game.get_screen_height(),
                                  self.game.get_screen_width(), 3)
        self.state = None
        self.window_surface = None
        self.depth = self.game.is_depth_buffer_enabled()
        self.labels = self.game.is_labels_buffer_enabled()
        self.automap = self.game.is_automap_buffer_enabled()
        self._label_colors = np.random.default_rng(42).uniform(
            25, 256, size=(256, 3)).astype(np.uint8)

    # -- engine interaction --

    def _read_game_variables(self):
        vzd = self._vzd
        self.game_variables = [
            self.game.get_game_variable(vzd.GameVariable.HEALTH),
            self.game.get_game_variable(vzd.GameVariable.HITCOUNT),
            self.game.get_game_variable(vzd.GameVariable.SELECTED_WEAPON_AMMO),
            self.game.get_game_variable(vzd.GameVariable.KILLCOUNT),
        ]

    def _observation(self) -> np.ndarray:
        if self.state is not None:
            return self.state.screen_buffer
        return np.zeros(self.observation_shape, dtype=np.uint8)

    def step(self, action: int):
        assert self.action_space.contains(action), f"{action!r} invalid"
        assert self.state is not None, "Call `reset` before `step`."
        act = build_action_vector(int(action), self.all_button_names,
                                  self.num_delta_buttons)
        reward = self.game.make_action(act, self.frame_skip)

        scenario = os.path.normpath(self.level).split(os.sep)[-1]
        if self.is_multiplayer or scenario in MULTI_REWARD_SCENARIOS:
            old_vars = self.game_variables
            self._read_game_variables()
            reward = shaped_multiplayer_reward(old_vars, self.game_variables,
                                               self.reward_cfg)

        self.state = self.game.get_state()
        done = self.game.is_episode_finished()
        return self._observation(), reward, done, {}

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.game.set_seed(seed)
        self.game.new_episode()
        self.state = self.game.get_state()
        self._read_game_variables()
        return self._observation()

    def render(self, mode: str = "human"):
        img = self._render_image()
        if mode == "rgb_array":
            return img
        import pygame
        img = img.transpose(1, 0, 2)
        if self.window_surface is None:
            pygame.init()
            pygame.display.set_caption("ViZDoom")
            self.window_surface = pygame.display.set_mode(img.shape[:2])
        surf = pygame.surfarray.make_surface(img)
        self.window_surface.blit(surf, (0, 0))
        pygame.display.update()

    def _render_image(self) -> np.ndarray:
        state = self.game.get_state()
        n_panels = 1 + self.depth + self.labels + self.automap
        if state is None:
            return compose_render_image(self.observation_shape,
                                        n_panels=n_panels)
        return compose_render_image(
            self.observation_shape,
            screen=state.screen_buffer,
            depth=state.depth_buffer if self.depth else None,
            labels_buffer=state.labels_buffer if self.labels else None,
            labels=[(l.object_id, l.value) for l in state.labels]
            if self.labels else (),
            automap=state.automap_buffer if self.automap else None,
            label_colors=self._label_colors)

    def close(self):
        if self.window_surface is not None:
            import pygame
            pygame.quit()
        self.game.close()


def make_vizdoom(env_id: str, *, frame_skip: int = 1, multi_conf: str = "",
                 is_host: bool = False, testing: bool = False, port: int = 5060,
                 num_players: int = 1, name: str = "AI",
                 reward_cfg: Optional[EnvConfig] = None, seed: int = 0
                 ) -> VizdoomEnv:
    """Resolve a Vizdoom*-v0 id against the scenario registry and build the
    env (ref gym_env_defns.py:6-13 resolves under vizdoom's scenarios_path)."""
    try:
        from vizdoom import scenarios_path
    except ImportError as e:
        raise ImportError(
            f"{env_id!r} requires the vizdoom package (not installed in this "
            "image); use the Fake backend or an ALE id instead") from e
    if env_id not in SCENARIOS:
        raise KeyError(f"unknown ViZDoom env id {env_id!r}; known: "
                       f"{sorted(SCENARIOS)}")
    level = os.path.join(scenarios_path, SCENARIOS[env_id])
    # multiplayer joiners default to the local host game (ref train.py:33-38)
    if multi_conf == "" and not is_host and num_players > 1:
        multi_conf = f"127.0.0.1:{port}"
    return VizdoomEnv(level, frame_skip, multi_conf, is_host, num_players,
                      port, testing, name, reward_cfg, seed)
