"""Synchronous vector environment: N per-lane envs behind one batched API.

The reference steps every env in its own Ray actor with batch-size-1
inference (/root/reference/worker.py:528-547); Podracer-class systems
(arxiv 2104.06272) instead drive many envs per worker so ONE jitted policy
call serves N lanes. This wrapper supplies the env side of that design:
``step`` takes an (N,) action vector and returns stacked (N, ...) arrays.

Semantics chosen to keep the per-lane experience stream IDENTICAL to the
scalar actor loop (runtime/actor_loop.py run_actor):

  * ``step`` returns each lane's TRUE next observation — including the
    terminal one on episode end, which the LocalBuffer records — never the
    auto-reset frame.
  * Auto-reset: a done lane is reset inside the same ``step`` call, and the
    new episode's initial observation rides in ``infos[lane]["reset_obs"]``
    (alongside the closed episode's accounting), so the caller restarts the
    lane without a second env round-trip. ``auto_reset=False`` leaves the
    lane to an explicit ``reset_lane`` (the actor loop's episode-truncation
    path uses ``reset_lane`` either way).
  * Per-lane episode accounting (steps, return) lives here, emitted on the
    done step — the vectorized twin of the scalar loop's episode counters.
"""

from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np


class SyncVectorEnv:
    """Drive N envs in lockstep; lane i is ``envs[i]``. OWNS the lane envs:
    ``close()`` closes every one (the vector actor loop closes the wrapper
    in its finally, exactly like the scalar loop owns its single env)."""

    def __init__(self, envs: Sequence, auto_reset: bool = True):
        if not envs:
            raise ValueError("SyncVectorEnv needs at least one lane env")
        self.envs = list(envs)
        self.num_envs = len(self.envs)
        self.action_space = self.envs[0].action_space
        self.auto_reset = auto_reset
        self._episode_steps = np.zeros(self.num_envs, np.int64)
        self._episode_returns = np.zeros(self.num_envs, np.float64)

    @property
    def episode_steps(self) -> np.ndarray:
        """Per-lane steps into the CURRENT episode — the single source of
        episode accounting (the vector actor loop reads this for its
        max_episode_steps truncation; treat as read-only)."""
        return self._episode_steps

    def reset(self) -> np.ndarray:
        """Reset every lane; returns stacked (N, H, W) initial obs."""
        obs = [self.reset_lane(i) for i in range(self.num_envs)]
        return np.stack(obs)

    def reset_lane(self, lane: int) -> np.ndarray:
        """Reset one lane (explicit restart — the truncation path)."""
        self._episode_steps[lane] = 0
        self._episode_returns[lane] = 0.0
        return np.asarray(self.envs[lane].reset())

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     List[dict]]:
        """Step all lanes. Returns (obs (N, H, W), rewards (N,) f32,
        dones (N,) bool, infos). Done lanes report the terminal obs in the
        stacked array; with auto_reset their info carries ``reset_obs``,
        ``episode_steps``, and ``episode_return``."""
        actions = np.asarray(actions)
        if actions.shape != (self.num_envs,):
            raise ValueError(
                f"expected ({self.num_envs},) actions, got {actions.shape}")
        obs_rows = []
        rewards = np.zeros(self.num_envs, np.float32)
        dones = np.zeros(self.num_envs, bool)
        infos: List[dict] = []
        for i, env in enumerate(self.envs):
            obs, reward, done, info = env.step(int(actions[i]))
            info = dict(info)
            self._episode_steps[i] += 1
            self._episode_returns[i] += float(reward)
            if done:
                info["episode_steps"] = int(self._episode_steps[i])
                info["episode_return"] = float(self._episode_returns[i])
                if self.auto_reset:
                    # reset_lane zeroes the accounting — read it out first
                    info["reset_obs"] = self.reset_lane(i)
            obs_rows.append(np.asarray(obs))
            rewards[i] = reward
            dones[i] = done
            infos.append(info)
        return np.stack(obs_rows), rewards, dones, infos

    def close(self) -> None:
        for env in self.envs:
            try:
                env.close()
            except Exception:
                pass


def make_vector_env(env_cfg, num_envs: int, *, seed: int = 0,
                    auto_reset: bool = True,
                    env_factory: Optional[Callable] = None,
                    **env_kwargs) -> SyncVectorEnv:
    """Factory-integrated construction: N ``create_env`` lanes with
    consecutive per-lane seeds (seed + lane), wrapped. ``env_kwargs`` pass
    through to every lane (multiplayer wiring is rejected upstream —
    Config validates envs_per_actor == 1 there)."""
    if env_factory is None:
        from r2d2_tpu.envs.factory import create_env
        env_factory = create_env
    envs = []
    try:
        for lane in range(num_envs):
            envs.append(env_factory(env_cfg, seed=seed + lane, **env_kwargs))
    except Exception:
        for env in envs:
            try:
                env.close()
            except Exception:
                pass
        raise
    return SyncVectorEnv(envs, auto_reset=auto_reset)
