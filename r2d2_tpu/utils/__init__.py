"""Small shared utilities."""

from r2d2_tpu.utils.platform import pin_platform

__all__ = ["pin_platform"]
