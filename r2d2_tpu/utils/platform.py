"""JAX platform pinning that actually sticks.

A PJRT plugin registered via site hooks (e.g. a remote-TPU tunnel plugin) can
hang *platform discovery* itself when its backend is unreachable — even when
``JAX_PLATFORMS`` excludes it, because the env var filters after the plugin
initializes. Routing the same request through ``jax.config`` filters before
any backend init, so a CPU-pinned process (actor subprocess, test runner,
CPU-only CLI run) never touches the accelerator plugin.
"""

import os
from typing import Optional


def force_host_device_count(n: int) -> None:
    """Set the virtual CPU device count in XLA_FLAGS, REPLACING any existing
    ``--xla_force_host_platform_device_count`` (an inherited value from a
    parent test/driver process would otherwise win). Must run before the CPU
    backend initializes."""
    flags = os.environ.get("XLA_FLAGS", "")
    kept = [f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    kept.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(kept)


def pin_cpu_platform(n_devices: int) -> None:
    """Pin this process to an ``n_devices``-wide virtual CPU platform.

    The one blessed preamble for every CPU-pinned entry point (tests,
    multichip/multihost dryruns): env vars for fresh/child processes, then
    the jax.config route for a jax that is already imported (effective until
    the first backend initialization). Must run before any jax computation.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    force_host_device_count(n_devices)
    os.environ.setdefault("JAX_ENABLE_X64", "0")
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized; callers verify jax.devices()


def pin_platform(platform: Optional[str] = None) -> None:
    """Apply ``platform`` (default: the JAX_PLATFORMS env var) through
    jax.config. No-op if no request or if a backend already initialized."""
    platform = platform or os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platform)
    except Exception:
        pass  # backends already initialized; the env var governed them
