"""JAX platform pinning that actually sticks.

A PJRT plugin registered via site hooks (e.g. a remote-TPU tunnel plugin) can
hang *platform discovery* itself when its backend is unreachable — even when
``JAX_PLATFORMS`` excludes it, because the env var filters after the plugin
initializes. Routing the same request through ``jax.config`` filters before
any backend init, so a CPU-pinned process (actor subprocess, test runner,
CPU-only CLI run) never touches the accelerator plugin.
"""

import os
from typing import Optional


def pin_platform(platform: Optional[str] = None) -> None:
    """Apply ``platform`` (default: the JAX_PLATFORMS env var) through
    jax.config. No-op if no request or if a backend already initialized."""
    platform = platform or os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platform)
    except Exception:
        pass  # backends already initialized; the env var governed them
