"""CPU-jitted actor policy — the reference's ``Network.step`` + ε-greedy
(/root/reference/model.py:67-84, /root/reference/worker.py:535-538) without
torch or Ray.

Actor processes run on host CPUs while the learner owns the TPU, so the
policy pins its params to the CPU backend: JAX placement follows committed
operands, making the same Flax module a CPU program here and a TPU program in
the learner — weight sync is a raw pytree copy, no format conversion
(the reference ships state_dicts through Ray's object store,
/root/reference/worker.py:286-290,572-576).

The policy owns the per-episode recurrent state and rolling frame stack
(ref worker.py:516,526,546-547, model.py:34,86-87).
"""

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.models.network import (NetworkApply, initial_hidden,
                                     is_quant_bundle, make_inference_bundle,
                                     quantized_inference_apply)


def _pin_params(params, cpu, copy: bool):
    """CPU-resident params, REALLY copied when ``copy``. ``device_put``
    alone is wrong for in-process aliases: to the same device it is a
    no-op, and when the source is the learner's train_state — whose
    buffers are donated by the next fused step — the alias dies with it
    (observed as 'Buffer has been deleted or donated' in a
    single-process CPU run). ONE implementation for both actor policies."""
    if copy:
        params = jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True), params)
    return jax.device_put(params, cpu)


def make_forward_fn(net: NetworkApply, inference_dtype: Optional[str] = None,
                    probe_interval: int = 0):
    """The ONE jitted acting forward (ISSUE 13 satellite): a (N, 1)
    single-step recurrent forward shared by ``ActorPolicy`` (N=1),
    ``BatchedActorPolicy``, and the central policy server
    (serve/server.py) — one definition of the acting forward across
    local and served inference, so parity between them is the identity
    of a single program, not a numerics argument.

    ``inference_dtype`` (default: ``net.config.inference_dtype``) is the
    quantized-inference knob (ISSUE 14) — because every consumer builds
    its forward HERE, flipping the config knob switches local actors,
    the policy server, and (through the same apply variant) the anakin
    scan together.

    At ``"f32"`` (the default) the program is byte-identical to pre-PR14:
    ``fn(params, stacked_obs, last_action, hidden)`` with ``stacked_obs``
    (N, H, W, stack) f32 in [0,1], ``last_action`` (N,) int32, ``hidden``
    (N, 2, hidden) packed — returns (greedy_actions (N,), q (N, A),
    hidden' (N, 2, hidden)).

    At ``"bf16"``/``"int8"`` the forward takes the PUBLISHED bundle
    ({"f32", "quant", "stamp"} — make_inference_bundle) plus a tick
    counter and the LIVE row count, and returns a 4th element, the
    accuracy probe: ``fn(bundle, stacked_obs, last_action, hidden,
    tick, live) -> (actions, q, hidden', (dq_max, agree_frac,
    probed))``. Every ``probe_interval``-th tick a ``lax.cond`` branch
    ALSO runs the f32 twin on the same live batch and emits
    max |Q_f32 − Q_quant| and the greedy-action agreement fraction over
    the first ``live`` rows (probed = 1.0) — the server pads
    under-filled dispatches to pow2 buckets, and degenerate pad rows
    must neither fire nor dilute quant_divergence; local policies pass
    live = N. Other ticks the branch is skipped and probed = 0.0.
    ``probe_interval=0`` compiles the probe OUT entirely — the
    program's weight arguments are then the quantized twin alone (what
    the costmodel's weight-bytes rows measure)."""
    mode = (inference_dtype if inference_dtype is not None
            else net.config.inference_dtype)

    if mode == "f32":
        def step_fn(params, stacked_obs, last_action, hidden):
            obs = stacked_obs[:, None]                     # (N, 1, ...)
            la = jax.nn.one_hot(last_action, net.action_dim,
                                dtype=jnp.float32)[:, None]
            q, h = net.module.apply(params, obs, la, hidden)
            return jnp.argmax(q[:, 0], axis=-1), q[:, 0], h

        return jax.jit(step_fn)

    from r2d2_tpu.models.network import f32_reference_module
    f32_module = f32_reference_module(net)
    interval = int(probe_interval)

    def quant_step_fn(bundle, stacked_obs, last_action, hidden, tick,
                      live):
        obs = stacked_obs[:, None]                         # (N, 1, ...)
        la = jax.nn.one_hot(last_action, net.action_dim,
                            dtype=jnp.float32)[:, None]
        q, h = quantized_inference_apply(net, bundle["quant"], obs, la,
                                         hidden)
        q = q[:, 0]
        actions = jnp.argmax(q, axis=-1)
        if interval > 0:
            def probe(_):
                q32, _h = f32_module.apply(bundle["f32"], obs, la, hidden)
                q32 = q32[:, 0]
                # first `live` rows only: the server's pow2 padding rows
                # are a fixed degenerate input, not policy behavior
                mask = (jnp.arange(q.shape[0]) <
                        jnp.asarray(live, jnp.int32))
                n = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
                dq = jnp.max(jnp.where(
                    mask[:, None], jnp.abs(q32 - q), 0.0))
                agree = jnp.sum(
                    ((jnp.argmax(q32, axis=-1) == actions) & mask
                     ).astype(jnp.float32)) / n
                return dq, agree, jnp.float32(1.0)

            probe_out = jax.lax.cond(
                jnp.asarray(tick, jnp.int32) % interval == 0, probe,
                lambda _: (jnp.float32(0.0), jnp.float32(0.0),
                           jnp.float32(0.0)),
                operand=None)
        else:
            probe_out = (jnp.float32(0.0), jnp.float32(0.0),
                         jnp.float32(0.0))
        return actions, q, h, probe_out

    return jax.jit(quant_step_fn)


def _force_f32(net: NetworkApply) -> NetworkApply:
    """Actors infer on host CPUs, where bf16 is emulated and slower —
    force the f32 compute policy regardless of the learner's (params are
    f32 storage under either policy, so the weight exchange is unchanged;
    the reference's amp is learner-only too, worker.py:309 vs the actors'
    plain CPU model worker.py:509)."""
    if net.config.bf16:
        import dataclasses
        h, w, s = net.obs_hw
        net = NetworkApply(net.action_dim,
                           dataclasses.replace(net.config, bf16=False),
                           s, h, w)
    return net


def feed_quant_probe(stats, probe_interval: int, probe, lanes: int,
                     tick: Optional[int] = None) -> None:
    """Route one forward's probe tuple (dq_max, agree_frac, probed) into
    a QuantStats — the ONE implementation shared by the local policies
    and the policy server's dispatch loop. No sink, a disabled probe,
    or an off-interval ``tick`` (the caller holds it host-side, so
    ``tick % interval`` is known BEFORE any device fetch) skips the
    three scalar fetches entirely."""
    if stats is None or probe_interval <= 0:
        return
    if tick is not None and tick % probe_interval != 0:
        return
    dq, agree, probed = (float(np.asarray(x)) for x in probe)
    if probed > 0.5:
        stats.on_probe(dq, agree, lanes=lanes)


class _QuantPolicyMixin:
    """The quantized-inference plumbing both local policies share
    (ISSUE 14): accept EITHER the published {"f32", "quant", "stamp"}
    bundle or raw params (a direct construction — eval, tests — gets a
    locally-built twin, stamp 0), drive the tick counter the in-graph
    probe keys on, and feed probe results / adopted publish stamps into
    the attached QuantStats. All no-ops at inference_dtype="f32"."""

    def _init_quant(self, net, quant_stats, probe_interval: int):
        self._quant = net.config.inference_dtype != "f32"
        self._quant_stats = quant_stats
        self._probe_interval = int(probe_interval) if self._quant else 0
        self._tick = 0

    def _prepare(self, params):
        """Bundle raw params for the quant forward (identity for a tree
        that already IS the published bundle, and at f32)."""
        if not self._quant or is_quant_bundle(params):
            return params
        return jax.device_get(make_inference_bundle(self.net, params))

    def _note_update(self, params) -> None:
        if self._quant and self._quant_stats is not None \
                and is_quant_bundle(params):
            self._quant_stats.on_stamp(int(np.asarray(params["stamp"])))

    def _feed_probe(self, probe, lanes: int) -> None:
        feed_quant_probe(self._quant_stats, self._probe_interval, probe,
                         lanes, tick=self._tick)


class ActorPolicy(_QuantPolicyMixin):
    def __init__(self, net: NetworkApply, params, epsilon: float, seed: int = 0,
                 copy_updates: bool = True, quant_stats=None,
                 quant_probe_interval: int = 0):
        net = _force_f32(net)
        self.net = net
        self.epsilon = float(epsilon)
        self.action_dim = net.action_dim
        self.rng = np.random.default_rng(seed)
        # local_devices, not devices: under a multihost (jax.distributed)
        # job jax.devices() is the GLOBAL list and index 0 is another
        # process's non-addressable device on every rank but 0
        self._cpu = jax.local_devices(backend="cpu")[0]
        # copy_updates=False: the transport hands over freshly-owned buffers
        # (WeightSubscriber.poll materializes a new copy per poll), so the
        # defensive copy in _pin would be a second full-tree copy per refresh
        self._copy_updates = copy_updates
        self._init_quant(net, quant_stats, quant_probe_interval)
        self.params = self._pin(self._prepare(params), copy=True)
        # the shared (N, 1) acting forward at N=1 — the exact program the
        # batched policy and the policy server run (inputs expand to the
        # same (1, 1, ...) shapes the old scalar closure built, so the
        # compiled computation is unchanged)
        self._fwd = make_forward_fn(net,
                                    probe_interval=self._probe_interval)
        self.reset_state()

    def _step(self, params, stacked, last_action, hidden, feed=True):
        if self._quant:
            action, q, h, probe = self._fwd(
                params, stacked[None], np.asarray(last_action)[None],
                hidden, np.int32(self._tick), np.int32(1))
            if feed:
                self._feed_probe(probe, lanes=1)
        else:
            action, q, h = self._fwd(params, stacked[None],
                                     np.asarray(last_action)[None], hidden)
        return action[0], q[0], h

    def reset_state(self) -> None:
        """Per-episode state reset (ref model.py:86-87, worker.py:584-591)."""
        self.hidden = jax.device_put(
            initial_hidden(1, self.net.config.hidden_dim), self._cpu)
        h, w, s = self.net.obs_hw
        self.stacked = np.zeros((h, w, s), np.float32)
        self.last_action = np.int32(-1)

    def observe_reset(self, obs: np.ndarray) -> None:
        """Fill the frame stack with the initial observation (ref worker.py:587)."""
        self.reset_state()
        self.stacked[:] = (np.asarray(obs, np.float32) / 255.0)[..., None]

    def observe(self, obs: np.ndarray, action: int) -> None:
        """Roll the frame stack and record the taken action (ref worker.py:543-547)."""
        self.stacked = np.roll(self.stacked, -1, axis=-1)
        self.stacked[..., -1] = np.asarray(obs, np.float32) / 255.0
        self.last_action = np.int32(action)

    def _pin(self, params, copy: bool):
        return _pin_params(params, self._cpu, copy)

    def update_params(self, params) -> None:
        self._note_update(params)
        self.params = self._pin(self._prepare(params),
                                copy=self._copy_updates)

    def step(self) -> Tuple[int, np.ndarray, np.ndarray]:
        """Greedy action + Q-values + packed hidden *after* this step; the
        ε-greedy override happens in ``act`` (ref worker.py:535-538)."""
        action, q, self.hidden = self._step(
            self.params, self.stacked, self.last_action, self.hidden)
        self._tick += 1
        return int(action), np.asarray(q), np.asarray(self.hidden[0])

    def act(self) -> Tuple[int, np.ndarray, np.ndarray]:
        action, q, hidden = self.step()
        if self.rng.random() < self.epsilon:
            action = int(self.rng.integers(self.action_dim))
        return action, q, hidden

    def bootstrap_q(self) -> np.ndarray:
        """Q at the current state without advancing the recurrent state —
        the block-boundary bootstrap (ref worker.py:560-563). feed=False:
        the tick doesn't advance here, so an on-interval bootstrap would
        otherwise feed the SAME tick's probe twice."""
        _, q, _ = self._step(self.params, self.stacked, self.last_action,
                             self.hidden, feed=False)
        return np.asarray(q)


class BatchedActorPolicy(_QuantPolicyMixin):
    """N env lanes through ONE jitted (N, 1) forward pass per tick.

    The scalar ActorPolicy pays a full jit dispatch + interpreter round-trip
    per env step; at N lanes the same recurrent forward amortizes both —
    the Podracer batching win (arxiv 2104.06272, and GPU Atari emulation's
    central measurement, arxiv 1907.08467). Per-lane state (rolling frame
    stack, packed LSTM hidden, last action) lives in host numpy so a single
    lane resets without touching the others; the Ape-X ε ladder assigns
    each lane its own ε and its own RNG stream, drawn in the scalar
    policy's exact order (one uniform per step, one integer draw only when
    exploring) so a lane is distributionally identical to the scalar actor
    it replaces.

    Numerics: the batched forward computes the same math as N scalar
    forwards, but XLA:CPU tiles its gemms differently at different batch
    sizes, so Q/hidden can differ from the scalar policy's by ~1 ulp
    (measured ≤ 1.2e-7 at f32); greedy actions are bit-identical whenever
    Q gaps exceed that (parity-tested in tests/test_actor_vector.py).
    """

    def __init__(self, net: NetworkApply, params,
                 epsilons: Sequence[float], seeds: Sequence[int],
                 copy_updates: bool = True, quant_stats=None,
                 quant_probe_interval: int = 0):
        if len(epsilons) != len(seeds):
            raise ValueError(
                f"epsilons ({len(epsilons)}) and seeds ({len(seeds)}) must "
                "have one entry per lane")
        net = _force_f32(net)
        self.net = net
        self.num_lanes = len(epsilons)
        self.epsilons = np.asarray(epsilons, np.float64)
        self.action_dim = net.action_dim
        # per-lane streams: lane i draws exactly like ActorPolicy(seed_i)
        self.rngs = [np.random.default_rng(s) for s in seeds]
        self._cpu = jax.local_devices(backend="cpu")[0]
        self._copy_updates = copy_updates
        self._init_quant(net, quant_stats, quant_probe_interval)
        self.params = self._pin(self._prepare(params), copy=True)
        # the shared acting forward (make_forward_fn) — identical closure
        # to the one this class used to define inline
        self._fwd = make_forward_fn(net,
                                    probe_interval=self._probe_interval)
        self.reset_state()

    def _step(self, params, stacked, last_action, hidden, feed=True):
        if self._quant:
            actions, q, h, probe = self._fwd(
                params, stacked, last_action, hidden,
                np.int32(self._tick), np.int32(self.num_lanes))
            if feed:
                self._feed_probe(probe, lanes=self.num_lanes)
            return actions, q, h
        return self._fwd(params, stacked, last_action, hidden)

    def reset_state(self) -> None:
        """Reset every lane's per-episode state."""
        h, w, s = self.net.obs_hw
        n = self.num_lanes
        # host numpy (not device arrays) so reset_lane mutates one row
        self.hidden = np.zeros((n, 2, self.net.config.hidden_dim), np.float32)
        self.stacked = np.zeros((n, h, w, s), np.float32)
        self.last_action = np.full(n, -1, np.int32)

    def reset_lane(self, lane: int) -> None:
        self.hidden[lane] = 0.0
        self.stacked[lane] = 0.0
        self.last_action[lane] = -1

    def observe_reset_lane(self, lane: int, obs: np.ndarray) -> None:
        """Fill lane's frame stack with its episode-initial observation
        (the scalar policy's observe_reset, per lane)."""
        self.reset_lane(lane)
        self.stacked[lane] = (np.asarray(obs, np.float32) / 255.0)[..., None]

    def observe(self, obs: np.ndarray, actions: np.ndarray) -> None:
        """Roll every lane's frame stack and record the taken actions.
        obs: (N, H, W) uint8; actions: (N,)."""
        self.stacked = np.roll(self.stacked, -1, axis=-1)
        self.stacked[..., -1] = np.asarray(obs, np.float32) / 255.0
        self.last_action = np.asarray(actions, np.int32)

    def _pin(self, params, copy: bool):
        return _pin_params(params, self._cpu, copy)

    def update_params(self, params) -> None:
        self._note_update(params)
        self.params = self._pin(self._prepare(params),
                                copy=self._copy_updates)

    def step(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Greedy actions (N,), Q-values (N, A), and packed hiddens
        (N, 2, hidden) *after* this step; ε-greedy overrides happen in
        ``act``."""
        actions, q, hidden = self._step(
            self.params, self.stacked, self.last_action, self.hidden)
        self._tick += 1
        # np.array, not asarray: device output views are read-only, and
        # reset_lane mutates rows of this buffer in place
        self.hidden = np.array(hidden)
        return np.asarray(actions), np.asarray(q), self.hidden

    def act(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        actions, q, hidden = self.step()
        actions = np.array(actions)          # writable for the ε overrides
        for i, rng in enumerate(self.rngs):
            if rng.random() < self.epsilons[i]:
                actions[i] = int(rng.integers(self.action_dim))
        return actions, q, hidden

    def bootstrap_q(self) -> np.ndarray:
        """(N, A) Q at every lane's current state without advancing any
        recurrent state — the block-boundary bootstrap, one jitted call
        for all lanes (rows of reset lanes are unused by the caller).
        feed=False: the tick doesn't advance here (see ActorPolicy)."""
        _, q, _ = self._step(
            self.params, self.stacked, self.last_action, self.hidden,
            feed=False)
        return np.asarray(q)
