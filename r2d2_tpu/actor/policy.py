"""CPU-jitted actor policy — the reference's ``Network.step`` + ε-greedy
(/root/reference/model.py:67-84, /root/reference/worker.py:535-538) without
torch or Ray.

Actor processes run on host CPUs while the learner owns the TPU, so the
policy pins its params to the CPU backend: JAX placement follows committed
operands, making the same Flax module a CPU program here and a TPU program in
the learner — weight sync is a raw pytree copy, no format conversion
(the reference ships state_dicts through Ray's object store,
/root/reference/worker.py:286-290,572-576).

The policy owns the per-episode recurrent state and rolling frame stack
(ref worker.py:516,526,546-547, model.py:34,86-87).
"""

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from r2d2_tpu.models.network import NetworkApply, initial_hidden


def _pin_params(params, cpu, copy: bool):
    """CPU-resident params, REALLY copied when ``copy``. ``device_put``
    alone is wrong for in-process aliases: to the same device it is a
    no-op, and when the source is the learner's train_state — whose
    buffers are donated by the next fused step — the alias dies with it
    (observed as 'Buffer has been deleted or donated' in a
    single-process CPU run). ONE implementation for both actor policies."""
    if copy:
        params = jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True), params)
    return jax.device_put(params, cpu)


def make_forward_fn(net: NetworkApply):
    """The ONE jitted acting forward (ISSUE 13 satellite): a (N, 1)
    single-step recurrent forward shared by ``ActorPolicy`` (N=1),
    ``BatchedActorPolicy``, and the central policy server
    (serve/server.py) — one definition of the acting forward across
    local and served inference, so parity between them is the identity
    of a single program, not a numerics argument.

    Signature: ``fn(params, stacked_obs, last_action, hidden)`` with
    ``stacked_obs`` (N, H, W, stack) f32 in [0,1], ``last_action`` (N,)
    int32, ``hidden`` (N, 2, hidden) packed — returns (greedy_actions
    (N,), q (N, A), hidden' (N, 2, hidden))."""

    def step_fn(params, stacked_obs, last_action, hidden):
        obs = stacked_obs[:, None]                         # (N, 1, ...)
        la = jax.nn.one_hot(last_action, net.action_dim,
                            dtype=jnp.float32)[:, None]
        q, h = net.module.apply(params, obs, la, hidden)
        return jnp.argmax(q[:, 0], axis=-1), q[:, 0], h

    return jax.jit(step_fn)


def _force_f32(net: NetworkApply) -> NetworkApply:
    """Actors infer on host CPUs, where bf16 is emulated and slower —
    force the f32 compute policy regardless of the learner's (params are
    f32 storage under either policy, so the weight exchange is unchanged;
    the reference's amp is learner-only too, worker.py:309 vs the actors'
    plain CPU model worker.py:509)."""
    if net.config.bf16:
        import dataclasses
        h, w, s = net.obs_hw
        net = NetworkApply(net.action_dim,
                           dataclasses.replace(net.config, bf16=False),
                           s, h, w)
    return net


class ActorPolicy:
    def __init__(self, net: NetworkApply, params, epsilon: float, seed: int = 0,
                 copy_updates: bool = True):
        net = _force_f32(net)
        self.net = net
        self.epsilon = float(epsilon)
        self.action_dim = net.action_dim
        self.rng = np.random.default_rng(seed)
        # local_devices, not devices: under a multihost (jax.distributed)
        # job jax.devices() is the GLOBAL list and index 0 is another
        # process's non-addressable device on every rank but 0
        self._cpu = jax.local_devices(backend="cpu")[0]
        # copy_updates=False: the transport hands over freshly-owned buffers
        # (WeightSubscriber.poll materializes a new copy per poll), so the
        # defensive copy in _pin would be a second full-tree copy per refresh
        self._copy_updates = copy_updates
        self.params = self._pin(params, copy=True)  # initial params: unknown owner
        # the shared (N, 1) acting forward at N=1 — the exact program the
        # batched policy and the policy server run (inputs expand to the
        # same (1, 1, ...) shapes the old scalar closure built, so the
        # compiled computation is unchanged)
        self._fwd = make_forward_fn(net)
        self.reset_state()

    def _step(self, params, stacked, last_action, hidden):
        action, q, h = self._fwd(params, stacked[None],
                                 np.asarray(last_action)[None], hidden)
        return action[0], q[0], h

    def reset_state(self) -> None:
        """Per-episode state reset (ref model.py:86-87, worker.py:584-591)."""
        self.hidden = jax.device_put(
            initial_hidden(1, self.net.config.hidden_dim), self._cpu)
        h, w, s = self.net.obs_hw
        self.stacked = np.zeros((h, w, s), np.float32)
        self.last_action = np.int32(-1)

    def observe_reset(self, obs: np.ndarray) -> None:
        """Fill the frame stack with the initial observation (ref worker.py:587)."""
        self.reset_state()
        self.stacked[:] = (np.asarray(obs, np.float32) / 255.0)[..., None]

    def observe(self, obs: np.ndarray, action: int) -> None:
        """Roll the frame stack and record the taken action (ref worker.py:543-547)."""
        self.stacked = np.roll(self.stacked, -1, axis=-1)
        self.stacked[..., -1] = np.asarray(obs, np.float32) / 255.0
        self.last_action = np.int32(action)

    def _pin(self, params, copy: bool):
        return _pin_params(params, self._cpu, copy)

    def update_params(self, params) -> None:
        self.params = self._pin(params, copy=self._copy_updates)

    def step(self) -> Tuple[int, np.ndarray, np.ndarray]:
        """Greedy action + Q-values + packed hidden *after* this step; the
        ε-greedy override happens in ``act`` (ref worker.py:535-538)."""
        action, q, self.hidden = self._step(
            self.params, self.stacked, self.last_action, self.hidden)
        return int(action), np.asarray(q), np.asarray(self.hidden[0])

    def act(self) -> Tuple[int, np.ndarray, np.ndarray]:
        action, q, hidden = self.step()
        if self.rng.random() < self.epsilon:
            action = int(self.rng.integers(self.action_dim))
        return action, q, hidden

    def bootstrap_q(self) -> np.ndarray:
        """Q at the current state without advancing the recurrent state —
        the block-boundary bootstrap (ref worker.py:560-563)."""
        _, q, _ = self._step(self.params, self.stacked, self.last_action, self.hidden)
        return np.asarray(q)


class BatchedActorPolicy:
    """N env lanes through ONE jitted (N, 1) forward pass per tick.

    The scalar ActorPolicy pays a full jit dispatch + interpreter round-trip
    per env step; at N lanes the same recurrent forward amortizes both —
    the Podracer batching win (arxiv 2104.06272, and GPU Atari emulation's
    central measurement, arxiv 1907.08467). Per-lane state (rolling frame
    stack, packed LSTM hidden, last action) lives in host numpy so a single
    lane resets without touching the others; the Ape-X ε ladder assigns
    each lane its own ε and its own RNG stream, drawn in the scalar
    policy's exact order (one uniform per step, one integer draw only when
    exploring) so a lane is distributionally identical to the scalar actor
    it replaces.

    Numerics: the batched forward computes the same math as N scalar
    forwards, but XLA:CPU tiles its gemms differently at different batch
    sizes, so Q/hidden can differ from the scalar policy's by ~1 ulp
    (measured ≤ 1.2e-7 at f32); greedy actions are bit-identical whenever
    Q gaps exceed that (parity-tested in tests/test_actor_vector.py).
    """

    def __init__(self, net: NetworkApply, params,
                 epsilons: Sequence[float], seeds: Sequence[int],
                 copy_updates: bool = True):
        if len(epsilons) != len(seeds):
            raise ValueError(
                f"epsilons ({len(epsilons)}) and seeds ({len(seeds)}) must "
                "have one entry per lane")
        net = _force_f32(net)
        self.net = net
        self.num_lanes = len(epsilons)
        self.epsilons = np.asarray(epsilons, np.float64)
        self.action_dim = net.action_dim
        # per-lane streams: lane i draws exactly like ActorPolicy(seed_i)
        self.rngs = [np.random.default_rng(s) for s in seeds]
        self._cpu = jax.local_devices(backend="cpu")[0]
        self._copy_updates = copy_updates
        self.params = self._pin(params, copy=True)
        # the shared acting forward (make_forward_fn) — identical closure
        # to the one this class used to define inline
        self._step = make_forward_fn(net)
        self.reset_state()

    def reset_state(self) -> None:
        """Reset every lane's per-episode state."""
        h, w, s = self.net.obs_hw
        n = self.num_lanes
        # host numpy (not device arrays) so reset_lane mutates one row
        self.hidden = np.zeros((n, 2, self.net.config.hidden_dim), np.float32)
        self.stacked = np.zeros((n, h, w, s), np.float32)
        self.last_action = np.full(n, -1, np.int32)

    def reset_lane(self, lane: int) -> None:
        self.hidden[lane] = 0.0
        self.stacked[lane] = 0.0
        self.last_action[lane] = -1

    def observe_reset_lane(self, lane: int, obs: np.ndarray) -> None:
        """Fill lane's frame stack with its episode-initial observation
        (the scalar policy's observe_reset, per lane)."""
        self.reset_lane(lane)
        self.stacked[lane] = (np.asarray(obs, np.float32) / 255.0)[..., None]

    def observe(self, obs: np.ndarray, actions: np.ndarray) -> None:
        """Roll every lane's frame stack and record the taken actions.
        obs: (N, H, W) uint8; actions: (N,)."""
        self.stacked = np.roll(self.stacked, -1, axis=-1)
        self.stacked[..., -1] = np.asarray(obs, np.float32) / 255.0
        self.last_action = np.asarray(actions, np.int32)

    def _pin(self, params, copy: bool):
        return _pin_params(params, self._cpu, copy)

    def update_params(self, params) -> None:
        self.params = self._pin(params, copy=self._copy_updates)

    def step(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Greedy actions (N,), Q-values (N, A), and packed hiddens
        (N, 2, hidden) *after* this step; ε-greedy overrides happen in
        ``act``."""
        actions, q, hidden = self._step(
            self.params, self.stacked, self.last_action, self.hidden)
        # np.array, not asarray: device output views are read-only, and
        # reset_lane mutates rows of this buffer in place
        self.hidden = np.array(hidden)
        return np.asarray(actions), np.asarray(q), self.hidden

    def act(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        actions, q, hidden = self.step()
        actions = np.array(actions)          # writable for the ε overrides
        for i, rng in enumerate(self.rngs):
            if rng.random() < self.epsilons[i]:
                actions[i] = int(rng.integers(self.action_dim))
        return actions, q, hidden

    def bootstrap_q(self) -> np.ndarray:
        """(N, A) Q at every lane's current state without advancing any
        recurrent state — the block-boundary bootstrap, one jitted call
        for all lanes (rows of reset lanes are unused by the caller)."""
        _, q, _ = self._step(
            self.params, self.stacked, self.last_action, self.hidden)
        return np.asarray(q)
