"""Actor-side block assembler — the reference's ``LocalBuffer``
(/root/reference/worker.py:395-492) re-done for fixed-shape device ingestion.

Accumulates up to ``block_length`` transitions, then ``finish()`` computes on
the CPU (cheap, once per 400 steps):

  * n-step discounted returns by convolution (ref worker.py:463-466);
  * per-step effective discount whose tail encodes termination (0) or
    bootstrap-window shortening (gamma^m) so no ``done`` flag is stored
    (ref worker.py:445-456);
  * LSTM hidden snapshots at each sequence's *window start*
    ``seq_start[s] - burn_in[s]`` (stored-state strategy, ref worker.py:459).
    Deliberate divergence: the reference snapshots at ``s*learning``
    unconditionally, which in the FIRST block of an episode (carried burn-in
    < max) hands the learner a state that has already consumed the burn-in
    steps it is about to replay — steps processed twice. Indexing by window
    start is identical in steady state and correct at episode starts;
  * initial priorities from the actor's own (slightly stale) Q-values
    (ref worker.py:475-480);
  * carry-over of the last burn_in(+stack) frames/actions/hiddens so the next
    block's sequences get cross-block burn-in (ref worker.py:482-489).

Output is a fixed-shape ``Block`` (see replay/structs.py): ragged tails are
zero-padded, with zero priority + zero learning_steps marking empty slots.
"""

import math
from typing import Optional

import numpy as np

from r2d2_tpu.ops.priority import mixed_td_errors_ragged
from r2d2_tpu.ops.returns import initial_priorities, n_step_gamma, n_step_return
from r2d2_tpu.replay.structs import Block, ReplaySpec, empty_block_np


class LocalBuffer:
    def __init__(self, spec: ReplaySpec, action_dim: int, gamma: float,
                 priority_eta: float = 0.9, quality_feed=None):
        self.spec = spec
        self.action_dim = action_dim
        self.gamma = gamma
        self.eta = priority_eta
        # optional Q-calibration tap (ISSUE 20): called with the block's
        # (size+1, A) decision-time Q-values and raw per-step rewards —
        # the only place both exist together before shapes are fixed
        self.quality_feed = quality_feed
        self.size = 0

    def __len__(self) -> int:
        return self.size

    def reset(self, init_obs: np.ndarray) -> None:
        """Start a new episode (ref worker.py:414-424). init_obs: (H, W) uint8."""
        spec = self.spec
        # frame_stack duplicate frames so the first stacked obs is well-defined
        self.obs_frames = [np.asarray(init_obs, np.uint8)] * spec.frame_stack
        self.last_actions = [-1]                      # -1 == null action
        self.hiddens = [np.zeros((2, spec.hidden_dim), np.float32)]
        self.actions = []
        self.rewards = []
        self.qvals = []
        self.curr_burn_in = 0
        self.size = 0
        self.sum_reward = 0.0
        self.done = False

    def add(self, action: int, reward: float, next_obs: np.ndarray,
            q_value: np.ndarray, hidden: np.ndarray) -> None:
        """Record one transition (ref worker.py:426-436). ``hidden`` is the
        packed (2, hidden_dim) state *after* this step."""
        self.hiddens.append(np.asarray(hidden, np.float32))
        self.actions.append(int(action))
        self.rewards.append(float(reward))
        self.obs_frames.append(np.asarray(next_obs, np.uint8))
        self.last_actions.append(int(action))
        self.qvals.append(np.asarray(q_value, np.float32).reshape(-1))
        self.sum_reward += float(reward)
        self.size += 1

    def finish(self, last_qval: Optional[np.ndarray] = None) -> Block:
        """Close the block. ``last_qval`` is the bootstrap Q at the next state
        (None ⇒ episode terminated). Returns a fixed-shape Block and keeps the
        burn-in tail for the next block."""
        spec = self.spec
        size = self.size
        assert 0 < size <= spec.block_length
        assert len(self.obs_frames) == spec.frame_stack + self.curr_burn_in + size
        assert len(self.last_actions) == self.curr_burn_in + size + 1

        num_seq = math.ceil(size / spec.learning)

        gammas = n_step_gamma(size, self.gamma, spec.forward, last_qval is not None)
        qvals = list(self.qvals)
        if last_qval is not None:
            qvals.append(np.asarray(last_qval, np.float32).reshape(-1))
        else:
            self.done = True
            qvals.append(np.zeros(self.action_dim, np.float32))
        qval_arr = np.stack(qvals)                       # (size+1, A)
        rewards = np.asarray(self.rewards, np.float64)
        returns = n_step_return(rewards, self.gamma, spec.forward)
        actions = np.asarray(self.actions, np.int32)

        if self.quality_feed is not None:
            # telemetry must never kill an actor
            try:
                self.quality_feed(qval_arr, rewards)
            except Exception:
                pass

        burn_in = np.array(
            [min(s * spec.learning + self.curr_burn_in, spec.burn_in)
             for s in range(num_seq)], np.int32)
        learning = np.array(
            [min(spec.learning, size - s * spec.learning) for s in range(num_seq)],
            np.int32)
        forward = np.array(
            [min(spec.forward, size + 1 - int(learning[: s + 1].sum()))
             for s in range(num_seq)], np.int32)
        assert forward[-1] == 1 and burn_in[0] == self.curr_burn_in

        td = initial_priorities(qval_arr, actions, returns, gammas, spec.forward)
        prios = mixed_td_errors_ragged(td, learning, self.eta)

        # ---- fixed-shape assembly ----
        blk = Block(**empty_block_np(spec))
        blk.num_sequences.fill(num_seq)
        blk.sum_reward.fill(self.sum_reward if self.done else np.nan)
        frames = np.stack(self.obs_frames)               # (stack+burn0+size, H, W)
        blk.obs_row[: frames.shape[0]] = frames
        la = np.asarray(self.last_actions, np.int32)     # (burn0+size+1,)
        blk.last_action_row[: la.shape[0]] = la
        # hidden at each sequence's window start (see module docstring)
        window_starts = [self.curr_burn_in + s * spec.learning - int(burn_in[s])
                         for s in range(num_seq)]
        blk.hidden[:num_seq] = np.stack(
            [self.hiddens[w] for w in window_starts])
        for s in range(num_seq):
            l = int(learning[s])
            lo = s * spec.learning
            blk.action[s, :l] = actions[lo : lo + l]
            blk.reward[s, :l] = returns[lo : lo + l]
            blk.gamma[s, :l] = gammas[lo : lo + l]
            blk.seq_start[s] = self.curr_burn_in + lo
        blk.priority[:num_seq] = prios
        blk.burn_in_steps[:num_seq] = burn_in
        blk.learning_steps[:num_seq] = learning
        blk.forward_steps[:num_seq] = forward

        # ---- burn-in carry to next block (ref worker.py:482-489) ----
        self.obs_frames = self.obs_frames[-spec.frame_stack - spec.burn_in :]
        self.last_actions = self.last_actions[-spec.burn_in - 1 :]
        self.hiddens = self.hiddens[-spec.burn_in - 1 :]
        self.actions.clear()
        self.rewards.clear()
        self.qvals.clear()
        self.curr_burn_in = len(self.last_actions) - 1
        self.size = 0
        return blk
