"""Actor-side components: CPU rollout policy, episode block assembly."""

from r2d2_tpu.actor.local_buffer import LocalBuffer
from r2d2_tpu.actor.policy import ActorPolicy, BatchedActorPolicy

__all__ = ["LocalBuffer", "ActorPolicy", "BatchedActorPolicy"]
