"""Fully on-device acting: batched jitted env + policy + block assembly
fused into ONE compiled program (Podracer "Anakin", arxiv 2104.06272).

The host actor fleet pays, per env step and per lane: a Python interpreter
round-trip, a jit dispatch, numpy frame-stack rolls, and LocalBuffer list
appends — the structural wall PERF.md quantifies (~1.8k env-steps/s for the
whole CPU fleet vs 11k+ learner seq-updates/s/chip). Here one acting
*segment* is a single ``lax.scan`` over ``block_length`` steps of N
batched lanes — pure-JAX env step (envs/jax_env.py), network forward,
ε-greedy, auto-reset — followed by in-graph assembly of one replay Block
per lane, emitted with a leading N axis so ``replay_add_many`` ring-writes
all N blocks in its one donated dispatch. Zero host transfers on the hot
path; the colocated learner's params are read by reference.

Semantics match the host pipeline exactly where they can be compared
(parity-tested in tests/test_anakin.py against LocalBuffer block for
block):

  * timeline layout, burn-in carry across segments, stored hidden states
    at each sequence's window start, n-step returns, and the gamma tail
    encoding termination/bootstrap are the LocalBuffer rules
    (actor/local_buffer.py) re-expressed as gathers;
  * auto-reset follows envs/vector.py: the done step records the TRUE
    terminal observation; the next step starts the new episode with a
    duplicated-initial-frame stack, zero hidden, null last action;
  * episode ends must land on block boundaries (Config validates
    ``episode_len % block_length == 0``), which is exactly the host
    loop's behavior on fixed-length episodes — emit-on-done and
    emit-on-block-boundary coincide;
  * initial priorities: by default a constant stamp
    (``actor.anakin_priority``) instead of the actor's own TD estimates
    — the learner's first sample of each sequence writes the real TD
    priority back. ``actor.anakin_priority="td"`` opts into the host
    path's seeding semantics IN-GRAPH: per-step n-step TD errors from
    the acting policy's own Q-values (recorded along the scan, plus one
    extra bootstrap forward at the segment end — ~1/block_length of the
    scan's cost), mixed per sequence with the learner's eta rule
    (ops/priority.py). Parity with LocalBuffer's
    ``initial_priorities``/``mixed_td_errors_ragged`` is tested.

The dp-sharded composition (``mesh.dp > 1``) lives in
parallel/sharded.py: the same act core runs per shard over its lane
group inside one shard_map program, writing into the shard's local
replay — see ``make_sharded_anakin_act``.
"""

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.structs import Block, ReplaySpec


class ActCarry(struct.PyTreeNode):
    """Per-lane acting state carried across segments (leading N axis).

    ``cur_stack``/``hidden``/``last_action`` are the policy's per-step
    state (the scalar ActorPolicy's stacked/hidden/last_action, batched);
    ``tail_*``/``burn0`` are the LocalBuffer's burn-in carry — the last
    ``stack+burn_in`` frames, ``burn_in+1`` actions and hidden snapshots
    of the timeline, RIGHT-ALIGNED in fixed-size buffers with ``burn0``
    (the host's ``curr_burn_in``) marking how much of each is live."""

    env_state: Any              # vmapped env pytree
    cur_stack: jnp.ndarray      # (N, stack, H, W) uint8, oldest -> newest
    hidden: jnp.ndarray         # (N, 2, hidden) f32 packed
    last_action: jnp.ndarray    # (N,) int32, -1 = null
    tail_frames: jnp.ndarray    # (N, stack + B, H, W) uint8
    tail_la: jnp.ndarray        # (N, B + 1) int32
    tail_hidden: jnp.ndarray    # (N, B + 1, 2, hidden) f32
    burn0: jnp.ndarray          # (N,) int32 — live burn-in length
    ep_return: jnp.ndarray      # (N,) f32 — return of the episode in flight
    key: jax.Array


def init_act_carry(env, spec: ReplaySpec, num_lanes: int,
                   key: jax.Array) -> ActCarry:
    """Fresh-episode carry for every lane: duplicated initial frames in
    the stack (the host policy's observe_reset), zero hidden, null last
    action, zero burn-in — the LocalBuffer.reset state, batched."""
    k_env, k_run = jax.random.split(key)
    env_state, obs = jax.vmap(env.reset)(jax.random.split(k_env, num_lanes))
    obs = jnp.asarray(obs, jnp.uint8)
    n, b, stack = num_lanes, spec.burn_in, spec.frame_stack
    cur_stack = jnp.repeat(obs[:, None], stack, axis=1)
    tail_frames = jnp.zeros(
        (n, stack + b, spec.frame_height, spec.frame_width), jnp.uint8
    ).at[:, b:].set(cur_stack)
    return ActCarry(
        env_state=env_state,
        cur_stack=cur_stack,
        hidden=jnp.zeros((n, 2, spec.hidden_dim), jnp.float32),
        last_action=jnp.full((n,), -1, jnp.int32),
        tail_frames=tail_frames,
        tail_la=jnp.full((n, b + 1), -1, jnp.int32),
        tail_hidden=jnp.zeros((n, b + 1, 2, spec.hidden_dim), jnp.float32),
        burn0=jnp.zeros((n,), jnp.int32),
        ep_return=jnp.zeros((n,), jnp.float32),
        key=k_run,
    )


def _take_rows(buf: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Per-lane gather along the time axis: buf (N, T, ...), idx (N, R)."""
    return jax.vmap(lambda b, i: jnp.take(b, i, axis=0))(buf, idx)


def emit_blocks(spec: ReplaySpec, gamma: float, priority,
                tail_frames: jnp.ndarray, tail_la: jnp.ndarray,
                tail_hidden: jnp.ndarray, burn0: jnp.ndarray,
                obs: jnp.ndarray, actions: jnp.ndarray,
                rewards: jnp.ndarray, hiddens: jnp.ndarray,
                terminal: jnp.ndarray, final_return: jnp.ndarray,
                report_mask: jnp.ndarray, reset_obs: jnp.ndarray,
                weight_version, *, q_seg: jnp.ndarray = None,
                q_boot: jnp.ndarray = None,
                priority_eta: float = 0.9,
                lanes: jnp.ndarray = None) -> Tuple[Block, tuple]:
    """LocalBuffer.finish, re-expressed as array ops over one segment.

    Inputs are lane-major: ``obs``/``actions``/``rewards``/``hiddens``
    are (N, L, ...) per-step records (obs = TRUE next observation incl.
    the terminal frame; hiddens = packed state AFTER each step), the
    ``tail_*``/``burn0`` the previous segment's burn-in carry, and
    ``terminal`` whether the segment's last step ended the episode.
    Returns N fixed-shape Blocks (leading N axis — ``replay_add_many``'s
    stacked-drain layout) plus the next segment's carry tails.

    ``priority`` is either a positive float (constant stamp on every
    sequence) or the string "td": the host assembler's initial-priority
    rule (ops/returns.py initial_priorities + the eta max/mean mix) from
    ``q_seg`` (N, L, A) — the acting policy's Q at each step's state —
    and ``q_boot`` (N, A), the bootstrap Q at the state after the last
    step (zeros where the episode terminated, LocalBuffer.finish(None)).

    ``lanes`` (N,) int32 is each lane's GLOBAL ε-ladder index — the
    block's lane-provenance stamp (ISSUE 10); None stamps -1 = unknown.

    The timeline of block row position ``i`` is ``frames_all[i]`` where
    ``frames_all = tail ++ segment`` — right-aligned tails make the
    offset a single per-lane constant ``B - burn0`` (see ActCarry)."""
    with jax.named_scope("emit_blocks"):
        return _emit_blocks_body(
            spec, gamma, priority, tail_frames, tail_la, tail_hidden, burn0,
            obs, actions, rewards, hiddens, terminal, final_return,
            report_mask, reset_obs, weight_version, q_seg=q_seg,
            q_boot=q_boot, priority_eta=priority_eta, lanes=lanes)


def _emit_blocks_body(spec, gamma, priority, tail_frames, tail_la,
                      tail_hidden, burn0, obs, actions, rewards, hiddens,
                      terminal, final_return, report_mask, reset_obs,
                      weight_version, *, q_seg, q_boot, priority_eta,
                      lanes=None):
    n, l_seg = actions.shape
    b, f, lrn = spec.burn_in, spec.forward, spec.learning
    s, stack = spec.seqs_per_block, spec.frame_stack
    assert l_seg == spec.block_length

    buf_frames = jnp.concatenate([tail_frames, obs], axis=1)
    buf_la = jnp.concatenate([tail_la, actions], axis=1)
    buf_hid = jnp.concatenate([tail_hidden, hiddens], axis=1)

    # --- obs / last-action rows (zero-padded past the live timeline) ---
    r_idx = jnp.arange(spec.obs_row_len, dtype=jnp.int32)
    idx = b - burn0[:, None] + r_idx[None, :]
    valid = r_idx[None, :] < stack + burn0[:, None] + l_seg
    obs_row = jnp.where(
        valid[:, :, None, None],
        _take_rows(buf_frames, jnp.clip(idx, 0, buf_frames.shape[1] - 1)),
        jnp.uint8(0))
    la_idx = jnp.arange(spec.la_row_len, dtype=jnp.int32)
    lidx = b - burn0[:, None] + la_idx[None, :]
    lvalid = la_idx[None, :] < burn0[:, None] + l_seg + 1
    la_row = jnp.where(
        lvalid,
        _take_rows(buf_la, jnp.clip(lidx, 0, buf_la.shape[1] - 1)),
        jnp.int32(-1))

    # --- per-sequence metadata (every slot full: L % learning == 0) ---
    s_arr = jnp.arange(s, dtype=jnp.int32)
    burn_in_s = jnp.minimum(s_arr[None, :] * lrn + burn0[:, None], b)
    # hidden at each sequence's WINDOW START (seq_start - burn_in): in
    # buffer coordinates the episode offset burn0 cancels out
    hid_idx = b + s_arr[None, :] * lrn - burn_in_s
    hidden_sel = _take_rows(buf_hid, hid_idx)

    # --- n-step returns + gamma tail (ops/returns.py, vectorized) ---
    padded = jnp.pad(rewards.astype(jnp.float32), ((0, 0), (0, f - 1)))
    returns = sum(np.float32(gamma ** i) * padded[:, i:i + l_seg]
                  for i in range(f))
    rem = (l_seg - jnp.arange(l_seg, dtype=jnp.int32))       # steps to end
    g_tail = jnp.asarray(gamma, jnp.float32) ** rem.astype(jnp.float32)
    gammas = jnp.where(
        rem[None, :] > f, np.float32(gamma ** f),
        jnp.where(terminal[:, None], jnp.float32(0.0), g_tail[None, :]))

    if isinstance(priority, str):
        # "td": per-step |n-step TD| from the acting policy's own
        # Q-values — initial_priorities vectorized. The bootstrap value
        # for step t is max_a Q at row min(t + mf, L) of the (L+1)-row
        # Q timeline (segment states + the post-segment bootstrap row),
        # which IS the host's [mf : size+1] slice edge-padded to size.
        mf = min(f, l_seg)
        max_rows = jnp.concatenate(
            [q_seg, q_boot[:, None]], axis=1).max(axis=-1)     # (N, L+1)
        boot_idx = jnp.minimum(
            jnp.arange(l_seg, dtype=jnp.int32) + mf, l_seg)
        chosen = jnp.take_along_axis(
            q_seg, actions[:, :, None].astype(jnp.int32), axis=2)[..., 0]
        td = jnp.abs(returns + gammas * max_rows[:, boot_idx] - chosen)
        td_s = td.reshape(n, s, lrn)
        prio = (np.float32(priority_eta) * td_s.max(axis=-1)
                + np.float32(1.0 - priority_eta) * td_s.mean(axis=-1))
    else:
        prio = jnp.full((n, s), priority, jnp.float32)

    forward_s = jnp.minimum(f, l_seg + 1 - (s_arr + 1) * lrn)
    sum_reward = jnp.where(terminal & report_mask,
                           final_return, jnp.float32(jnp.nan))
    blocks = Block(
        obs_row=obs_row.astype(jnp.uint8),
        last_action_row=la_row.astype(jnp.int32),
        hidden=hidden_sel.astype(jnp.float32),
        action=actions.reshape(n, s, lrn).astype(jnp.int32),
        reward=returns.reshape(n, s, lrn).astype(jnp.float32),
        gamma=gammas.reshape(n, s, lrn).astype(jnp.float32),
        priority=prio.astype(jnp.float32),
        burn_in_steps=burn_in_s.astype(jnp.int32),
        learning_steps=jnp.full((n, s), lrn, jnp.int32),
        forward_steps=jnp.broadcast_to(forward_s.astype(jnp.int32), (n, s)),
        seq_start=(burn0[:, None] + s_arr[None, :] * lrn).astype(jnp.int32),
        num_sequences=jnp.full((n,), s, jnp.int32),
        sum_reward=sum_reward.astype(jnp.float32),
        weight_version=jnp.broadcast_to(
            jnp.asarray(weight_version, jnp.int32), (n,)),
        lane=(jnp.broadcast_to(jnp.asarray(lanes, jnp.int32), (n,))
              if lanes is not None
              else jnp.full((n,), -1, jnp.int32)),
    )

    # --- burn-in carry to the next segment (LocalBuffer tail trim; a
    # terminal lane restarts from LocalBuffer.reset instead) ---
    t1 = terminal[:, None]
    t3 = terminal[:, None, None, None]
    reset_tail = jnp.concatenate([
        jnp.zeros_like(tail_frames[:, :b]),
        jnp.repeat(reset_obs[:, None], stack, axis=1)], axis=1)
    new_tails = (
        jnp.where(t3, reset_tail, buf_frames[:, -(stack + b):]),
        jnp.where(t1, jnp.int32(-1),
                  buf_la[:, -(b + 1):]).astype(jnp.int32),
        jnp.where(t3, jnp.float32(0.0), buf_hid[:, -(b + 1):]),
        jnp.where(terminal, jnp.int32(0),
                  jnp.minimum(burn0 + l_seg, b)).astype(jnp.int32),
    )
    return blocks, new_tails


def make_act_core(env, net: NetworkApply, spec: ReplaySpec, *,
                  num_lanes: int, gamma: float, priority,
                  priority_eta: float = 0.9, unroll: int = 1,
                  quant_probe: bool = True) -> Callable:
    """The traceable acting segment, parameterized by per-lane arrays:

        core(params, carry, weight_version, eps, report, lanes=None)
            -> (carry, blocks, stats)

    ``eps`` (num_lanes,) f32 and ``report`` (num_lanes,) bool are traced
    (or constant-folded) inputs rather than baked Python constants, so
    the SAME core serves both compositions: ``make_anakin_act`` closes
    over the full static ladder (the 1x1-mesh path), and the dp-sharded
    program (parallel/sharded.py make_sharded_anakin_act) feeds each
    shard its slice of the GLOBAL ladder inside shard_map. ``lanes``
    (num_lanes,) int32 is the matching slice of GLOBAL lane indices —
    the blocks' lane-provenance stamp (ISSUE 10); None stamps -1.

    ``unroll`` feeds the acting scan's ``lax.scan(..., unroll=)``:
    identical math (parity-tested), >1 trades compile time for fewer
    loop-iteration boundaries. ``unroll=block_length`` is also how the
    cost model (telemetry/costmodel.py) builds its fully-unrolled twin —
    XLA's cost analysis counts a while-loop body once, so only the
    unrolled program's FLOP count reflects executed acting work."""
    td_priority = isinstance(priority, str)
    if td_priority and priority != "td":
        raise ValueError(f"priority must be a positive float or 'td', "
                         f"got {priority!r}")
    action_dim = net.action_dim
    # quantized acting (ISSUE 14): when the config knob is on, ``params``
    # is the published inference bundle and every policy forward inside
    # the scan runs the quantized twin (the same apply variant the shared
    # make_forward_fn uses — flipping the knob switches host actors, the
    # server, and this scan together). At "f32" the branch below is a
    # python-level identity and the traced program is byte-identical.
    quant = net.config.inference_dtype != "f32"
    # the per-segment accuracy probe honors the same kill switch as the
    # host actors' lax.cond probe (telemetry.quant_probe_interval = 0):
    # off, the f32 twin never enters the program at all
    quant_probe = quant and bool(quant_probe)
    if quant:
        from r2d2_tpu.models.network import (f32_reference_module,
                                             quantized_inference_apply)
        # the ONE shared definition of the probe's f32 reference twin
        f32_module = f32_reference_module(net)

        def policy_apply(params, obs, la, hidden):
            return quantized_inference_apply(net, params["quant"], obs, la,
                                             hidden)
    else:
        def policy_apply(params, obs, la, hidden):
            return net.module.apply(params, obs, la, hidden)
    if env.action_dim != action_dim:
        raise ValueError(f"env action_dim {env.action_dim} != network "
                         f"action_dim {action_dim}")
    if env.episode_len % spec.block_length != 0:
        # the same alignment Config validates for actor.on_device; direct
        # callers must honor it too — the scan resets lanes only at the
        # segment boundary, so a mid-segment done would step a finished
        # episode instead of restarting it
        raise ValueError(
            f"env.episode_len {env.episode_len} must be a multiple of "
            f"block_length {spec.block_length}")

    def core(params, carry: ActCarry, weight_version, eps, report,
             lanes=None):
        # ONE speculative reset per segment, not per step: fixed-length
        # episodes end only on segment boundaries (the alignment asserted
        # above), so the auto-reset selection applies exactly once, after
        # the scan. Hoisting it out of the body removes the dominant
        # per-step cost for envs with expensive resets (JaxFakeEnv draws
        # its whole target schedule at reset — ~block_length random ints
        # per lane per step if left inside the scan).
        k_seg, k_run = jax.random.split(carry.key)
        carry = carry.replace(key=k_run)
        with jax.named_scope("env_reset"):
            reset_state, reset_obs = jax.vmap(env.reset)(
                jax.random.split(k_seg, num_lanes))
            reset_obs = jnp.asarray(reset_obs, jnp.uint8)

        def body(c: ActCarry, _):
            key, k_eps, k_expl, k_env = jax.random.split(c.key, 4)
            # policy forward: T=1 window over the normalized frame stack
            # (the BatchedActorPolicy's step, traced into the scan);
            # "act_forward" scopes the ε-greedy selection — the network
            # itself carries its own torso/lstm/head component scopes
            with jax.named_scope("act_forward"):
                stacked = (c.cur_stack.astype(jnp.float32)
                           / np.float32(255.0)).transpose(0, 2, 3, 1)
                la_1h = jax.nn.one_hot(c.last_action, action_dim,
                                       dtype=jnp.float32)
                q, hid = policy_apply(params, stacked[:, None],
                                      la_1h[:, None], c.hidden)
                greedy = jnp.argmax(q[:, 0], axis=-1).astype(jnp.int32)
                explore = jax.random.uniform(k_eps, (num_lanes,)) < eps
                randa = jax.random.randint(k_expl, (num_lanes,), 0,
                                           action_dim, jnp.int32)
                action = jnp.where(explore, randa, greedy)

            with jax.named_scope("env_step"):
                es, obs, reward, done = jax.vmap(env.step)(
                    c.env_state, action, jax.random.split(k_env, num_lanes))
                obs = jnp.asarray(obs, jnp.uint8)
            reward = reward.astype(jnp.float32)
            rolled = jnp.concatenate([c.cur_stack[:, 1:], obs[:, None]],
                                     axis=1)
            c = c.replace(
                env_state=es,
                cur_stack=rolled,
                hidden=hid,
                last_action=action,
                ep_return=c.ep_return + reward,
                key=key)
            y = {"obs": obs, "action": action, "reward": reward,
                 "done": done, "hidden": hid, "ep_ret": c.ep_return}
            if td_priority:
                y["q"] = q[:, 0]     # Q at the state the action was taken in
            return c, y

        out_carry, ys = jax.lax.scan(body, carry, None,
                                     length=spec.block_length,
                                     unroll=unroll)
        # auto-reset where the segment's last step ended the episode: the
        # step's y already recorded the TRUE terminal obs; the carry
        # restarts from envs/vector.py's reset state (duplicated initial
        # frames, zero hidden, null last action)
        terminal = ys["done"][-1]

        q_boot = None
        if td_priority:
            # bootstrap Q at the PRE-reset end-of-segment state — the
            # value the host caller passes to LocalBuffer.finish; zeroed
            # where the episode terminated (finish(None)). One extra T=1
            # forward per segment, ~1/block_length of the scan's cost.
            stacked_b = (out_carry.cur_stack.astype(jnp.float32)
                         / np.float32(255.0)).transpose(0, 2, 3, 1)
            la_b = jax.nn.one_hot(out_carry.last_action, action_dim,
                                  dtype=jnp.float32)
            qb, _ = policy_apply(params, stacked_b[:, None],
                                 la_b[:, None], out_carry.hidden)
            q_boot = jnp.where(terminal[:, None], jnp.float32(0.0),
                               qb[:, 0])

        probe_stats = None
        if quant_probe:
            # accuracy probe (ISSUE 14): once per segment — already
            # ~2/block_length of the scan's cost — run the quantized
            # forward AND the f32 twin on the PRE-reset end-of-segment
            # state and record max |ΔQ| + greedy agreement across the
            # lanes; the host loop feeds these into the record's quant
            # block (the host actors' lax.cond probe, at segment cadence)
            stacked_p = (out_carry.cur_stack.astype(jnp.float32)
                         / np.float32(255.0)).transpose(0, 2, 3, 1)
            la_p = jax.nn.one_hot(out_carry.last_action, action_dim,
                                  dtype=jnp.float32)
            qq, _ = policy_apply(params, stacked_p[:, None],
                                 la_p[:, None], out_carry.hidden)
            qf, _ = f32_module.apply(params["f32"], stacked_p[:, None],
                                     la_p[:, None], out_carry.hidden)
            qq, qf = qq[:, 0], qf[:, 0]
            probe_stats = {
                "quant_dq": jnp.max(jnp.abs(qf - qq)).astype(jnp.float32),
                "quant_agree": jnp.mean(
                    (jnp.argmax(qf, axis=-1)
                     == jnp.argmax(qq, axis=-1)).astype(jnp.float32)),
            }

        def sel(a, b):
            d = terminal.reshape(terminal.shape + (1,) * (a.ndim - 1))
            return jnp.where(d, a, b)

        out_carry = out_carry.replace(
            env_state=jax.tree_util.tree_map(sel, reset_state,
                                             out_carry.env_state),
            cur_stack=sel(jnp.repeat(reset_obs[:, None], spec.frame_stack,
                                     axis=1), out_carry.cur_stack),
            hidden=sel(jnp.zeros_like(out_carry.hidden), out_carry.hidden),
            last_action=sel(jnp.full_like(out_carry.last_action, -1),
                            out_carry.last_action),
            ep_return=sel(jnp.zeros_like(out_carry.ep_return),
                          out_carry.ep_return))
        # lane-major views for assembly
        obs_nl = jnp.swapaxes(ys["obs"], 0, 1)
        act_nl = jnp.swapaxes(ys["action"], 0, 1)
        rew_nl = jnp.swapaxes(ys["reward"], 0, 1)
        hid_nl = jnp.swapaxes(ys["hidden"], 0, 1)
        report_m = jnp.asarray(report)
        blocks, (tf, tl, th, b0) = emit_blocks(
            spec, gamma, priority, carry.tail_frames, carry.tail_la,
            carry.tail_hidden, carry.burn0, obs_nl, act_nl, rew_nl, hid_nl,
            terminal, ys["ep_ret"][-1], report_m,
            reset_obs, weight_version,
            q_seg=(jnp.swapaxes(ys["q"], 0, 1) if td_priority else None),
            q_boot=q_boot, priority_eta=priority_eta, lanes=lanes)
        done_rep = ys["done"] & report_m[None, :]
        stats = {
            "episodes": jnp.sum(ys["done"]).astype(jnp.int32),
            "reported_episodes": jnp.sum(done_rep).astype(jnp.int32),
            "reported_return_sum": jnp.sum(
                jnp.where(done_rep, ys["ep_ret"], 0.0)).astype(jnp.float32),
        }
        if probe_stats is not None:
            stats.update(probe_stats)
        out_carry = out_carry.replace(tail_frames=tf, tail_la=tl,
                                      tail_hidden=th, burn0=b0)
        return out_carry, blocks, stats

    return core


def make_anakin_act(env, net: NetworkApply, spec: ReplaySpec, *,
                    num_lanes: int, epsilons, gamma: float,
                    priority, near_greedy_eps: float,
                    priority_eta: float = 0.9, unroll: int = 1,
                    lane_base: int = 0, quant_probe: bool = True) -> Callable:
    """Build the jitted acting segment (1x1-mesh composition):

        act(params, carry, weight_version) -> (carry, blocks, stats)

    One call = ``block_length`` fused env+policy steps across all
    ``num_lanes`` lanes + in-graph block assembly. ``blocks`` carries a
    leading N axis (feed straight to ``replay_add_many``); ``stats`` are
    small device scalars (episode counts / near-greedy return sums) the
    host fetches lazily at log time. The carry is donated — its large
    frame buffers update in place.

    ``epsilons`` is the per-lane Ape-X ladder; lanes with ε <=
    ``near_greedy_eps`` report episode returns (the host loop's
    filtering rule). Exploration uses jax.random streams — same
    distribution as the host's per-lane numpy generators, different
    draws. ``priority`` is the constant stamp or "td" (see
    emit_blocks); ``priority_eta`` is the learner's max/mean mix.
    ``lane_base`` offsets the blocks' lane-provenance stamps (ISSUE 10)
    when these lanes are one slice of a wider global ladder — how the
    sharded-anakin parity tests reproduce one shard's stamps."""
    eps_list = [float(e) for e in epsilons]
    if len(eps_list) != num_lanes:
        raise ValueError(f"need one epsilon per lane: got {len(eps_list)} "
                         f"for {num_lanes} lanes")
    eps = jnp.asarray(eps_list, jnp.float32)
    report = np.asarray([e <= near_greedy_eps for e in eps_list])
    core = make_act_core(env, net, spec, num_lanes=num_lanes, gamma=gamma,
                         priority=priority, priority_eta=priority_eta,
                         unroll=unroll, quant_probe=quant_probe)

    def act(params, carry: ActCarry, weight_version):
        # the static ladder constant-folds into the program — the dp=1
        # path compiles the same program it did before the core split.
        # Lane stamps are the ladder positions themselves (ISSUE 10).
        return core(params, carry, weight_version, eps, report,
                    lanes=lane_base + jnp.arange(num_lanes, dtype=jnp.int32))

    return jax.jit(act, donate_argnums=1)
