"""Central policy inference service (ISSUE 13): micro-batched policy
server with server-side recurrent state — the SEED-style serving plane.

    transport.py   — the rung ladder: in-proc queue, shm record rings
                     (the shm_feeder discipline), TCP sockets
    state_cache.py — sharded per-client LSTM/frame-stack cache with
                     lease/evict/reconnect + shard-handoff semantics
    server.py      — the micro-batcher + jitted forward loop, ServingStats,
                     admission control (queue-depth brownout)
    router.py      — the serving fleet (ISSUE 17): shard→server routing,
                     ServerFleet with elastic grow/shrink/adopt
    client.py      — RemotePolicy / RemoteBatchedPolicy (the local
                     policies' surface, served)
"""

from r2d2_tpu.serve.client import RemoteBatchedPolicy, RemotePolicy
from r2d2_tpu.serve.router import (RoutingChannel, ServerFleet, ShardMap,
                                   contiguous_partition)
from r2d2_tpu.serve.server import (PolicyServer, ServingStats, collect_batch,
                                   serve_buckets)
from r2d2_tpu.serve.state_cache import MisroutedClient, StateCache
from r2d2_tpu.serve.transport import (InprocChannel, InprocEndpoint,
                                      KIND_BOOTSTRAP, KIND_DISCONNECT,
                                      KIND_STEP, Reply, Request,
                                      STATUS_EXPIRED, STATUS_MISROUTED,
                                      STATUS_OK, STATUS_RETRY,
                                      ServeTimeout, ServeUnavailable,
                                      ShmRecordRing, ShmServeChannel,
                                      ShmServeTransport, SocketChannel,
                                      SocketServerTransport)

__all__ = [
    "RemoteBatchedPolicy", "RemotePolicy", "PolicyServer", "ServingStats",
    "collect_batch", "serve_buckets", "MisroutedClient", "StateCache",
    "RoutingChannel", "ServerFleet", "ShardMap", "contiguous_partition",
    "InprocChannel", "InprocEndpoint", "KIND_BOOTSTRAP", "KIND_DISCONNECT",
    "KIND_STEP", "Reply", "Request", "STATUS_EXPIRED", "STATUS_MISROUTED",
    "STATUS_OK", "STATUS_RETRY", "ServeTimeout", "ServeUnavailable",
    "ShmRecordRing", "ShmServeChannel", "ShmServeTransport", "SocketChannel",
    "SocketServerTransport",
]
