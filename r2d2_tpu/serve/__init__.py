"""Central policy inference service (ISSUE 13): micro-batched policy
server with server-side recurrent state — the SEED-style serving plane.

    transport.py   — the rung ladder: in-proc queue, shm record rings
                     (the shm_feeder discipline), TCP sockets
    state_cache.py — sharded per-client LSTM/frame-stack cache with
                     lease/evict/reconnect semantics
    server.py      — the micro-batcher + jitted forward loop, ServingStats
    client.py      — RemotePolicy / RemoteBatchedPolicy (the local
                     policies' surface, served)
"""

from r2d2_tpu.serve.client import RemoteBatchedPolicy, RemotePolicy
from r2d2_tpu.serve.server import (PolicyServer, ServingStats, collect_batch,
                                   serve_buckets)
from r2d2_tpu.serve.state_cache import StateCache
from r2d2_tpu.serve.transport import (InprocChannel, InprocEndpoint,
                                      KIND_BOOTSTRAP, KIND_DISCONNECT,
                                      KIND_STEP, Reply, Request,
                                      ServeTimeout, ServeUnavailable,
                                      ShmRecordRing, ShmServeChannel,
                                      ShmServeTransport, SocketChannel,
                                      SocketServerTransport)

__all__ = [
    "RemoteBatchedPolicy", "RemotePolicy", "PolicyServer", "ServingStats",
    "collect_batch", "serve_buckets", "StateCache", "InprocChannel",
    "InprocEndpoint", "KIND_BOOTSTRAP", "KIND_DISCONNECT", "KIND_STEP",
    "Reply", "Request", "ServeTimeout", "ServeUnavailable", "ShmRecordRing",
    "ShmServeChannel", "ShmServeTransport", "SocketChannel",
    "SocketServerTransport",
]
