"""Thin serving clients: the local acting policies' surface over a
request/reply channel.

``RemotePolicy`` mirrors ``ActorPolicy`` and ``RemoteBatchedPolicy``
mirrors ``BatchedActorPolicy`` (actor/policy.py) method-for-method, so
the existing run loops (runtime/actor_loop.py) drive served inference
UNCHANGED — ``actor.inference="server"`` swaps the policy object and
nothing else. The division of labor:

  * server-side: frame stack, LSTM hidden, last action (the state
    cache), the batched forward, weight sync;
  * client-side: the ε-greedy draw. The RNG stream and draw order are
    EXACTLY the local policy's (one uniform per step, one integer draw
    only when exploring), which is half of the action-parity guarantee —
    the other half is the shared forward factory the server runs.

State mutations (observe/observe_reset) are buffered and piggybacked
onto the next forward request, so they cost no extra round trip.

Failure handling: a timed-out request backs off on the PR-3
``WorkerHealth`` ladder (breaker disabled — a serving client retries
until ``max_retry_s``, then raises ``ServeUnavailable`` so worker
supervision takes over), reconnects its channel, and RESENDS the
buffered state with the retry. The reply carries the server's adopted
weight publish count, which the client exposes as ``weight_version`` —
the staleness stamp instrument_block_sink records on every block, kept
live in served mode.
"""

import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from r2d2_tpu.serve.transport import (KIND_BOOTSTRAP, KIND_STEP, Reply,
                                      Request, STATUS_OK, STATUS_RETRY,
                                      ServeTimeout, ServeUnavailable)


class _Lane:
    """One client identity's pending-mutation buffer + op/req counters.
    ``op_seq`` advances once per LOGICAL operation (``begin_op``) and is
    stable across retries, which is what lets the server dedup a retried
    op whose first copy was applied but whose reply was lost; ``req_seq``
    advances per ATTEMPT so every wire request has a fresh id."""

    __slots__ = ("client_id", "req_seq", "op_seq", "pending_reset",
                 "pending_obs", "pending_action")

    def __init__(self, client_id: int):
        self.client_id = int(client_id)
        self.req_seq = 0
        self.op_seq = 0
        self.pending_reset: Optional[np.ndarray] = None
        self.pending_obs: Optional[np.ndarray] = None
        self.pending_action: int = -1

    def begin_op(self) -> None:
        self.op_seq += 1

    def build(self, kind: int) -> Request:
        self.req_seq += 1
        # req_id is globally unique per channel exchange: lane id in the
        # high bits so pipelined lanes on one channel never collide
        req = Request(client_id=self.client_id,
                      req_id=(self.client_id << 32) | self.req_seq,
                      kind=kind, op_seq=self.op_seq,
                      t_submit=time.monotonic())
        if self.pending_reset is not None:
            req.reset_obs = self.pending_reset
        elif self.pending_obs is not None:
            req.obs = self.pending_obs
            req.action = self.pending_action
        return req

    def clear(self) -> None:
        self.pending_reset = None
        self.pending_obs = None
        self.pending_action = -1

    def observe_reset(self, obs: np.ndarray) -> None:
        self.pending_reset = np.ascontiguousarray(obs, np.uint8)
        self.pending_obs = None

    def observe(self, obs: np.ndarray, action: int) -> None:
        # an unsent reset wins (reset clears the stack server-side; an
        # observe cannot follow it before the next forward in the local
        # protocol, but be defensive about ordering)
        if self.pending_reset is None:
            self.pending_obs = np.ascontiguousarray(obs, np.uint8)
            self.pending_action = int(action)


class _RetryPolicy:
    """Reconnect backoff on the PR-3 WorkerHealth ladder (one slot, no
    breaker): first retry immediate, then exponential up to the cap."""

    def __init__(self, backoff_base_s: float = 0.25,
                 backoff_max_s: float = 5.0):
        from r2d2_tpu.runtime.feeder import WorkerHealth
        self.health = WorkerHealth(
            1, None, backoff_base_s=backoff_base_s,
            backoff_max_s=backoff_max_s, max_restarts_per_window=0)
        self.failures = 0

    def on_failure(self) -> None:
        self.failures += 1
        self.health.on_failure(0, time.time())

    def wait(self, should_stop: Optional[Callable[[], bool]] = None) -> None:
        while not self.health.respawn_due(0, time.time()):
            if should_stop is not None and should_stop():
                return
            time.sleep(0.05)


class _RemoteBase:
    def __init__(self, channel, action_dim: int, *, stats=None,
                 timeout_s: float = 5.0, max_retry_s: float = 60.0,
                 backoff_base_s: float = 0.25, backoff_max_s: float = 5.0,
                 should_stop: Optional[Callable[[], bool]] = None,
                 trace_every: int = 0):
        self.channel = channel
        # Distributed tracing (ISSUE 19): every Nth exchange attaches a
        # trace dict to its requests (0 = never — the default keeps
        # request objects and wire frames byte-identical to untraced).
        self._trace_every = max(int(trace_every), 0)
        self._exchanges = 0
        self.action_dim = int(action_dim)
        self.stats = stats
        self.timeout_s = timeout_s
        self.max_retry_s = max_retry_s
        self._backoff = (backoff_base_s, backoff_max_s)
        self._retry = _RetryPolicy(backoff_base_s, backoff_max_s)
        # shed (brownout) pacing is its OWN ladder, reset once an
        # exchange completes: the crash ladder accumulates across
        # exchanges (right for a flapping server), but a browning-out
        # server that still makes progress every tick would walk the
        # client to the multi-second cap and collapse goodput far below
        # what the server is actually shedding
        self._shed_retry = _RetryPolicy(backoff_base_s, backoff_max_s)
        self._should_stop = should_stop
        self.weight_version = 0
        self.timeouts = 0
        self.reconnects = 0
        self.shed_retries = 0      # STATUS_RETRY rejections absorbed

    def update_params(self, params) -> None:
        """No-op: the server owns (and syncs) the weights."""

    def _exchange_many(self, lanes: List[_Lane],
                       kind: int) -> List[Reply]:
        """Pipelined request/reply for every lane, with per-lane retries
        on the backoff ladder. Mutation buffers are rebuilt into each
        attempt and cleared only on an OK reply — a request the server
        expired (never applied) keeps its mutation for the resend."""
        t0 = time.monotonic()
        for lane in lanes:
            lane.begin_op()        # one logical op per lane per exchange
        reqs = {lane.client_id: lane.build(kind) for lane in lanes}
        traced = (self._trace_every
                  and self._exchanges % self._trace_every == 0)
        self._exchanges += 1
        if traced:
            from r2d2_tpu.telemetry.tracing import new_request_trace
            for req in reqs.values():
                req.trace = new_request_trace(req.req_id)
        out: dict = {}
        while True:
            pending_lanes = [lane for lane in lanes
                             if lane.client_id not in out]
            if not pending_lanes:
                break
            if traced:
                # the route hop ends here: submit->send is the client's
                # own build/queue time (retries re-stamp, so a resent
                # request's transit hop starts at ITS send)
                now_wall = time.time()
                for lane in pending_lanes:
                    tr = getattr(reqs[lane.client_id], "trace", None)
                    if tr is not None:
                        tr["t_send_wall"] = now_wall
            got = self.channel.request_many(
                [reqs[lane.client_id] for lane in pending_lanes],
                timeout=self.timeout_s)
            now = time.monotonic()
            missing, expired, shed = [], [], []
            for lane in pending_lanes:
                reply = got.get(reqs[lane.client_id].req_id)
                if reply is None:
                    missing.append(lane)
                elif reply.status == STATUS_OK:
                    out[lane.client_id] = reply
                elif reply.status == STATUS_RETRY:
                    shed.append((lane, reply))
                else:
                    expired.append(lane)
            if now - t0 > self.max_retry_s and (missing or expired or shed):
                raise ServeUnavailable(
                    f"policy server unreachable for {now - t0:.1f}s")
            if self._should_stop is not None and self._should_stop() \
                    and (missing or expired or shed):
                raise ServeUnavailable("stopped while retrying")
            # EXPIRED: the server is alive but judged the request stale
            # (its TTL guards against replaying a dead server's backlog)
            # and did NOT apply the op — rebuild with a fresh id and
            # resend, paced on the backoff ladder (no reconnect: the
            # channel is fine) so a persistently-expiring condition
            # cannot busy-spin the core at full request rate
            for lane in expired:
                reqs[lane.client_id] = lane.build(kind)
            # SHED (brownout): admission control rejected at the queue
            # bound — NOT applied. Same rebuild + ladder as EXPIRED, but
            # honor the server's retry-after hint first so a browning-out
            # server is not re-hammered at the ladder's immediate first
            # retry
            if shed:
                self.shed_retries += len(shed)
                for lane, _r in shed:
                    reqs[lane.client_id] = lane.build(kind)
            if shed and not missing:
                pause = max(r.retry_after_ms for _, r in shed) / 1e3
                if pause > 0:
                    time.sleep(min(pause, 1.0))
            if expired and not missing:
                self._retry.on_failure()
                self._retry.wait(self._should_stop)
            elif shed and not missing:
                self._shed_retry.on_failure()
                self._shed_retry.wait(self._should_stop)
            if missing:
                self.timeouts += len(missing)
                if self.stats is not None:
                    for _ in missing:
                        self.stats.on_timeout(self.timeout_s)
                self._retry.on_failure()
                self._retry.wait(self._should_stop)
                self.channel.reconnect()
                self.reconnects += 1
                # fresh req ids for the retries: the old copies may still
                # be processed late; TTL expiry discards them server-side
                for lane in missing:
                    reqs[lane.client_id] = lane.build(kind)
        elapsed = time.monotonic() - t0
        if self._shed_retry.failures:
            # exchange completed: the brownout is admitting us again, so
            # the next shed starts back at the ladder's first rung
            self._shed_retry = _RetryPolicy(*self._backoff)
        if self.stats is not None:
            for _ in lanes:
                self.stats.on_request_latency(elapsed)
        replies = []
        for lane in lanes:
            reply = out[lane.client_id]
            lane.clear()
            self.weight_version = reply.weight_version
            replies.append(reply)
        return replies

    def close(self) -> None:
        try:
            for lane in self._lanes():
                self.channel.disconnect(lane.client_id)
            self.channel.close()
        except Exception:
            pass

    def _lanes(self) -> List[_Lane]:
        raise NotImplementedError


class RemotePolicy(_RemoteBase):
    """``ActorPolicy`` over a serve channel — drop-in for ``run_actor``."""

    def __init__(self, channel, action_dim: int, epsilon: float,
                 seed: int = 0, client_id: int = 0, **kw):
        super().__init__(channel, action_dim, **kw)
        self.epsilon = float(epsilon)
        self.rng = np.random.default_rng(seed)
        self._lane = _Lane(client_id)

    def _lanes(self) -> List[_Lane]:
        return [self._lane]

    def reset_state(self) -> None:
        self._lane.clear()

    def observe_reset(self, obs: np.ndarray) -> None:
        self._lane.observe_reset(obs)

    def observe(self, obs: np.ndarray, action: int) -> None:
        self._lane.observe(obs, action)

    def step(self) -> Tuple[int, np.ndarray, np.ndarray]:
        (reply,) = self._exchange_many([self._lane], KIND_STEP)
        return int(reply.action), np.asarray(reply.q), \
            np.asarray(reply.hidden)

    def act(self) -> Tuple[int, np.ndarray, np.ndarray]:
        action, q, hidden = self.step()
        if self.rng.random() < self.epsilon:
            action = int(self.rng.integers(self.action_dim))
        return action, q, hidden

    def bootstrap_q(self) -> np.ndarray:
        (reply,) = self._exchange_many([self._lane], KIND_BOOTSTRAP)
        return np.asarray(reply.q)


class RemoteBatchedPolicy(_RemoteBase):
    """``BatchedActorPolicy`` over a serve channel — drop-in for
    ``run_vector_actor``. Each lane is its own server-side client
    (``client_base + lane``, the global ε-ladder position), and every
    tick pipelines all N requests before collecting any reply — N lanes
    arriving together are exactly what fills the server's micro-batch."""

    def __init__(self, channel, action_dim: int,
                 epsilons: Sequence[float], seeds: Sequence[int],
                 client_base: int = 0, **kw):
        super().__init__(channel, action_dim, **kw)
        if len(epsilons) != len(seeds):
            raise ValueError(
                f"epsilons ({len(epsilons)}) and seeds ({len(seeds)}) must "
                "have one entry per lane")
        self.num_lanes = len(epsilons)
        self.epsilons = np.asarray(epsilons, np.float64)
        self.rngs = [np.random.default_rng(s) for s in seeds]
        self._lane_list = [_Lane(client_base + i)
                           for i in range(self.num_lanes)]

    def _lanes(self) -> List[_Lane]:
        return self._lane_list

    def reset_state(self) -> None:
        for lane in self._lane_list:
            lane.clear()

    def reset_lane(self, lane: int) -> None:
        self._lane_list[lane].clear()

    def observe_reset_lane(self, lane: int, obs: np.ndarray) -> None:
        self._lane_list[lane].observe_reset(obs)

    def observe(self, obs: np.ndarray, actions: np.ndarray) -> None:
        for i, lane in enumerate(self._lane_list):
            lane.observe(obs[i], int(actions[i]))

    def step(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        replies = self._exchange_many(self._lane_list, KIND_STEP)
        actions = np.asarray([r.action for r in replies], np.int64)
        q = np.stack([np.asarray(r.q) for r in replies])
        hidden = np.stack([np.asarray(r.hidden) for r in replies])
        return actions, q, hidden

    def act(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        actions, q, hidden = self.step()
        actions = np.array(actions)
        for i, rng in enumerate(self.rngs):
            if rng.random() < self.epsilons[i]:
                actions[i] = int(rng.integers(self.action_dim))
        return actions, q, hidden

    def bootstrap_q(self) -> np.ndarray:
        replies = self._exchange_many(self._lane_list, KIND_BOOTSTRAP)
        return np.stack([np.asarray(r.q) for r in replies])
