"""Request/reply transports for the central policy inference service.

The serving plane moves tiny fixed-shape records — one observation frame
up, one (action, Q, hidden) down — at env-step cadence, so the transport
ladder mirrors the experience path's (ISSUE 2) but for request/response:

  * ``InprocEndpoint``   — thread-mode clients in the server's process:
    a plain queue of (Request, reply_fn) pairs. The endpoint OUTLIVES
    server restarts (the chaos drill kills and restarts the server loop
    against the same endpoint), which is what makes in-proc reconnect
    trivial: clients keep submitting, the replacement server drains.
  * ``ShmServeTransport`` / ``ShmServeChannel`` — process-mode clients on
    the same host: the shm_feeder ring discipline (native Vyukov MPMC
    ring, one memcpy per side) applied to fixed-layout request records;
    each client owns a small private REPLY ring whose name rides in every
    request, so the server routes replies without a connection registry.
  * ``SocketServerTransport`` / ``SocketChannel`` — cross-host clients:
    length-prefixed pickle over TCP, one connection per client process,
    replies matched by ``req_id`` so pipelined lanes may complete out of
    order.

All three deliver into ONE server inbox; the micro-batcher
(serve/server.py) neither knows nor cares which rung a request climbed.
"""

import pickle
import queue
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

# Request kinds. STEP advances the client's server-held recurrent state
# (the local policy's ``step``); BOOTSTRAP runs the forward WITHOUT
# advancing it (the block-boundary ``bootstrap_q``); DISCONNECT releases
# the client's state-slot lease (state retained until the lease times
# out, so a reconnect resumes mid-episode).
KIND_STEP, KIND_BOOTSTRAP, KIND_DISCONNECT = 0, 1, 2
# Reply statuses. EXPIRED: judged stale, NOT applied — rebuild + resend.
# MISROUTED: this server does not own the client's state shard (the
# fleet re-sliced); the reply carries the current shard→server map so a
# routing client re-aims before resending. RETRY: admission control shed
# the request at the queue-depth bound (brownout) — NOT applied; back
# off ``retry_after_ms`` on the ladder and resend.
STATUS_OK, STATUS_EXPIRED, STATUS_MISROUTED, STATUS_RETRY = 0, 1, 2, 3

# shm layout: reply-ring names are materialized into a fixed char field
_REPLY_NAME_BYTES = 48


class ServeTimeout(Exception):
    """A request saw no reply inside the client timeout (server busy,
    dead, or mid-restart) — the client backs off and retries."""


class ServeUnavailable(Exception):
    """Retries exhausted (``max_retry_s``): the server stayed unreachable
    long enough that the caller should fail loudly and let worker
    supervision take over (respawn with backoff, breaker)."""


@dataclass
class Request:
    """One client→server message. ``reset_obs``/``obs`` piggyback the
    local policy's state mutations (observe_reset / observe) onto the
    next forward request, so pure state updates never cost a round
    trip."""

    client_id: int
    req_id: int
    kind: int = KIND_STEP
    t_submit: float = 0.0          # client time.monotonic (informational)
    # Logical operation number, incremented ONCE per client step()/
    # bootstrap() — STABLE across retries of the same op (req_id is
    # fresh per attempt). The server dedups on it: a retried op whose
    # first copy was already applied replays the CACHED reply instead
    # of re-advancing state (idempotent RPC). -1 = no dedup.
    op_seq: int = -1
    reset_obs: Optional[np.ndarray] = None   # (H, W) uint8 episode start
    obs: Optional[np.ndarray] = None         # (H, W) uint8 pending frame
    action: int = -1                          # pending observe action
    reply_to: str = ""             # shm: the client's reply-ring name
    t_recv: float = 0.0            # server-side arrival stamp (monotonic —
    #                                the TTL clock: comparable across
    #                                processes AND hosts, unlike t_submit)


@dataclass
class Reply:
    req_id: int
    status: int = STATUS_OK
    action: int = -1
    q: Optional[np.ndarray] = None           # (A,) f32
    hidden: Optional[np.ndarray] = None      # (2, hidden) f32 post-step
    weight_version: int = 0        # server's adopted publish count
    # Admission control (STATUS_RETRY): suggested client pause before the
    # resend — informational; the client's WorkerHealth ladder paces it.
    retry_after_ms: float = 0.0
    # Fleet routing (STATUS_MISROUTED): the replying server's current
    # shard→server assignment, so a RoutingChannel re-aims without a
    # separate map-fetch round trip. None on every other status.
    shard_map: Optional[tuple] = None


# ---------------------------------------------------------------------------
# In-proc rung.


class _ReplyBox:
    __slots__ = ("event", "reply")

    def __init__(self):
        self.event = threading.Event()
        self.reply: Optional[Reply] = None

    def set(self, reply: Reply) -> None:
        self.reply = reply
        self.event.set()


class InprocEndpoint:
    """The server's inbox + the thread-mode client rendezvous. Created
    ONCE by the orchestrating process and shared by every client channel
    and every server incarnation — a server restart attaches to the same
    endpoint, so in-flight requests survive the gap (bounded by the
    request TTL, which the replacement server enforces)."""

    def __init__(self, maxsize: int = 0):
        self.inbox: "queue.Queue[Tuple[Request, Callable]]" = \
            queue.Queue(maxsize)

    def submit(self, req: Request, reply_cb: Callable[[Reply], None]) -> None:
        req.t_recv = time.monotonic()
        trace = getattr(req, "trace", None)
        if trace is not None:
            trace["t_recv_wall"] = time.time()
        self.inbox.put((req, reply_cb))

    def submit_many(self, items) -> None:
        """Bulk submit under ONE lock acquisition: a batched client's N
        pipelined lanes land in the inbox atomically, so the server's
        fill loop sees the whole tick at once instead of N arrivals
        interleaved with its own wakeups (measured as several ms of
        arrival spread per tick on a contended host)."""
        now = time.monotonic()
        wall = None
        for req, _cb in items:
            req.t_recv = now
            trace = getattr(req, "trace", None)
            if trace is not None:
                if wall is None:
                    wall = time.time()
                trace["t_recv_wall"] = wall
        with self.inbox.mutex:
            self.inbox.queue.extend(items)
            self.inbox.not_empty.notify()

    def connect(self) -> "InprocChannel":
        return InprocChannel(self)


class InprocChannel:
    """Thread-mode client channel: submit into the endpoint queue, block
    on a per-request reply box. Pipelining (request_many) submits every
    lane before collecting any reply — the shape that fills the server's
    micro-batch."""

    def __init__(self, endpoint: InprocEndpoint):
        self._ep = endpoint

    def submit(self, req: Request) -> _ReplyBox:
        box = _ReplyBox()
        self._ep.submit(req, box.set)
        return box

    def collect(self, box: _ReplyBox, timeout: float) -> Reply:
        if not box.event.wait(timeout):
            raise ServeTimeout("no reply within timeout")
        return box.reply

    def request(self, req: Request, timeout: float = 5.0) -> Reply:
        return self.collect(self.submit(req), timeout)

    def request_many(self, reqs: List[Request],
                     timeout: float = 5.0) -> Dict[int, Reply]:
        boxes = [_ReplyBox() for _ in reqs]
        self._ep.submit_many(list(zip(reqs, [b.set for b in boxes])))
        deadline = time.monotonic() + timeout
        out: Dict[int, Reply] = {}
        for r, box in zip(reqs, boxes):
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not box.event.wait(remaining):
                continue            # missing replies: the caller retries
            out[r.req_id] = box.reply
        return out

    def reconnect(self) -> None:
        """Nothing to re-dial in-process; the endpoint persists."""

    def disconnect(self, client_id: int) -> None:
        """Best-effort lease release (fire and forget)."""
        self._ep.submit(Request(client_id=client_id, req_id=-1,
                                kind=KIND_DISCONNECT,
                                t_submit=time.monotonic()),
                        lambda _reply: None)

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Socket rung (cross-host): length-prefixed pickle frames.


def _send_frame(sock: socket.socket, obj, lock: threading.Lock) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    with lock:
        sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket):
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


def send_frame(sock: socket.socket, obj, lock: threading.Lock) -> None:
    """Public length-prefixed-pickle frame writer — the serving plane's
    wire discipline, shared with the replay service's socket rung
    (fleet/replay_service.py) so the experience and inference paths
    cannot drift on framing."""
    _send_frame(sock, obj, lock)


def recv_frame(sock: socket.socket):
    """Public frame reader — see :func:`send_frame`."""
    return _recv_frame(sock)


def send_frames(sock: socket.socket, objs, lock: threading.Lock) -> None:
    """Batched frame writer: concatenate the length-prefixed pickles of
    ``objs`` and ship them in ONE sendall under ONE lock acquisition.
    The wire bytes are identical to N send_frame calls — the receiver
    cannot tell the difference — but a windowed producer bursting K
    frames pays one syscall/lock round-trip instead of K
    (fleet/replay_service.py uses this on its pipelined send path)."""
    if not objs:
        return
    parts = []
    for obj in objs:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        parts.append(struct.pack(">I", len(payload)))
        parts.append(payload)
    with lock:
        sock.sendall(b"".join(parts))


class SocketServerTransport:
    """TCP listener feeding the server inbox: one reader thread per
    connection; replies go back over the same connection under a per-
    connection send lock (batched replies from the server thread may
    interleave with nothing else, but the lock keeps frames atomic)."""

    def __init__(self, submit: Callable[[Request, Callable], None],
                 host: str = "127.0.0.1", port: int = 0):
        self._submit = submit
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.25)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._conns: List[socket.socket] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="serve-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            # request/reply at env-step cadence is exactly the small-
            # write/small-read pattern Nagle + delayed ACK turns into a
            # ~40 ms stall per exchange — same fix as the replay service
            # rung (fleet/replay_service.py), which left serving behind
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            threading.Thread(target=self._reader_loop, args=(conn,),
                             daemon=True, name="serve-conn").start()

    def _reader_loop(self, conn: socket.socket) -> None:
        lock = threading.Lock()

        def reply_cb(reply: Reply, _conn=conn, _lock=lock):
            try:
                _send_frame(_conn, reply, _lock)
            except OSError:
                pass               # client went away; lease expiry cleans up

        try:
            while not self._stop.is_set():
                req = _recv_frame(conn)
                self._submit(req, reply_cb)
        except (ConnectionError, OSError, EOFError, pickle.PickleError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._accept_thread.join(timeout=2.0)


class SocketChannel:
    """Client channel over TCP. Lazily (re)dials; replies are matched by
    ``req_id`` (a stash absorbs out-of-order completions when lanes are
    pipelined). Every socket failure surfaces as ``ServeTimeout`` so the
    caller's one retry/backoff path covers dead server, mid-restart, and
    plain slowness alike.

    ISSUE 18: every dial climbs a bounded backoff ladder
    (``connect_retries`` attempts at ``min(base * 2^(n-1), max)``
    spacing) so a client rank may start before its server finishes
    binding; the terminal failure re-raises the real refusal.
    ``eager_connect=True`` dials AT CONSTRUCTION — a misaddressed
    client (RemotePolicy's channel) fails where it is built, not at the
    first request a thousand steps later."""

    def __init__(self, host: str, port: int, dial_timeout: float = 2.0,
                 connect_retries: int = 0, backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0, eager_connect: bool = False):
        self._addr = (host, port)
        self._dial_timeout = dial_timeout
        self.connect_retries = max(int(connect_retries), 0)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._stash: Dict[int, Reply] = {}
        if eager_connect:
            self._ensure()

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            attempt = 0
            while True:
                try:
                    s = socket.create_connection(
                        self._addr, timeout=self._dial_timeout)
                    break
                except OSError:
                    attempt += 1
                    if attempt > self.connect_retries:
                        raise
                    time.sleep(min(
                        self.backoff_base_s * (2 ** (attempt - 1)),
                        self.backoff_max_s))
            s.settimeout(self._dial_timeout)
            # disable Nagle on the client side too: a reply ACK riding a
            # delayed timer stalls the next pipelined send (the replay
            # rung's measured ~40 ms per small exchange)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            self._stash.clear()
        return self._sock

    def _recv_until(self, req_id: int, deadline: float) -> Reply:
        while True:
            if req_id in self._stash:
                return self._stash.pop(req_id)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ServeTimeout("no reply within timeout")
            sock = self._ensure()
            sock.settimeout(remaining)
            reply = _recv_frame(sock)
            if reply.req_id == req_id:
                return reply
            self._stash[reply.req_id] = reply

    def request(self, req: Request, timeout: float = 5.0) -> Reply:
        deadline = time.monotonic() + timeout
        try:
            _send_frame(self._ensure(), req, self._lock)
            return self._recv_until(req.req_id, deadline)
        except (ConnectionError, OSError, EOFError, socket.timeout) as e:
            self.reconnect()
            raise ServeTimeout(str(e)) from None

    def request_many(self, reqs: List[Request],
                     timeout: float = 5.0) -> Dict[int, Reply]:
        deadline = time.monotonic() + timeout
        out: Dict[int, Reply] = {}
        try:
            sock = self._ensure()
            for r in reqs:
                _send_frame(sock, r, self._lock)
            for r in reqs:
                out[r.req_id] = self._recv_until(r.req_id, deadline)
        except (ConnectionError, OSError, EOFError, socket.timeout,
                ServeTimeout):
            # partial results are fine — the caller retries the missing
            self.reconnect()
        return out

    def reconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def disconnect(self, client_id: int) -> None:
        try:
            _send_frame(self._ensure(),
                        Request(client_id=client_id, req_id=-1,
                                kind=KIND_DISCONNECT,
                                t_submit=time.monotonic()), self._lock)
        except (ConnectionError, OSError):
            pass

    def close(self) -> None:
        self.reconnect()


# ---------------------------------------------------------------------------
# Shm rung (same host, cross process): the shm_feeder ring discipline over
# fixed-layout request/reply records.


def request_layout(h: int, w: int,
                   tracing: bool = False) -> List[Tuple[str, tuple, np.dtype]]:
    """(field, shape, dtype) of one request slot — the serve twin of
    shm_feeder.block_layout, derived once so client and server views of
    the ring cannot drift (both sides build it from the same config).

    ``tracing`` (ISSUE 19) appends the two wall-stamp fields a traced
    request's hop decomposition needs; 0.0 = this request untraced. Off,
    the layout — and thus the ring's slot bytes — is exactly the PR-18
    one. Clients never choose: the ring handle they attach to pickles
    its layout, so the server's knob decides for every process."""
    fields = [("client_id", (), np.dtype(np.int64)),
              ("req_id", (), np.dtype(np.int64)),
              ("kind", (), np.dtype(np.int64)),
              ("op_seq", (), np.dtype(np.int64)),
              ("action", (), np.dtype(np.int64)),
              ("flags", (), np.dtype(np.int64)),   # bit0 reset, bit1 observe
              ("t_submit", (), np.dtype(np.float64)),
              ("reply_to", (_REPLY_NAME_BYTES,), np.dtype(np.uint8)),
              ("reset_obs", (h, w), np.dtype(np.uint8)),
              ("obs", (h, w), np.dtype(np.uint8))]
    if tracing:
        fields.extend([("t_submit_wall", (), np.dtype(np.float64)),
                       ("t_send_wall", (), np.dtype(np.float64))])
    return fields


def reply_layout(action_dim: int,
                 hidden_dim: int) -> List[Tuple[str, tuple, np.dtype]]:
    return [("req_id", (), np.dtype(np.int64)),
            ("status", (), np.dtype(np.int64)),
            ("action", (), np.dtype(np.int64)),
            ("weight_version", (), np.dtype(np.int64)),
            ("q", (action_dim,), np.dtype(np.float32)),
            ("hidden", (2, hidden_dim), np.dtype(np.float32))]


@dataclass
class _Field:
    name: str
    shape: tuple
    dtype: np.dtype
    offset: int
    nbytes: int


class ShmRecordRing:
    """Generic fixed-record MPMC ring over the native shm ring
    (native/shm_ring.cc) — ``ShmBlockRing`` with the layout injected
    instead of derived from the Block schema, so the serving plane's
    request and reply records ride the same reserve/commit discipline.
    Picklable by name like the block ring: the creating side owns (and
    unlinks) the region; an unpickled handle attaches lazily."""

    def __init__(self, layout: List[Tuple[str, tuple, np.dtype]],
                 maxsize: int = 64, _attach_name: Optional[str] = None):
        from multiprocessing import shared_memory
        self.layout = [(n, tuple(s), np.dtype(d)) for n, s, d in layout]
        self.capacity = maxsize
        self._fields: List[_Field] = []
        off = 0
        for name, shape, dtype in self.layout:
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            self._fields.append(_Field(name, shape, dtype, off, nbytes))
            off += nbytes
        self.slot_bytes = off
        self._owner = _attach_name is None
        self._shm = None
        self._base = 0
        if self._owner:
            from r2d2_tpu.native import ring_lib
            lib = ring_lib()
            size = int(lib.ring_required_bytes(self.capacity,
                                               self.slot_bytes))
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self._bind()
            lib.ring_init(self._base, self.capacity, self.slot_bytes)
        else:
            self._name = _attach_name

    def __getstate__(self):
        return {"layout": self.layout, "capacity": self.capacity,
                "name": self.name}

    def __setstate__(self, state):
        self.__init__(state["layout"], state["capacity"],
                      _attach_name=state["name"])

    @property
    def name(self) -> str:
        return self._shm.name if self._shm is not None else self._name

    def _bind(self) -> None:
        import ctypes
        self._cbuf = ctypes.c_char.from_buffer(self._shm.buf)
        self._base = ctypes.addressof(self._cbuf)

    def _ensure(self):
        if self._shm is None:
            from multiprocessing import shared_memory

            from r2d2_tpu.runtime.weights import untrack_attached_shm
            self._shm = shared_memory.SharedMemory(name=self._name)
            untrack_attached_shm(self._shm)
            self._bind()
        from r2d2_tpu.native import ring_lib
        return ring_lib()

    def _slot_view(self, lib, pos: int) -> np.ndarray:
        off = int(lib.ring_payload_offset(self._base, pos))
        return np.ndarray((self.slot_bytes,), np.uint8, self._shm.buf, off)

    def put(self, record: Dict[str, np.ndarray],
            timeout: Optional[float] = None) -> None:
        lib = self._ensure()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            pos = int(lib.ring_reserve_push(self._base))
            if pos >= 0:
                break
            if deadline is None or time.monotonic() >= deadline:
                raise queue.Full
            time.sleep(0.0005)
        slot = self._slot_view(lib, pos)
        for f in self._fields:
            src = np.ascontiguousarray(record[f.name], f.dtype)
            slot[f.offset:f.offset + f.nbytes] = \
                src.view(np.uint8).reshape(-1)
        lib.ring_commit_push(self._base, pos)

    def get_nowait(self) -> Optional[Dict[str, np.ndarray]]:
        lib = self._ensure()
        pos = int(lib.ring_reserve_pop(self._base))
        if pos < 0:
            return None
        slot = self._slot_view(lib, pos)
        out = {}
        for f in self._fields:
            raw = slot[f.offset:f.offset + f.nbytes]
            out[f.name] = raw.view(f.dtype).reshape(f.shape).copy()
        lib.ring_commit_pop(self._base, pos)
        return out

    def qsize(self) -> int:
        lib = self._ensure()
        return int(lib.ring_size(self._base))

    def close(self) -> None:
        if self._shm is None:
            return
        self._base = 0
        self._cbuf = None
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
        self._shm = None


def _encode_name(name: str) -> np.ndarray:
    raw = name.encode()[:_REPLY_NAME_BYTES]
    out = np.zeros(_REPLY_NAME_BYTES, np.uint8)
    out[:len(raw)] = np.frombuffer(raw, np.uint8)
    return out


def _decode_name(arr: np.ndarray) -> str:
    raw = bytes(np.asarray(arr, np.uint8))
    return raw.rstrip(b"\x00").decode(errors="replace")


class ShmServeTransport:
    """Server side of the shm rung: owns the shared REQUEST ring, drains
    it into the inbox off-thread, and routes replies into each client's
    private reply ring (attached lazily by the name riding in the
    request)."""

    def __init__(self, submit: Callable[[Request, Callable], None],
                 frame_hw: Tuple[int, int], action_dim: int,
                 hidden_dim: int, request_slots: int = 256,
                 tracing: bool = False):
        h, w = frame_hw
        self.request_ring = ShmRecordRing(request_layout(h, w,
                                                         tracing=tracing),
                                          maxsize=request_slots)
        self._reply_layout = reply_layout(action_dim, hidden_dim)
        self._submit = submit
        self._reply_rings: Dict[str, ShmRecordRing] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._drain_loop,
                                        daemon=True, name="serve-shm-drain")
        self._thread.start()

    def _reply_cb_for(self, name: str) -> Callable[[Reply], None]:
        def cb(reply: Reply, _name=name):
            ring = self._reply_rings.get(_name)
            if ring is None:
                try:
                    ring = ShmRecordRing(self._reply_layout,
                                         _attach_name=_name, maxsize=0)
                    self._reply_rings[_name] = ring
                except (OSError, FileNotFoundError):
                    return          # client's ring is gone — drop
            try:
                ring.put({
                    "req_id": np.int64(reply.req_id),
                    "status": np.int64(reply.status),
                    "action": np.int64(reply.action),
                    "weight_version": np.int64(reply.weight_version),
                    "q": (reply.q if reply.q is not None
                          else np.zeros(self._reply_layout[4][1],
                                        np.float32)),
                    "hidden": (reply.hidden if reply.hidden is not None
                               else np.zeros(self._reply_layout[5][1],
                                             np.float32)),
                }, timeout=1.0)
            except (queue.Full, OSError):
                # a wedged/dead client's ring must not block the server:
                # drop the reply; the client times out and retries
                self._reply_rings.pop(_name, None)
        return cb

    def _drain_loop(self) -> None:
        while not self._stop.is_set():
            rec = None
            try:
                rec = self.request_ring.get_nowait()
            except OSError:
                return
            if rec is None:
                time.sleep(0.0005)
                continue
            flags = int(rec["flags"])
            req = Request(
                client_id=int(rec["client_id"]), req_id=int(rec["req_id"]),
                kind=int(rec["kind"]), op_seq=int(rec["op_seq"]),
                action=int(rec["action"]),
                t_submit=float(rec["t_submit"]),
                reset_obs=rec["reset_obs"] if flags & 1 else None,
                obs=rec["obs"] if flags & 2 else None,
                reply_to=_decode_name(rec["reply_to"]))
            if "t_submit_wall" in rec and float(rec["t_submit_wall"]) > 0:
                trace = {"id": req.req_id,
                         "t_submit_wall": float(rec["t_submit_wall"])}
                if float(rec["t_send_wall"]) > 0:
                    trace["t_send_wall"] = float(rec["t_send_wall"])
                req.trace = trace
            self._submit(req, self._reply_cb_for(req.reply_to))

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self.request_ring.close()
        for ring in self._reply_rings.values():
            ring.close()
        self._reply_rings.clear()


class ShmServeChannel:
    """Client side of the shm rung: pushes requests into the server's
    shared ring (the handle crossed the spawn boundary by name) and polls
    its own private reply ring. Built IN the client process so the reply
    ring is owned (and unlinked) by the process that reads it."""

    def __init__(self, request_ring: ShmRecordRing, action_dim: int,
                 hidden_dim: int, reply_slots: int = 8):
        self._req_ring = request_ring
        self._reply_ring = ShmRecordRing(reply_layout(action_dim, hidden_dim),
                                         maxsize=reply_slots)
        self._name_field = _encode_name(self._reply_ring.name)
        self._stash: Dict[int, Reply] = {}
        # layout self-negotiation: the attached ring carries the server's
        # request_layout (pickled with the handle), so a traced server
        # teaches every client to fill the wall-stamp fields
        self._traced_ring = any(name == "t_submit_wall"
                                for name, _, _ in self._req_ring.layout)

    def _push(self, req: Request) -> None:
        h, w = next(shape for name, shape, _ in self._req_ring.layout
                    if name == "obs")
        zeros = None
        flags = (1 if req.reset_obs is not None else 0) | \
                (2 if req.obs is not None else 0)
        if req.reset_obs is None or req.obs is None:
            zeros = np.zeros((h, w), np.uint8)
        record = {
            "client_id": np.int64(req.client_id),
            "req_id": np.int64(req.req_id),
            "kind": np.int64(req.kind),
            "op_seq": np.int64(req.op_seq),
            "action": np.int64(req.action),
            "flags": np.int64(flags),
            "t_submit": np.float64(req.t_submit),
            "reply_to": self._name_field,
            "reset_obs": (req.reset_obs if req.reset_obs is not None
                          else zeros),
            "obs": req.obs if req.obs is not None else zeros,
        }
        if self._traced_ring:
            # the server's layout says tracing is on: the wall stamps
            # ride the ring (0.0 = this particular request untraced)
            trace = getattr(req, "trace", None) or {}
            record["t_submit_wall"] = np.float64(
                trace.get("t_submit_wall", 0.0))
            record["t_send_wall"] = np.float64(
                trace.get("t_send_wall", 0.0))
        try:
            self._req_ring.put(record, timeout=1.0)
        except queue.Full:
            raise ServeTimeout("request ring full") from None

    def _poll(self, req_id: int, deadline: float) -> Reply:
        while True:
            if req_id in self._stash:
                return self._stash.pop(req_id)
            rec = self._reply_ring.get_nowait()
            if rec is None:
                if time.monotonic() >= deadline:
                    raise ServeTimeout("no reply within timeout")
                time.sleep(0.0005)
                continue
            reply = Reply(req_id=int(rec["req_id"]),
                          status=int(rec["status"]),
                          action=int(rec["action"]),
                          q=rec["q"], hidden=rec["hidden"],
                          weight_version=int(rec["weight_version"]))
            if reply.req_id == req_id:
                return reply
            self._stash[reply.req_id] = reply

    def request(self, req: Request, timeout: float = 5.0) -> Reply:
        deadline = time.monotonic() + timeout
        self._push(req)
        return self._poll(req.req_id, deadline)

    def request_many(self, reqs: List[Request],
                     timeout: float = 5.0) -> Dict[int, Reply]:
        deadline = time.monotonic() + timeout
        out: Dict[int, Reply] = {}
        try:
            for r in reqs:
                self._push(r)
            for r in reqs:
                out[r.req_id] = self._poll(r.req_id, deadline)
        except ServeTimeout:
            pass                    # partial: the caller retries the rest
        return out

    def reconnect(self) -> None:
        """The rings persist across server restarts; nothing to re-dial.
        Drop any stale stashed replies so a fresh exchange starts clean."""
        self._stash.clear()

    def disconnect(self, client_id: int) -> None:
        try:
            self._push(Request(client_id=client_id, req_id=-1,
                               kind=KIND_DISCONNECT,
                               t_submit=time.monotonic()))
        except ServeTimeout:
            pass

    def close(self) -> None:
        self._reply_ring.close()
