"""Central policy inference server: micro-batched forwards over a
server-held state cache (ISSUE 13 tentpole; SEED arXiv 1910.03552,
CPU/GPU placement study arXiv 2012.04210).

One loop owns the resident params and the ``StateCache``; requests from
any transport rung (serve/transport.py) land in one inbox and the
micro-batcher folds them into ONE jitted gather-state → forward →
scatter-state dispatch under a latency deadline:

    dispatch when the batch FILLS (``serve.max_batch``)
    OR the OLDEST pending request ages out (``serve.deadline_ms``)

Batches are padded up to power-of-two buckets (all pre-compiled at start,
the ingest stager's AOT recipe) so fill jitter never retraces. The
forward is the ONE shared acting forward (``actor.policy.make_forward_fn``
— the same program local policies run, which is what makes local-vs-served
action parity exact). Weights sync from the existing weight service
(runtime/weights.py): the server polls its reader on an interval and
stamps every reply with the adopted publish count, so the staleness
accounting (ISSUE 5) stays live for served actors.

Telemetry rides the canonical stages (``serve/enqueue``,
``serve/batch_wait``, ``serve/forward``, ``serve/reply``) plus the
``ServingStats`` aggregator: request-latency and batch-fill histograms on
the shared 64-bucket layout, lease/churn counters — the periodic record's
``serving`` block and the ``serve_*`` alert rules' input.
"""

import logging
import queue
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from r2d2_tpu.serve.state_cache import MisroutedClient
from r2d2_tpu.serve.transport import (KIND_DISCONNECT, KIND_STEP, Reply,
                                      Request, STATUS_EXPIRED,
                                      STATUS_MISROUTED, STATUS_OK,
                                      STATUS_RETRY)


def serve_buckets(max_batch: int) -> List[int]:
    """Power-of-two dispatch widths up to ``max_batch`` (inclusive, as
    its own bucket when not a power of two) — the stager's pow2 recipe,
    so every possible fill compiles at server start, never mid-run."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def collect_batch(inbox: "queue.Queue", first, max_batch: int,
                  deadline_s: float, expected: Optional[int] = None) -> list:
    """The micro-batch fill loop: starting from ``first`` (already
    popped), keep pulling until the batch fills or the OLDEST request
    (= first) ages past ``deadline_s`` from its arrival stamp.

    ``expected`` is the early-dispatch target: the number of clients
    that can possibly have a request outstanding (blocking clients hold
    at most one in flight, so once every connected client is
    represented, waiting out the deadline is pure added latency — the
    measured cost was a full deadline per dispatch at steady state).
    Reaching it stops the WAIT but still drains any immediately-pending
    backlog up to ``max_batch``.

    The deadline bounds WAITING only: when ``first`` is already past it
    (it aged in the queue while the server was mid-forward), the
    immediately-pending backlog is still drained before dispatch —
    otherwise a backlogged server degenerates into batch-1 dispatches
    of stale requests, each one aging the rest of the queue further
    (measured as fill ~1 at 4x the per-request latency under a 4-deep
    backlog). Module-level so the deadline/fill semantics unit-test
    without a server."""
    batch = [first]
    deadline = first[0].t_recv + deadline_s
    target = (max_batch if expected is None
              else min(max_batch, max(int(expected), 1)))
    while len(batch) < max_batch:
        remaining = deadline - time.monotonic()
        if len(batch) >= target or remaining <= 0:
            try:
                batch.append(inbox.get_nowait())
                continue           # burst backlog: take it, don't wait
            except queue.Empty:
                break
        try:
            batch.append(inbox.get(timeout=remaining))
        except queue.Empty:
            break
    return batch


class ServingStats:
    """Thread-safe serving aggregator shared by the server loop and (in
    in-proc mode) the clients: request-latency and batch-fill histograms
    on the shared 64-bucket layout (telemetry/histogram.py — mergeable,
    percentile-summarized), dispatch-cause counters, and client-churn
    accounting. ``interval_block`` consumes the interval (the
    TrainMetrics provider contract); ``disconnects``/``timeouts`` stay
    CUMULATIVE inside the block so the counter-kind alert rules
    (``serve_client_churn``) get their edge semantics."""

    def __init__(self):
        from r2d2_tpu.telemetry.histogram import NBUCKETS
        self._lock = threading.Lock()
        self._nb = NBUCKETS
        self._lat = np.zeros(NBUCKETS, np.int64)
        self._fill = np.zeros(NBUCKETS, np.int64)
        self._fill_sum = 0
        self._batches = 0
        self._full = 0
        self._deadline = 0
        self._starved = 0
        self._requests = 0
        self._replies = 0
        self._expired = 0
        self.timeouts_total = 0
        self.disconnects_total = 0
        self._connects = 0
        self._reconnects = 0
        self._evictions = 0
        self.active_clients = 0
        # -- admission control / routing (ISSUE 17) -- the ``admission``
        # sub-block only exists when the fleet features are ON
        # (admission_enabled), which is what keeps the default
        # single-server record byte-identical (kill-switch contract).
        self.admission_enabled = False
        self._shed = 0
        self._misrouted = 0
        self._adm_lat = np.zeros(NBUCKETS, np.int64)
        # -- distributed tracing (ISSUE 19) -- a ServeTrace is attached
        # when telemetry.tracing_enabled; the ``trace`` sub-block exists
        # only then (same presence gating as ``admission``).
        self.trace = None

    # -- feed points --

    def on_request_latency(self, seconds: float) -> None:
        """One client-visible request completion (or timed-out attempt —
        the wait was experienced either way; during a server outage these
        attempts ARE the latency signal the SLO rule fires on)."""
        from r2d2_tpu.telemetry.histogram import bucket_index
        with self._lock:
            self._lat[bucket_index(seconds)] += 1

    def on_timeout(self, seconds: float) -> None:
        with self._lock:
            self.timeouts_total += 1
        self.on_request_latency(seconds)

    def on_batch(self, fill: int, hit_full: bool, hit_deadline: bool,
                 starved: bool) -> None:
        from r2d2_tpu.telemetry.histogram import value_counts_np
        counts = value_counts_np(np.asarray([fill], np.float64))
        with self._lock:
            self._fill += counts
            self._fill_sum += fill
            self._batches += 1
            self._full += int(hit_full)
            self._deadline += int(hit_deadline)
            self._starved += int(starved)

    def on_requests(self, n: int = 1) -> None:
        with self._lock:
            self._requests += n

    def on_replies(self, n: int = 1) -> None:
        with self._lock:
            self._replies += n

    def on_expired(self, n: int = 1) -> None:
        with self._lock:
            self._expired += n

    def on_shed(self, n: int = 1) -> None:
        """Requests rejected at the queue-depth bound (STATUS_RETRY) —
        they count as requests seen but never reach a dispatch."""
        with self._lock:
            self._shed += n
            self._requests += n

    def on_misrouted(self, n: int = 1) -> None:
        """Requests aimed at a server that does not own the client's
        shard (stale routing map) — bounced with the current map."""
        with self._lock:
            self._misrouted += n

    def on_admitted_latency(self, seconds: float) -> None:
        """Server-side receive→reply latency of an ADMITTED request —
        the brownout contract's p99 (shed requests never enter it)."""
        from r2d2_tpu.telemetry.histogram import bucket_index
        with self._lock:
            self._adm_lat[bucket_index(seconds)] += 1

    def on_clients(self, connects: int = 0, reconnects: int = 0,
                   disconnects: int = 0, evictions: int = 0) -> None:
        with self._lock:
            self._connects += connects
            self._reconnects += reconnects
            self.disconnects_total += disconnects
            self._evictions += evictions

    # -- emission --

    def interval_block(self, deadline_ms: Optional[float] = None,
                       max_batch: Optional[int] = None) -> Optional[dict]:
        """The periodic record's ``serving`` block; consumes the
        interval's histograms/counters. None when the interval saw no
        serving traffic at all (the block is then omitted — consumers
        key on presence, like every other pillar block)."""
        from r2d2_tpu.telemetry.histogram import summarize, value_summary
        with self._lock:
            if (self._requests == 0 and self._batches == 0
                    and not self._lat.any()):
                return None
            lat = summarize(self._lat)
            fill = value_summary(self._fill)
            block = {
                "requests": self._requests,
                "replies": self._replies,
                "expired": self._expired,
                "timeouts": self.timeouts_total,       # cumulative
                "latency": lat,
                "batch": {
                    "count": self._batches,
                    "fill_mean": (round(self._fill_sum / self._batches, 2)
                                  if self._batches else None),
                    "fill_p50": fill.get("p50") if fill else None,
                    "fill_p99": fill.get("p99") if fill else None,
                    "full_frac": (round(self._full / self._batches, 3)
                                  if self._batches else None),
                    "deadline_frac": (round(self._deadline / self._batches,
                                            3) if self._batches else None),
                    "starved_frac": (round(self._starved / self._batches, 3)
                                     if self._batches else None),
                },
                "clients": {
                    "active": self.active_clients,
                    "connects": self._connects,
                    "reconnects": self._reconnects,
                    "disconnects": self.disconnects_total,  # cumulative
                    "evictions": self._evictions,
                },
            }
            if deadline_ms is not None:
                block["deadline_ms"] = deadline_ms
            if max_batch is not None:
                block["max_batch"] = max_batch
            if self.admission_enabled:
                adm = summarize(self._adm_lat)
                block["admission"] = {
                    "shed": self._shed,
                    "shed_frac": (round(self._shed / self._requests, 3)
                                  if self._requests else 0.0),
                    "misrouted": self._misrouted,
                    "admitted_latency": adm,
                }
            if self.trace is not None:
                tr = self.trace.interval_block()
                if tr is not None:
                    block["trace"] = tr
            self._lat[:] = 0
            self._fill[:] = 0
            self._fill_sum = 0
            self._batches = self._full = self._deadline = self._starved = 0
            self._requests = self._replies = self._expired = 0
            self._connects = self._reconnects = self._evictions = 0
            self._shed = self._misrouted = 0
            self._adm_lat[:] = 0
        return block


class PolicyServer:
    """The server loop. Construction pins the params and (by default)
    pre-compiles every dispatch bucket; ``start()`` spawns the loop
    thread; ``stop()`` winds it down. The inbox (an ``InprocEndpoint``)
    and any shm/socket transports are EXTERNAL and survive a server
    restart — the chaos drill's server-kill/restart replaces only this
    object.

    ``weight_poll``/``weight_version``: the weight-service reader pair
    (e.g. ``lambda: store.poll("serve")`` + ``lambda:
    store.reader_version("serve")``, or a ``WeightSubscriber``'s
    ``poll``/``publish_count``). ``client_timed=True`` means in-proc
    clients feed the latency histogram themselves (round-trip including
    queueing and retries); the server then skips its own receive→reply
    observation so requests aren't double-counted."""

    def __init__(self, cfg, net, params, *, endpoint,
                 weight_poll: Optional[Callable] = None,
                 weight_version: Optional[Callable[[], int]] = None,
                 copy_updates: bool = True,
                 stats: Optional[ServingStats] = None,
                 telemetry=None, client_timed: bool = False,
                 warmup: Optional[bool] = None, quant_stats=None,
                 cache=None, server_id: int = 0, shard_map=None,
                 queue_depth_bound: Optional[int] = None,
                 device_index: int = 0, forward_fn=None,
                 local_stats: Optional[ServingStats] = None):
        import jax

        from r2d2_tpu.actor.policy import (_force_f32, _pin_params,
                                           make_forward_fn)
        from r2d2_tpu.models.network import (is_quant_bundle,
                                             make_inference_bundle)
        from r2d2_tpu.telemetry import NULL_TELEMETRY
        sv = cfg.serve
        self.cfg = cfg
        self.max_batch = sv.max_batch
        self.deadline_s = sv.deadline_ms / 1e3
        self.ttl_s = sv.request_ttl_s
        self._weight_poll = weight_poll
        self._weight_version_fn = weight_version
        self._copy_updates = copy_updates
        self.weight_version = int(weight_version()) if weight_version else 0
        self.stats = stats if stats is not None else ServingStats()
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self._client_timed = client_timed
        self.endpoint = endpoint
        # -- serving fleet (ISSUE 17) --
        self.server_id = server_id
        self._shard_map = shard_map
        self.queue_depth_bound = (sv.queue_depth_bound
                                  if queue_depth_bound is None
                                  else queue_depth_bound)
        self.local_stats = local_stats
        # grow/shrink moves whole shard groups between live servers:
        # the fleet holds this lock while detaching/importing, and the
        # dispatch path holds it across every cache mutation
        self.cache_lock = threading.Lock()
        if self.queue_depth_bound > 0 or sv.servers > 1:
            self.stats.admission_enabled = True
            if local_stats is not None:
                local_stats.admission_enabled = True
        # The serving forward runs on THIS process's default backend —
        # the accelerator, when there is one: central placement is the
        # point (SEED). On CPU hosts force f32 like the local policies
        # (bf16 is emulated and slower there). Fleet servers pin by
        # slot (device_index) so N loops spread over N devices.
        devs = jax.local_devices()
        self._device = devs[device_index % len(devs)]
        if self._device.platform != "tpu":
            net = _force_f32(net)
        self.net = net
        self.action_dim = net.action_dim
        # quantized serving (ISSUE 14): the SAME shared forward the
        # local policies build — the config knob flips all of them
        # together. The server's tick is its dispatch counter, so the
        # accuracy probe runs on a real live micro-batch every
        # quant_probe_interval dispatches.
        self._quant = net.config.inference_dtype != "f32"
        self.quant_stats = quant_stats
        self._quant_probe_interval = (cfg.telemetry.quant_probe_interval
                                      if self._quant else 0)
        if forward_fn is not None:
            # bench-only device stand-in (timed-forward emulation):
            # plain f32 signature, no quant probe, no warmup needed
            self._quant = False
            self._quant_probe_interval = 0
            self._fwd = forward_fn
        else:
            self._fwd = make_forward_fn(
                net, probe_interval=self._quant_probe_interval)
        if self._quant and not is_quant_bundle(params):
            # direct construction from raw params (cold start, the
            # standalone CLI): build the twin once here — the weight
            # poll hands over published bundles from then on
            params = jax.device_get(make_inference_bundle(net, params))
        self._params = _pin_params(params, self._device, copy=True)
        h, w, s = net.obs_hw
        self.cache = (cache if cache is not None
                      else StateCacheFromConfig(cfg, (h, w), s,
                                                net.config.hidden_dim,
                                                net.action_dim))
        self.buckets = serve_buckets(self.max_batch)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_weight_poll = 0.0
        self._last_sweep = 0.0
        self.batches_dispatched = 0
        if forward_fn is None and (warmup if warmup is not None
                                   else sv.warmup):
            self._warmup((h, w, s))

    def _warmup(self, obs_hw: Tuple[int, int, int]) -> None:
        """AOT-compile every dispatch bucket at start — a lazy mid-run
        compile would park every connected client for its duration (the
        ingest stager learned this the hard way, PERF.md)."""
        h, w, s = obs_hw
        hd = self.net.config.hidden_dim
        for b in self.buckets:
            args = (self._params,
                    np.zeros((b, h, w, s), np.float32),
                    np.zeros(b, np.int32),
                    np.zeros((b, 2, hd), np.float32))
            if self._quant:
                # tick 0 exercises the probe branch too (lax.cond
                # compiles both; this keeps warm-up honest about it)
                np.asarray(self._fwd(*args, np.int32(0), np.int32(b))[0])
            else:
                np.asarray(self._fwd(*args)[0])

    # -- lifecycle --

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "PolicyServer":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="policy-server")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- the loop --

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    first = self.endpoint.inbox.get(timeout=0.1)
                except queue.Empty:
                    self._idle_work()
                    continue
                batch = collect_batch(self.endpoint.inbox, first,
                                      self.max_batch, self.deadline_s,
                                      expected=self.cache.active_clients)
                self._shed_overflow()
                with self.cache_lock:
                    self._dispatch(batch)
                self._idle_work()
        except Exception:
            logging.getLogger(__name__).exception(
                "policy server loop died; clients will time out and back "
                "off until a replacement starts")

    def _each_stats(self):
        yield self.stats
        if self.local_stats is not None:
            yield self.local_stats

    def _shed_overflow(self) -> None:
        """Admission control (ISSUE 17): after each batch fill, shed the
        OLDEST still-queued requests while the backlog exceeds
        ``queue_depth_bound`` — a fast STATUS_RETRY (with a retry-after
        hint one deadline out) instead of letting batch_wait run away.
        Shedding the queue head converts the worst-latency waits into
        rejects the client backs off on (WorkerHealth ladder).
        Disconnects are never shed: retention bookkeeping must run."""
        bound = self.queue_depth_bound
        if bound <= 0:
            return
        inbox = self.endpoint.inbox
        shed = 0
        while inbox.qsize() > bound:
            try:
                req, cb = inbox.get_nowait()
            except queue.Empty:
                break
            if req.kind == KIND_DISCONNECT:
                now = time.monotonic()
                with self.cache_lock:
                    try:
                        if self.cache.release(req.client_id, now):
                            for st in self._each_stats():
                                st.on_clients(disconnects=1)
                    except MisroutedClient:
                        pass        # unowned client: disconnect is a no-op
                self._safe_reply(cb, Reply(
                    req.req_id, STATUS_OK,
                    weight_version=self.weight_version))
                continue
            shed += 1
            self._safe_reply(cb, Reply(
                req.req_id, STATUS_RETRY,
                retry_after_ms=self.cfg.serve.deadline_ms))
        if shed:
            for st in self._each_stats():
                st.on_shed(shed)

    def _misroute_reply(self, cb: Callable, req: Request) -> None:
        """Stale routing map: bounce with the CURRENT map so the routing
        client re-aims without a discovery round trip."""
        wire = (self._shard_map.to_wire()
                if self._shard_map is not None else None)
        for st in self._each_stats():
            st.on_misrouted(1)
        self._safe_reply(cb, Reply(req.req_id, STATUS_MISROUTED,
                                   shard_map=wire))

    def _idle_work(self) -> None:
        now = time.monotonic()
        sv = self.cfg.serve
        if (self._weight_poll is not None
                and now - self._last_weight_poll >= sv.weight_poll_interval_s):
            self._last_weight_poll = now
            fresh = self._weight_poll()
            if fresh is not None:
                from r2d2_tpu.actor.policy import _pin_params
                from r2d2_tpu.models.network import is_quant_bundle
                if self._quant and self.quant_stats is not None \
                        and is_quant_bundle(fresh):
                    # publish-time-twin staleness stamp: the publication
                    # this twin was quantized at, surfaced in the quant
                    # block alongside the agreement gauge
                    self.quant_stats.on_stamp(
                        int(np.asarray(fresh["stamp"])))
                self._params = _pin_params(fresh, self._device,
                                           copy=self._copy_updates)
                if self._weight_version_fn is not None:
                    self.weight_version = int(self._weight_version_fn())
        if now - self._last_sweep >= 1.0:
            self._last_sweep = now
            with self.cache_lock:
                evicted = self.cache.sweep(now)
                active = self.cache.active_clients
            for st in self._each_stats():
                if evicted:
                    st.on_clients(evictions=evicted)
                st.active_clients = active

    def _dispatch(self, batch: list) -> None:
        now = time.monotonic()
        tele = self.telemetry
        tele.observe("serve/batch_wait", max(now - batch[0][0].t_recv, 0.0))
        for req, _cb in batch:
            tele.observe("serve/enqueue", max(now - req.t_recv, 0.0))
        for st in self._each_stats():
            st.on_requests(len(batch))
        live: List[Tuple[Request, Callable, int]] = []
        ev0 = self.cache.evictions
        co0, rc0 = self.cache.connects, self.cache.reconnects
        for req, cb in batch:
            if req.kind == KIND_DISCONNECT:
                try:
                    released = self.cache.release(req.client_id, now)
                except MisroutedClient:
                    self._misroute_reply(cb, req)
                    continue
                if released:
                    for st in self._each_stats():
                        st.on_clients(disconnects=1)
                self._safe_reply(cb, Reply(req.req_id, STATUS_OK,
                                           weight_version=self.weight_version))
                continue
            if self.ttl_s > 0 and now - req.t_recv > self.ttl_s:
                # stale backlog (e.g. queued against a dead server):
                # drop WITHOUT touching state — the client has long
                # since timed out and will resend current state. Aged on
                # the SERVER-side arrival stamp (t_recv), which is
                # comparable across processes and hosts; the client's
                # t_submit monotonic clock is neither.
                for st in self._each_stats():
                    st.on_expired()
                self._safe_reply(cb, Reply(req.req_id, STATUS_EXPIRED))
                continue
            try:
                slot, fresh = self.cache.lease(req.client_id, now)
            except MisroutedClient:
                self._misroute_reply(cb, req)
                continue
            if fresh:
                # unknown client (first contact, post-eviction, or a
                # server that restarted and lost the cache): start from
                # the episode-reset state — the local policy's
                # reset_state semantics
                self.cache.reset_slot(slot)
                self.cache.reset_op(slot)
            elif req.op_seq >= 0:
                last = int(self.cache.op_seq[slot])
                if req.op_seq == last:
                    # duplicate of an ALREADY-APPLIED op (the client
                    # timed out and retried, but the first copy was
                    # processed and its reply lost): replay the cached
                    # result — state advanced exactly once per logical
                    # step, no matter how many copies arrive
                    action, q = self.cache.cached_reply(slot)
                    self._safe_reply(cb, Reply(
                        req.req_id, STATUS_OK, action, q,
                        self.cache.hidden[slot].copy(),
                        weight_version=self.weight_version))
                    for st in self._each_stats():
                        st.on_replies(1)
                    continue
                if req.op_seq < last:
                    # older than the applied horizon: a stale copy the
                    # client has already moved past — never re-apply
                    for st in self._each_stats():
                        st.on_expired()
                    self._safe_reply(cb, Reply(req.req_id, STATUS_EXPIRED))
                    continue
            if req.reset_obs is not None:
                self.cache.reset_slot(slot, req.reset_obs)
            elif req.obs is not None:
                self.cache.observe(slot, req.obs, req.action)
            live.append((req, cb, slot))
        for st in self._each_stats():
            st.on_clients(
                connects=self.cache.connects - co0,
                reconnects=self.cache.reconnects - rc0,
                evictions=self.cache.evictions - ev0)
            st.active_clients = self.cache.active_clients
        if not live:
            return
        # distributed tracing (ISSUE 19): close each traced request's
        # route/transit hops and record its micro-batch fill wait (the
        # server's own monotonic clock — exact); the batch's forward and
        # reply hops follow below iff any request was traced
        traced_any = False
        trace_sinks = [st.trace for st in self._each_stats()
                       if st.trace is not None]
        if trace_sinks:
            for req, _cb, _slot in live:
                tr = getattr(req, "trace", None)
                if tr is not None:
                    traced_any = True
                    qw = max(now - req.t_recv, 0.0)
                    for sink in trace_sinks:
                        sink.on_request(tr, qw)
        fill = len(live)
        stacked, last_action, hidden = self.cache.gather(
            [slot for _, _, slot in live])
        bucket = next(b for b in self.buckets if b >= fill)
        if bucket > fill:
            pad = bucket - fill
            stacked = np.concatenate(
                [stacked, np.zeros((pad,) + stacked.shape[1:],
                                   stacked.dtype)])
            last_action = np.concatenate(
                [last_action, np.full(pad, -1, last_action.dtype)])
            hidden = np.concatenate(
                [hidden, np.zeros((pad,) + hidden.shape[1:], hidden.dtype)])
        t0 = time.perf_counter()
        if self._quant:
            from r2d2_tpu.actor.policy import feed_quant_probe
            # live=fill: the probe masks the bucket's padding rows out
            # of the agreement/|dQ| signal
            actions, q, h, probe = self._fwd(
                self._params, stacked, last_action, hidden,
                np.int32(self.batches_dispatched), np.int32(fill))
            feed_quant_probe(self.quant_stats, self._quant_probe_interval,
                             probe, lanes=fill,
                             tick=self.batches_dispatched)
        else:
            actions, q, h = self._fwd(self._params, stacked, last_action,
                                      hidden)
        actions = np.asarray(actions)
        q = np.asarray(q)
        h = np.asarray(h)
        t1 = time.perf_counter()
        tele.observe("serve/forward", t1 - t0)
        if tele.spans.enabled:
            # the serving plane's track in the cross-process Perfetto
            # merge (ISSUE 19): one span per dispatched micro-batch
            wall = time.time()
            tele.record_span("serve/forward", wall - (t1 - t0), wall,
                             {"fill": fill})
        reply_t = time.monotonic()
        for i, (req, cb, slot) in enumerate(live):
            if req.kind == KIND_STEP:
                self.cache.write_hidden(slot, h[i])
            if req.op_seq >= 0:
                self.cache.record_op(slot, req.op_seq, int(actions[i]),
                                     q[i])
            self._safe_reply(cb, Reply(
                req.req_id, STATUS_OK, int(actions[i]), q[i].copy(),
                h[i].copy(), weight_version=self.weight_version))
            lat = max(reply_t - req.t_recv, 0.0)
            for st in self._each_stats():
                if not self._client_timed:
                    st.on_request_latency(lat)
                if st.admission_enabled:
                    # the brownout contract's p99: server-side
                    # receive→reply of ADMITTED requests only
                    st.on_admitted_latency(lat)
        reply_s = time.perf_counter() - t1
        tele.observe("serve/reply", reply_s)
        if tele.spans.enabled:
            wall = time.time()
            tele.record_span("serve/reply", wall - reply_s, wall)
        if traced_any:
            for sink in trace_sinks:
                sink.on_batch(t1 - t0, reply_s)
        for st in self._each_stats():
            st.on_replies(fill)
            st.on_batch(
                fill,
                hit_full=len(batch) >= self.max_batch,
                hit_deadline=(len(batch) < self.max_batch
                              and now - batch[0][0].t_recv >= self.deadline_s),
                starved=(fill == 1 and self.cache.active_clients > 1))
        self.batches_dispatched += 1

    @staticmethod
    def _safe_reply(cb: Callable, reply: Reply) -> None:
        try:
            cb(reply)
        except Exception:
            pass                    # a dead client must not kill the server


def StateCacheFromConfig(cfg, frame_hw, frame_stack, hidden_dim,
                         action_dim: int = 1):
    from r2d2_tpu.serve.state_cache import StateCache
    sv = cfg.serve
    return StateCache(sv.state_slots, sv.state_shards, frame_hw,
                      frame_stack, hidden_dim,
                      lease_timeout_s=sv.lease_timeout_s,
                      action_dim=action_dim)
