"""Scale-out policy serving: N server loops behind a client-side router
(ISSUE 17 tentpole; ROADMAP item 2a–c).

The state cache was ALREADY sharded by client hash into independent
shard groups (serve/state_cache.py) — this module puts those groups
behind N micro-batching server loops:

  * ``ShardMap``        — the versioned shard→server assignment every
    router and server shares. Contiguous slices (``contiguous_partition``)
    so a re-slice moves the fewest groups.
  * ``RoutingChannel``  — the client side: one sub-channel per server
    slot, requests routed by ``client_id % total_shards → server``; a
    request NEVER crosses servers, so the PR-12 parity contract (served
    ≡ local at equal seeds/ε) holds per server. STATUS_MISROUTED replies
    carry the current map — the channel re-aims and resends once before
    surfacing a miss to the retry ladder.
  * ``ServerFleet``     — the server side: max_servers in-proc endpoints
    created UP-FRONT (addresses are static; growth is a map change, not
    address discovery), PolicyServer loops over per-server cache slices,
    PR-14 membership leases for the slot board, ``grow_server`` /
    ``shrink_server`` re-slicing with lease-handoff of whole shard
    groups (state + op-dedup bookkeeping move together, so a mid-kill
    re-route stays bit-identical), and a bouncer draining parked
    endpoints with MISROUTED+map so stale routers self-heal. ``supervise``
    adopts a dead server's orphaned shards onto the survivors — the
    kill-one-of-N chaos drill's recovery path.

Admission control (the ``serve.queue_depth_bound`` brownout) lives in
the server loop itself (serve/server.py ``_shed_overflow``); this module
only routes its STATUS_RETRY verdicts back to the ladder.
"""

import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from r2d2_tpu.serve.server import PolicyServer, ServingStats
from r2d2_tpu.serve.state_cache import StateCache
from r2d2_tpu.serve.transport import (InprocEndpoint, Reply, Request,
                                      STATUS_MISROUTED)


def contiguous_partition(total_shards: int,
                         servers: Sequence[int]) -> Dict[int, List[int]]:
    """Assign ``total_shards`` global shard-group ids to the given server
    slots as contiguous slices (np.array_split semantics: sizes differ by
    at most one, earlier servers take the remainder). Contiguity is the
    re-slice-cost property: growing N→N+1 moves only boundary groups."""
    servers = list(servers)
    if not servers:
        raise ValueError("no servers to partition shards over")
    if total_shards < len(servers):
        raise ValueError(
            f"{total_shards} shard groups cannot cover {len(servers)} "
            "servers (every server needs >= 1)")
    pieces = np.array_split(np.arange(total_shards), len(servers))
    return {slot: [int(g) for g in piece]
            for slot, piece in zip(servers, pieces)}


class ShardMap:
    """Versioned shard→server assignment, shared by every router and
    server in one process and shipped over the wire as
    ``(version, assign_tuple)`` (the STATUS_MISROUTED payload). Updates
    only ever move FORWARD (apply_wire ignores stale versions), so a
    late bounce from a pre-re-slice server cannot roll a router back."""

    def __init__(self, total_shards: int,
                 assign: Optional[Sequence[int]] = None):
        self.total_shards = total_shards
        self._lock = threading.Lock()
        self._assign = tuple(int(s) for s in (
            assign if assign is not None else [0] * total_shards))
        if len(self._assign) != total_shards:
            raise ValueError(
                f"assignment covers {len(self._assign)} shards, expected "
                f"{total_shards}")
        self.version = 1

    def server_for(self, client_id: int) -> int:
        return self._assign[int(client_id) % self.total_shards]

    def shard_server(self, shard: int) -> int:
        return self._assign[int(shard)]

    def assignment(self) -> Tuple[int, ...]:
        return self._assign

    def servers(self) -> List[int]:
        """Distinct server slots in the current assignment."""
        return sorted(set(self._assign))

    def shards_of(self, slot: int) -> List[int]:
        return [g for g, s in enumerate(self._assign) if s == int(slot)]

    def update(self, assign: Sequence[int]) -> int:
        with self._lock:
            assign = tuple(int(s) for s in assign)
            if len(assign) != self.total_shards:
                raise ValueError(
                    f"assignment covers {len(assign)} shards, expected "
                    f"{self.total_shards}")
            self._assign = assign
            self.version += 1
            return self.version

    def to_wire(self) -> tuple:
        with self._lock:
            return (self.version, self._assign)

    def apply_wire(self, wire: Optional[tuple]) -> bool:
        """Adopt a wire map if it is NEWER than ours; returns whether
        anything changed (stale and None wires are ignored)."""
        if not wire:
            return False
        version, assign = int(wire[0]), tuple(int(s) for s in wire[1])
        with self._lock:
            if version <= self.version or len(assign) != self.total_shards:
                return False
            self._assign = assign
            self.version = version
            return True


class RoutingChannel:
    """Client-side router over per-server sub-channels. Implements the
    channel API the remote policies consume (``request_many`` /
    ``request`` / ``reconnect`` / ``disconnect`` / ``close``) so
    ``RemotePolicy``/``RemoteBatchedPolicy`` route transparently.

    In-proc sub-channels are driven TWO-PHASE: every lane submits before
    any reply is collected, so N server loops fill their micro-batches
    concurrently instead of serializing behind the first server's
    dispatch. Socket sub-channels use their fused ``request_many``
    (replies buffer in the kernel while later servers are drained).

    A STATUS_MISROUTED reply applies the carried map and re-sends that
    request ONCE within the call; anything still unresolved surfaces as
    a missing reply and rides the caller's retry ladder."""

    def __init__(self, channels: Dict[int, object], shard_map: ShardMap):
        self._channels = dict(channels)
        self.shard_map = shard_map
        self.reroutes = 0           # misroute bounces absorbed (tests)
        self._mirror = None         # shadow-scoring tap (ISSUE 20)

    def set_mirror(self, mirror) -> None:
        """Install a shadow tap: ``mirror(reqs, replies)`` is called with
        every request batch AND the live replies dict after each
        ``request_many`` — the ShadowScorer's intake. The tap must treat
        both as read-only; it enqueues copies and returns immediately
        (never blocks the live path). ``None`` uninstalls."""
        self._mirror = mirror

    def _route(self, reqs: Sequence[Request]) -> Dict[int, List[Request]]:
        by_server: Dict[int, List[Request]] = {}
        for r in reqs:
            slot = self.shard_map.server_for(r.client_id)
            by_server.setdefault(slot, []).append(r)
        return by_server

    def _exchange_round(self, by_server: Dict[int, List[Request]],
                        deadline: float) -> Dict[int, Reply]:
        out: Dict[int, Reply] = {}
        inproc: List[Tuple[object, List[Request], list]] = []
        socketed: List[Tuple[object, List[Request]]] = []
        for slot, reqs in by_server.items():
            ch = self._channels.get(slot)
            if ch is None:
                continue            # stale map names an unknown slot
            if hasattr(ch, "submit"):
                inproc.append((ch, reqs, [ch.submit(r) for r in reqs]))
            else:
                socketed.append((ch, reqs))
        for ch, reqs in socketed:
            remaining = max(deadline - time.monotonic(), 0.001)
            out.update(ch.request_many(reqs, timeout=remaining))
        for ch, reqs, boxes in inproc:
            for r, box in zip(reqs, boxes):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not box.event.wait(remaining):
                    continue        # missing: the caller's ladder retries
                out[r.req_id] = box.reply
        return out

    def request_many(self, reqs: List[Request],
                     timeout: float = 5.0) -> Dict[int, Reply]:
        deadline = time.monotonic() + timeout
        out = self._exchange_round(self._route(reqs), deadline)
        bounced = [r for r in reqs
                   if out.get(r.req_id) is not None
                   and out[r.req_id].status == STATUS_MISROUTED]
        if bounced:
            changed = False
            for r in bounced:
                changed |= self.shard_map.apply_wire(out[r.req_id].shard_map)
                del out[r.req_id]
            self.reroutes += len(bounced)
            if changed:
                # one in-call re-aim on the adopted map; a second bounce
                # (map still stale) is left missing for the retry ladder
                out.update(self._exchange_round(self._route(bounced),
                                                deadline))
                for r in bounced:
                    rep = out.get(r.req_id)
                    if rep is not None and rep.status == STATUS_MISROUTED:
                        self.shard_map.apply_wire(rep.shard_map)
                        del out[r.req_id]
        if self._mirror is not None:
            # shadow scoring never perturbs the live path
            try:
                self._mirror(reqs, out)
            except Exception:
                pass
        return out

    def request(self, req: Request, timeout: float = 5.0) -> Reply:
        from r2d2_tpu.serve.transport import ServeTimeout
        got = self.request_many([req], timeout=timeout)
        reply = got.get(req.req_id)
        if reply is None:
            raise ServeTimeout("no reply within timeout")
        return reply

    def reconnect(self) -> None:
        for ch in self._channels.values():
            ch.reconnect()

    def disconnect(self, client_id: int) -> None:
        ch = self._channels.get(self.shard_map.server_for(client_id))
        if ch is not None:
            ch.disconnect(client_id)

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()


class ServerFleet:
    """N PolicyServer loops over per-server state-cache slices, with
    PR-14 membership leases as the slot board and lease-handoff re-slices
    (grow/shrink/adopt). Thread-mode owner: endpoints are in-proc; the
    socket rungs (cli/serve.py, process actors) attach one
    ``SocketServerTransport`` per endpoint and ship the address table +
    assignment as the serve spec.

    All ``max_servers`` endpoints exist from construction — a parked
    slot's endpoint keeps accepting (the bouncer drains it with
    MISROUTED + the current map), so growth never changes an address."""

    def __init__(self, cfg, net, params, *, stats: ServingStats,
                 telemetry=None, client_timed: bool = False,
                 weight_poll_factory: Optional[Callable[[int], Optional[
                     Callable]]] = None,
                 weight_version: Optional[Callable[[], int]] = None,
                 weight_version_factory: Optional[Callable[[int], Optional[
                     Callable]]] = None,
                 copy_updates: bool = True, quant_stats=None,
                 warmup: Optional[bool] = None,
                 forward_fn_factory: Optional[Callable[[int], object]] = None):
        from r2d2_tpu.fleet.membership import FleetMembership
        sv = cfg.serve
        self.cfg = cfg
        self.net = net
        self._params = params
        self.stats = stats
        self.stats.admission_enabled = True
        self.telemetry = telemetry
        self._client_timed = client_timed
        self._weight_poll_factory = weight_poll_factory
        self._weight_version = weight_version
        self._weight_version_factory = weight_version_factory
        self._copy_updates = copy_updates
        self.quant_stats = quant_stats
        self._warmup = warmup
        self._fwd_factory = forward_fn_factory
        self.total_shards = sv.state_shards
        self.per_shard_slots = sv.state_slots // sv.state_shards
        self.max_servers = sv.max_servers or sv.servers
        self.membership = FleetMembership(self.max_servers,
                                          initial_active=sv.servers)
        self.endpoints = [InprocEndpoint() for _ in range(self.max_servers)]
        active = self.membership.active_slots()
        parts = contiguous_partition(self.total_shards, active)
        assign = [0] * self.total_shards
        for slot, groups in parts.items():
            for g in groups:
                assign[g] = slot
        self.shard_map = ShardMap(self.total_shards, assign)
        self.servers: Dict[int, PolicyServer] = {}
        self.local_stats: Dict[int, ServingStats] = {}
        self.adoptions = 0          # shard groups adopted off dead servers
        self._lock = threading.RLock()
        self._stop = threading.Event()
        for slot in active:
            self._start_server(slot, parts[slot])
        self._bouncer = threading.Thread(target=self._bounce_loop,
                                         daemon=True, name="serve-bouncer")
        self._bouncer.start()

    # -- server lifecycle --

    def _build_cache(self, owned: List[int]) -> StateCache:
        sv = self.cfg.serve
        h, w, s = self.net.obs_hw
        return StateCache(self.per_shard_slots * len(owned), len(owned),
                          (h, w), s, self.net.config.hidden_dim,
                          lease_timeout_s=sv.lease_timeout_s,
                          action_dim=self.net.action_dim,
                          owned_shards=owned,
                          total_shards=self.total_shards)

    def _build_server(self, slot: int, cache: StateCache) -> PolicyServer:
        lstats = self.local_stats.setdefault(slot, ServingStats())
        poll = (self._weight_poll_factory(slot)
                if self._weight_poll_factory is not None else None)
        version = (self._weight_version_factory(slot)
                   if self._weight_version_factory is not None
                   else self._weight_version)
        fwd = (self._fwd_factory(slot)
               if self._fwd_factory is not None else None)
        return PolicyServer(
            self.cfg, self.net, self._params,
            endpoint=self.endpoints[slot],
            weight_poll=poll, weight_version=version,
            copy_updates=self._copy_updates, stats=self.stats,
            telemetry=self.telemetry, client_timed=self._client_timed,
            warmup=self._warmup, quant_stats=self.quant_stats,
            cache=cache, server_id=slot, shard_map=self.shard_map,
            device_index=slot, forward_fn=fwd, local_stats=lstats)

    def _start_server(self, slot: int, owned: List[int]) -> PolicyServer:
        server = self._build_server(slot, self._build_cache(owned))
        self.servers[slot] = server
        server.start()
        return server

    # -- elastic re-slice (grow / shrink / adopt) --

    def grow_server(self) -> int:
        """Lease a parked/free slot, re-slice, and hand the boundary
        shard groups off to the new server. Returns the grown slot.

        Ordering keeps the misroute window to the handoff itself: the
        new server is BUILT (incl. warmup) while the old map still
        routes everything at the donors; only then does the map flip and
        the donors detach — a straggler that raced the flip bounces off
        the donor with the NEW map already attached."""
        with self._lock:
            lease = self.membership.lease()
            slot = lease.slot
            active = sorted(set(self.servers) | {slot})
            parts = contiguous_partition(self.total_shards, active)
            owned = parts[slot]
            cache = self._build_cache(owned)
            server = self._build_server(slot, cache)
            assign = [0] * self.total_shards
            for s, groups in parts.items():
                for g in groups:
                    assign[g] = s
            self.shard_map.update(assign)
            for g in owned:
                donor = self.servers[
                    next(s for s in self.servers
                         if g in self.servers[s].cache.owned_shards)]
                with donor.cache_lock:
                    cache.restore_shard(donor.cache.detach_shard(g))
            self.servers[slot] = server
            server.start()
            return slot

    def shrink_server(self, slot: Optional[int] = None) -> int:
        """Stop one server (highest slot by default), hand its shard
        groups off to the survivors, and park its membership slot. The
        parked endpoint keeps accepting — the bouncer answers with
        MISROUTED + the new map, so routed clients re-aim without a
        single lost op (the donor's op-dedup state moved with the
        shards)."""
        with self._lock:
            if len(self.servers) <= 1:
                raise RuntimeError("cannot shrink the last serve server")
            if slot is None:
                slot = max(self.servers)
            victim = self.servers.pop(slot)
            victim.stop()
            survivors = sorted(self.servers)
            parts = contiguous_partition(self.total_shards, survivors)
            assign = [0] * self.total_shards
            for s, groups in parts.items():
                for g in groups:
                    assign[g] = s
            self._rehome(victim.cache, assign)
            self.shard_map.update(assign)
            self.membership.park(slot, reason="shrunk")
            return slot

    def kill_server(self, slot: int) -> None:
        """Chaos: stop a server loop ABRUPTLY — no handoff, membership
        still ACTIVE, map still aimed at the corpse. Clients time out /
        queue against the dead endpoint until :meth:`supervise` adopts
        the orphaned shards."""
        self.servers[slot].stop()

    def supervise(self) -> int:
        """Detect dead-but-ACTIVE servers and adopt their shard groups
        onto the survivors (the kill-one-of-N drill's recovery): the
        in-proc cache object survives its loop thread, so adoption is a
        detach/import like a clean shrink — state, leases, and op-dedup
        intact, which is what keeps the re-routed action streams
        bit-identical. Returns the number of servers reaped."""
        with self._lock:
            dead = [s for s, srv in self.servers.items() if not srv.running]
            if not dead or len(dead) == len(self.servers):
                return 0            # total outage: nothing to adopt onto
            for slot in dead:
                victim = self.servers.pop(slot)
                survivors = sorted(self.servers)
                parts = contiguous_partition(self.total_shards, survivors)
                assign = [0] * self.total_shards
                for s, groups in parts.items():
                    for g in groups:
                        assign[g] = s
                orphaned = len(victim.cache.owned_shards)
                self._rehome(victim.cache, assign)
                self.shard_map.update(assign)
                self.membership.park(slot, reason="died")
                self.adoptions += orphaned
                logging.getLogger(__name__).warning(
                    "serve server %d died; survivors adopted its shards",
                    slot)
            return len(dead)

    def _rehome(self, donor_cache: StateCache, assign: List[int]) -> None:
        """Move every shard group the donor cache still owns to the
        server the new assignment names (detach → import, whole-package
        handoff)."""
        for g in list(donor_cache.owned_shards):
            target = self.servers[assign[g]]
            state = donor_cache.detach_shard(g)
            with target.cache_lock:
                target.cache.import_shard(state)

    # -- parked-endpoint bouncer --

    def _bounce_loop(self) -> None:
        while not self._stop.is_set():
            live = set(self.servers)
            for slot, ep in enumerate(self.endpoints):
                if slot in live:
                    continue
                wire = self.shard_map.to_wire()
                while True:
                    try:
                        req, cb = ep.inbox.get_nowait()
                    except queue.Empty:
                        break
                    self.stats.on_misrouted(1)
                    try:
                        cb(Reply(req.req_id, STATUS_MISROUTED,
                                 shard_map=wire))
                    except Exception:
                        pass
            self._stop.wait(0.02)

    # -- client + telemetry surfaces --

    def connect(self) -> RoutingChannel:
        """A router over ALL slots' endpoints (parked ones bounce with
        the map, so a post-grow route needs no new connection)."""
        return RoutingChannel(
            {slot: ep.connect() for slot, ep in enumerate(self.endpoints)},
            self.shard_map)

    def serve_spec_servers(self) -> Dict[int, object]:
        """Slot → endpoint table for transport attachment (cli/serve.py
        and the orchestrator's process-actor socket rung)."""
        return dict(enumerate(self.endpoints))

    def interval_block(self, deadline_ms: Optional[float] = None,
                       max_batch: Optional[int] = None) -> Optional[dict]:
        """The fleet's ``serving`` record block: the shared aggregate
        (identical keys to single-server mode) plus a ``servers``
        sub-block with per-server rows — inspect's per-server panel."""
        block = self.stats.interval_block(deadline_ms=deadline_ms,
                                          max_batch=max_batch)
        if block is None:
            return None
        rows = {}
        with self._lock:
            for slot in sorted(self.servers):
                lb = self.local_stats[slot].interval_block()
                if lb is None:
                    continue
                # client-timed mode leaves the request histogram to the
                # clients (aggregate only); the per-server row falls back
                # to the server-side admitted latency
                lat = (lb["latency"]
                       or lb.get("admission", {}).get("admitted_latency")
                       or {})
                rows[str(slot)] = {
                    "requests": lb["requests"],
                    "latency_p50_ms": lat.get("p50_ms"),
                    "latency_p99_ms": lat.get("p99_ms"),
                    "fill_mean": lb["batch"]["fill_mean"],
                    "shed": lb.get("admission", {}).get("shed", 0),
                    "shards": len(self.servers[slot].cache.owned_shards),
                }
            block["servers"] = {
                "count": len(self.servers),
                "map_version": self.shard_map.version,
                "membership": self.membership.snapshot(),
                "rows": rows,
            }
        return block

    @property
    def running(self) -> bool:
        return any(srv.running for srv in self.servers.values())

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._lock:
            for srv in self.servers.values():
                srv.stop(timeout=timeout)
        self._bouncer.join(timeout=2.0)
