"""Server-side per-client acting state: the SEED placement move.

``ActorPolicy``/``BatchedActorPolicy`` hold three pieces of per-episode
state on the actor host — the packed LSTM hidden, the rolling frame
stack, and the last action (actor/policy.py). The central inference
service moves exactly that state here, keyed by client id, so thin
clients ship ONE raw frame per step and the recurrent context never
crosses the wire (SEED, arXiv 1910.03552 §3: "the state is kept on the
inference server").

The cache is SHARDED: client ids hash onto ``shards`` independent slot
groups, each with its own lease table — the layout under which a future
multi-device server pins shard s's arrays to device s and the per-shard
lease churn never contends. Leases:

  * ``lease``   — resolve client → slot. A new client takes a free slot
    (connect); a known client renews (and, if it had disconnected,
    RECONNECTS to its retained state — mid-episode recovery). A full
    shard evicts the stalest releasable lease (disconnected first, then
    oldest-idle) and resets the slot.
  * ``release`` — mark disconnected; state is RETAINED until
    ``lease_timeout_s`` so a bouncing client resumes where it left off.
  * ``sweep``   — evict disconnected leases idle past the timeout.

State mutations mirror the local policies' math exactly (observe_reset
broadcast fill, observe roll — parity-tested in tests/test_serve.py), so
a served actor's blocks are indistinguishable from a local one's.
"""

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class MisroutedClient(Exception):
    """A request reached a server whose cache does not own the client's
    shard group (the fleet re-sliced mid-flight): the server replies
    STATUS_MISROUTED with the current shard→server map instead of
    touching state, and the routing client re-aims."""

    def __init__(self, shard: int):
        super().__init__(f"client shard {shard} not owned by this cache")
        self.shard = shard


class StateCache:
    """``owned_shards``/``total_shards`` (fleet mode): this cache holds
    only the named GLOBAL shard groups of a ``total_shards``-wide hash
    space — server k's contiguous slice. Slot indices stay local and
    contiguous (owned position p covers ``[p*per_shard, (p+1)*per_shard)``);
    only the client→shard hash spans the global space. Defaults keep the
    single-server layout byte-identical (owns every shard)."""

    def __init__(self, slots: int, shards: int, frame_hw: Tuple[int, int],
                 frame_stack: int, hidden_dim: int,
                 lease_timeout_s: float = 120.0, action_dim: int = 1,
                 owned_shards: Optional[Sequence[int]] = None,
                 total_shards: Optional[int] = None):
        if shards > 0 and slots % shards != 0:
            raise ValueError(f"state slots ({slots}) must be divisible by "
                             f"shards ({shards})")
        self.slots = slots
        self.shards = shards
        self.per_shard = slots // shards if shards else 0
        self.total_shards = shards if total_shards is None else total_shards
        self._owned = (list(range(shards)) if owned_shards is None
                       else [int(g) for g in owned_shards])
        if len(self._owned) != shards:
            raise ValueError(
                f"owned_shards has {len(self._owned)} entries for "
                f"{shards} shard groups")
        self._pos = {g: p for p, g in enumerate(self._owned)}
        self.lease_timeout_s = lease_timeout_s
        self._frame_hw = tuple(frame_hw)
        self._frame_stack = frame_stack
        self._hidden_dim = hidden_dim
        self._action_dim = action_dim
        h, w = frame_hw
        self.hidden = np.zeros((slots, 2, hidden_dim), np.float32)
        self.stacked = np.zeros((slots, h, w, frame_stack), np.float32)
        self.last_action = np.full(slots, -1, np.int32)
        # Idempotent-RPC bookkeeping: the last APPLIED logical operation
        # per slot plus its cached result. A retried op (client timed
        # out, reply lost, but the first copy WAS processed) replays the
        # cached action/Q instead of re-rolling the frame stack and
        # re-advancing the hidden — one logical step mutates state
        # exactly once no matter how many copies reach the server.
        self.op_seq = np.full(slots, -1, np.int64)
        self.reply_action = np.zeros(slots, np.int64)
        self.reply_q = np.zeros((slots, max(action_dim, 1)), np.float32)
        # lease bookkeeping: slot -> client (-1 free) + per-shard maps
        self._slot_client = np.full(slots, -1, np.int64)
        self._last_seen = np.zeros(slots, np.float64)
        self._connected = np.zeros(slots, bool)
        self._leases: List[Dict[int, int]] = [dict() for _ in range(shards)]
        self.connects = 0
        self.reconnects = 0
        self.evictions = 0

    # -- leases --

    def _shard_of(self, client_id: int) -> int:
        g = int(client_id) % self.total_shards
        p = self._pos.get(g)
        if p is None:
            raise MisroutedClient(g)
        return p

    @property
    def owned_shards(self) -> List[int]:
        return list(self._owned)

    @property
    def active_clients(self) -> int:
        return int(self._connected.sum())

    @property
    def leased_slots(self) -> int:
        return int((self._slot_client >= 0).sum())

    def lease(self, client_id: int,
              now: Optional[float] = None) -> Tuple[int, bool]:
        """Resolve ``client_id`` to its slot; returns ``(slot, fresh)``
        where ``fresh`` means the slot holds NO prior state for this
        client (new connect or post-eviction re-admit) and the caller
        must reset it before use."""
        now = time.monotonic() if now is None else now
        s = self._shard_of(client_id)
        leases = self._leases[s]
        slot = leases.get(int(client_id))
        if slot is not None:
            if not self._connected[slot]:
                self.reconnects += 1     # retained state, resumed
            self._connected[slot] = True
            self._last_seen[slot] = now
            return slot, False
        slot = self._find_slot(s, now)
        leases[int(client_id)] = slot
        self._slot_client[slot] = int(client_id)
        self._connected[slot] = True
        self._last_seen[slot] = now
        self.connects += 1
        return slot, True

    def _find_slot(self, shard: int, now: float) -> int:
        lo, hi = shard * self.per_shard, (shard + 1) * self.per_shard
        owners = self._slot_client[lo:hi]
        free = np.flatnonzero(owners < 0)
        if len(free):
            return lo + int(free[0])
        # full shard: evict the stalest releasable lease — disconnected
        # leases first (their clients already left), else the oldest-idle
        # connected one (admission beats starvation; the evictee's next
        # request re-admits it with fresh state)
        ages = self._last_seen[lo:hi]
        disc = np.flatnonzero(~self._connected[lo:hi])
        cand = disc if len(disc) else np.arange(self.per_shard)
        victim = lo + int(cand[np.argmin(ages[cand])])
        self._evict(shard, victim)
        return victim

    def _evict(self, shard: int, slot: int) -> None:
        owner = int(self._slot_client[slot])
        self._leases[shard].pop(owner, None)
        self._slot_client[slot] = -1
        self._connected[slot] = False
        self.reset_slot(slot)
        self.reset_op(slot)
        self.evictions += 1

    def release(self, client_id: int,
                now: Optional[float] = None) -> bool:
        """Client disconnect: keep the state, mark the lease releasable.
        Returns True when the client actually held a lease."""
        now = time.monotonic() if now is None else now
        s = self._shard_of(client_id)
        slot = self._leases[s].get(int(client_id))
        if slot is None:
            return False
        self._connected[slot] = False
        self._last_seen[slot] = now
        return True

    def sweep(self, now: Optional[float] = None) -> int:
        """Evict disconnected leases idle past ``lease_timeout_s``;
        returns the number evicted."""
        now = time.monotonic() if now is None else now
        evicted = 0
        leased = np.flatnonzero(self._slot_client >= 0)
        for slot in leased:
            if (not self._connected[slot]
                    and now - self._last_seen[slot] > self.lease_timeout_s):
                self._evict(slot // self.per_shard, int(slot))
                evicted += 1
        return evicted

    # -- state mutations (the local policies' exact math) --

    def reset_slot(self, slot: int, obs: Optional[np.ndarray] = None) -> None:
        """Per-episode reset (ActorPolicy.reset_state / observe_reset):
        zero hidden, ``obs`` (if given) broadcast across the stack."""
        self.hidden[slot] = 0.0
        self.last_action[slot] = -1
        if obs is None:
            self.stacked[slot] = 0.0
        else:
            self.stacked[slot] = \
                (np.asarray(obs, np.float32) / 255.0)[..., None]

    def observe(self, slot: int, obs: np.ndarray, action: int) -> None:
        """Frame-stack roll + last-action record (ActorPolicy.observe)."""
        self.stacked[slot] = np.roll(self.stacked[slot], -1, axis=-1)
        self.stacked[slot][..., -1] = np.asarray(obs, np.float32) / 255.0
        self.last_action[slot] = np.int32(action)

    # -- batch assembly --

    def gather(self, slots: List[int]
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        idx = np.asarray(slots, np.int64)
        return (self.stacked[idx], self.last_action[idx], self.hidden[idx])

    def write_hidden(self, slot: int, hidden: np.ndarray) -> None:
        self.hidden[slot] = hidden

    # -- idempotent-op bookkeeping --

    def reset_op(self, slot: int) -> None:
        """Forget the slot's op history (fresh lease / eviction) — a new
        client's op numbering starts over."""
        self.op_seq[slot] = -1

    def record_op(self, slot: int, op_seq: int, action: int,
                  q: np.ndarray) -> None:
        self.op_seq[slot] = op_seq
        self.reply_action[slot] = action
        self.reply_q[slot] = q

    def cached_reply(self, slot: int) -> Tuple[int, np.ndarray]:
        return int(self.reply_action[slot]), self.reply_q[slot].copy()

    # -- shard lease-handoff (the elastic serve fleet's re-slice) --
    #
    # A shard group moves between servers as ONE package: its state
    # arrays (hidden/stack/last_action), the idempotent-op bookkeeping
    # (op_seq + cached replies — a retried op deduplicates across the
    # handoff, which is what makes a mid-kill re-route bit-identical),
    # and the lease table with connect/last-seen ages (disconnect
    # retention survives the move).

    _ARRAYS = ("hidden", "stacked", "last_action", "op_seq",
               "reply_action", "reply_q", "_slot_client", "_last_seen",
               "_connected")

    def export_shard(self, shard: int) -> dict:
        """Copy global shard group ``shard``'s full state out (the donor
        keeps it — see :meth:`detach_shard` for the removing variant)."""
        p = self._pos[int(shard)]
        lo, hi = p * self.per_shard, (p + 1) * self.per_shard
        state = {name: getattr(self, name)[lo:hi].copy()
                 for name in self._ARRAYS}
        state["shard"] = int(shard)
        state["per_shard"] = self.per_shard
        state["leases"] = {c: s - lo for c, s in self._leases[p].items()}
        return state

    def detach_shard(self, shard: int) -> dict:
        """Export global shard group ``shard`` and REMOVE it from this
        cache — the donor half of a re-slice. Later requests hashing onto
        it raise :class:`MisroutedClient` (→ STATUS_MISROUTED + map)."""
        state = self.export_shard(shard)
        p = self._pos.pop(int(shard))
        lo = p * self.per_shard
        keep = np.ones(self.slots, bool)
        keep[lo:lo + self.per_shard] = False
        for name in self._ARRAYS:
            setattr(self, name, getattr(self, name)[keep])
        self._leases.pop(p)
        # the compaction shifted every later group's rows down one
        # group: rebase those groups' lease slot indices to match
        for q in range(p, len(self._leases)):
            self._leases[q] = {c: s - self.per_shard
                               for c, s in self._leases[q].items()}
        self._owned.pop(p)
        self._pos = {g: q for q, g in enumerate(self._owned)}
        self.shards -= 1
        self.slots -= self.per_shard
        return state

    def import_shard(self, state: dict) -> None:
        """Append a handed-off shard group (the adopter half). The group
        arrives with its leases, ages, and op bookkeeping intact, so
        retained-state reconnects and retry dedup span the handoff."""
        if state["per_shard"] != self.per_shard:
            raise ValueError(
                f"shard geometry mismatch: incoming per_shard "
                f"{state['per_shard']} != {self.per_shard}")
        g = int(state["shard"])
        if g in self._pos:
            raise ValueError(f"shard {g} already owned")
        lo = self.slots
        for name in self._ARRAYS:
            setattr(self, name,
                    np.concatenate([getattr(self, name), state[name]]))
        self._leases.append({c: s + lo for c, s in state["leases"].items()})
        self._owned.append(g)
        self._pos[g] = len(self._owned) - 1
        self.shards += 1
        self.slots += self.per_shard

    def restore_shard(self, state: dict) -> None:
        """Overwrite an ALREADY-OWNED (fresh) shard group in place with
        handed-off state — how a newly-grown server adopts the shards the
        re-slice assigned to it."""
        g = int(state["shard"])
        if state["per_shard"] != self.per_shard:
            raise ValueError(
                f"shard geometry mismatch: incoming per_shard "
                f"{state['per_shard']} != {self.per_shard}")
        p = self._pos[g]
        lo = p * self.per_shard
        for name in self._ARRAYS:
            getattr(self, name)[lo:lo + self.per_shard] = state[name]
        self._leases[p] = {c: s + lo for c, s in state["leases"].items()}
