"""r2d2_tpu — a TPU-native distributed recurrent-replay RL framework.

A from-scratch JAX/XLA re-architecture of R2D2 (Recurrent Experience Replay in
Distributed Reinforcement Learning) with the full capability surface of the
reference implementation (McFredward/R2D2, PyTorch + Ray + CUDA): Ape-X actor
fan-out, prioritized sequence replay with burn-in and stored LSTM state,
dueling/double recurrent DQN, invertible value-rescaled n-step targets, Atari
and ViZDoom single/multiplayer self-play — redesigned TPU-first:

* the learner is a single fused XLA program (sample -> train -> priority
  update) over HBM-resident replay, so it never stalls on host-side tree walks;
* scaling is a `jax.sharding.Mesh` axis change (dp over ICI, optional mp),
  not a comms-library rewrite;
* CPU actor processes run a jitted CPU policy and pull weights from a
  shared-memory weight service instead of a Ray object store.
"""

from r2d2_tpu.config import Config, apex_epsilon, parse_overrides

__version__ = "0.1.0"

__all__ = ["Config", "apex_epsilon", "parse_overrides", "__version__"]
