"""Model zoo: recurrent value networks for the TPU-native R2D2 framework."""

from r2d2_tpu.models.network import (
    R2D2Network,
    NetworkApply,
    init_network,
    initial_hidden,
    pack_hidden,
    unpack_hidden,
)

__all__ = [
    "R2D2Network",
    "NetworkApply",
    "init_network",
    "initial_hidden",
    "pack_hidden",
    "unpack_hidden",
]
