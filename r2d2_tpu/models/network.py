"""Recurrent dueling/double DQN in Flax — the R2D2 model, TPU-first.

Capability parity with the reference PyTorch ``Network``
(/root/reference/model.py:8-157): Nature-DQN conv torso, LSTM over
[cnn latent ⊕ one-hot last action], dueling value/advantage heads with
mean-advantage baseline, and the four inference modes (single ``step``,
grad-enabled sequence Q, no-grad target sequence Q at t+n, hidden reset).

TPU-native re-design rather than translation:

* **One unroll, not three.** The reference runs three LSTM passes per train
  step: online ``caculate_q_`` for double-DQN action selection, target
  ``caculate_q_``, and grad-enabled online ``caculate_q``
  (/root/reference/worker.py:335-344). Because an LSTM output at t depends
  only on inputs <= t, the online pass over the full window subsumes both
  online passes: Q(s_t) and the action-selection Q(s_{t+n}) are *gathers from
  the same unrolled outputs* (see ops/indexing.py). Only the target net needs
  a second unroll — 2 sequential passes instead of 3.
* **Static shapes.** No pack/pad (/root/reference/model.py:103-108): every
  sequence unrolls the full fixed window under ``lax.scan``; ragged semantics
  live in gather indices + masks computed in ops/indexing.py.
* **NHWC convs + bf16 policy.** Channels-last is the TPU-friendly conv
  layout; ``compute_dtype=bfloat16`` replaces torch.cuda.amp
  (/root/reference/config.py:35) with f32 params and f32 Q outputs.
* **Sharding-ready.** Kernel params carry logical sharding annotations
  (``nn.with_partitioning``-free: we annotate at the mesh layer instead so a
  1-device run pays nothing) — model parallelism is a mesh-axis change.
"""

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from r2d2_tpu.config import NetworkConfig

# Hidden-state packing convention matches the reference actor protocol:
# packed[0] = h, packed[1] = c (torch.cat(hidden_state) at
# /root/reference/model.py:84). Flax LSTMCell carries (c, h).


def pack_hidden(carry: Tuple[jnp.ndarray, jnp.ndarray]) -> jnp.ndarray:
    c, h = carry
    return jnp.stack([h, c], axis=-2)  # (..., 2, hidden)


def unpack_hidden(packed: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    h = packed[..., 0, :]
    c = packed[..., 1, :]
    return (c, h)


def initial_hidden(batch_size: int, hidden_dim: int, dtype=jnp.float32) -> jnp.ndarray:
    """Zero packed hidden state (ref model.py:34,86-87)."""
    return jnp.zeros((batch_size, 2, hidden_dim), dtype=dtype)


def space_to_depth_2x2(x: jnp.ndarray) -> jnp.ndarray:
    """(B, H, W, C) -> (B, H/2, W/2, 4C); channel index (dh*2 + dw)*C + c."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)


class ConvTorso(nn.Module):
    """Nature-DQN feature extractor (ref model.py:22-31), NHWC.

    Input: (B, H, W, stack) normalized f32/bf16. Output: (B, cnn_out_dim).

    ``space_to_depth``: rewrite the FIRST conv as the mathematically
    identical conv over a 2x2 space-to-depth input — kernel/stride halved,
    input channels x4 (stack 4 -> 16). The first conv's tiny channel count
    otherwise wastes most of the MXU's 128 input lanes; the transform is
    EXACT (same linear map, weights re-indexed — parity-tested), it only
    changes the parameter layout, so checkpoints are specific to the
    setting like any architecture field. Requires even H/W/kernel/stride
    on layer 0 (validated by NetworkApply).
    """

    cnn_out_dim: int
    conv_layers: Sequence[Tuple[int, int, int]]
    dtype: jnp.dtype
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i, (features, kernel, stride) in enumerate(self.conv_layers):
            if i == 0 and self.space_to_depth:
                if kernel % 2 or stride % 2:
                    raise ValueError(
                        f"space_to_depth needs an even first-conv "
                        f"kernel/stride (got {kernel}/{stride}) — an odd "
                        "value would silently change the architecture "
                        "instead of being the exact rewrite")
                x = space_to_depth_2x2(x)
                kernel //= 2
                stride //= 2
            # VALID padding matches torch Conv2d's default zero-pad=0.
            x = nn.Conv(
                features,
                (kernel, kernel),
                strides=(stride, stride),
                padding="VALID",
                dtype=self.dtype,
            )(x)
            x = nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(self.cnn_out_dim, dtype=self.dtype)(x)
        return x


def convert_params_space_to_depth(params, frame_stack: int):
    """Migrate a standard-layout checkpoint to the space_to_depth layout:
    re-index the first conv's kernel (2k, 2k, C, O) -> (k, k, 4C, O) with
    w'[ph, pw, (dh*2+dw)*C + c, o] = w[2ph+dh, 2pw+dw, c, o] — the exact
    transform ConvTorso applies to the input, so the converted checkpoint
    computes identical outputs (parity-tested). Use when flipping
    network.space_to_depth on for a warm start from an off-layout run."""
    import flax
    params = flax.core.unfreeze(params) if hasattr(params, "unfreeze") else \
        jax.tree_util.tree_map(lambda x: x, params)
    torso = params["params"]["torso"]
    w = jnp.asarray(torso["Conv_0"]["kernel"])
    kh, kw, c, o = w.shape
    if c != frame_stack:
        raise ValueError(
            f"first conv kernel has {c} input channels; expected the "
            f"standard layout's frame_stack={frame_stack} — already "
            "converted?")
    if kh % 2 or kw % 2:
        raise ValueError(f"first conv kernel {kh}x{kw} must be even")
    torso["Conv_0"]["kernel"] = (
        w.reshape(kh // 2, 2, kw // 2, 2, c, o)
         .transpose(0, 2, 1, 3, 4, 5)
         .reshape(kh // 2, kw // 2, 4 * c, o))
    return params


class DuelingHead(nn.Module):
    """Dueling Q decomposition q = v + a - mean(a) (ref model.py:36-46,59-63)."""

    action_dim: int
    hidden_dim: int
    use_dueling: bool
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, h: jnp.ndarray) -> jnp.ndarray:
        adv = nn.Dense(self.hidden_dim, dtype=self.dtype, name="adv_hidden")(h)
        adv = nn.relu(adv)
        adv = nn.Dense(self.action_dim, dtype=self.dtype, name="adv_out")(adv)
        if not self.use_dueling:
            return adv.astype(jnp.float32)
        val = nn.Dense(self.hidden_dim, dtype=self.dtype, name="val_hidden")(h)
        val = nn.relu(val)
        val = nn.Dense(1, dtype=self.dtype, name="val_out")(val)
        q = val + adv - jnp.mean(adv, axis=-1, keepdims=True)
        return q.astype(jnp.float32)


def _block_orthogonal_init(num_blocks: int):
    """Per-gate orthogonal recurrent init, concatenated — the same
    distribution as flax's per-gate ``recurrent_kernel_init=orthogonal()``
    (one semi-orthogonal (H, num_blocks*H) draw would correlate gates)."""
    base = nn.initializers.orthogonal()

    def init(key, shape, dtype=jnp.float32):
        rows, cols = shape
        block = cols // num_blocks
        keys = jax.random.split(key, num_blocks)
        return jnp.concatenate(
            [base(k, (rows, block), dtype) for k in keys], axis=1)

    return init


def lstm_cell_step(xp, c, h, w_rec, bias):
    """One LSTM step given the precomputed input projection ``xp`` =
    x_t @ Wi. THE cell math (gate order i,f,g,o; sigmoid/sigmoid/tanh/
    sigmoid) — shared by the in-chip scan (HoistedLSTM) and the
    sequence-parallel pipelined scan (parallel/sequence_parallel.py), so
    the two cannot diverge."""
    gates = xp + h @ w_rec + bias
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    new_c = nn.sigmoid(f) * c + nn.sigmoid(i) * jnp.tanh(g)
    new_h = nn.sigmoid(o) * jnp.tanh(new_c)
    return new_c, new_h


class HoistedLSTM(nn.Module):
    """LSTM over a (B, T, D) sequence with the input projection hoisted out
    of the time scan.

    One LSTM step is ``gates = x_t @ Wi + h @ Wh + b``. The ``x @ Wi`` term
    has no serial dependency, so it is computed for the WHOLE window as one
    (B*T, D) x (D, 4H) MXU matmul before the scan; the scan body keeps only
    the (B, H) x (H, 4H) recurrent matmul — shrinking the work on the
    55-step serial dependency chain ~3x at the reference scale (D=1042,
    H=512). Identical math to ``nn.OptimizedLSTMCell`` (gate order i,f,g,o,
    sigmoid/sigmoid/tanh/sigmoid, c'=f*c+i*g, h'=o*tanh(c')), verified
    param-for-param in tests/test_network.py. Replaces the reference's
    cuDNN ``nn.LSTM`` (/root/reference/model.py:33)."""

    features: int
    dtype: jnp.dtype = jnp.float32
    # lax.scan unroll factor: >1 trades compile time/code size for fewer
    # loop-iteration boundaries on the serial chain (NetworkConfig.scan_unroll)
    unroll: int = 1
    # Fused pallas time-scan (ops/pallas_lstm.py) instead of lax.scan —
    # NetworkConfig.pallas_lstm, resolved. Identical math (the kernel folds
    # bias into the hoisted projection; tolerance-parity-tested).
    use_pallas: bool = False
    # timesteps per kernel grid iteration (NetworkConfig.pallas_lstm_block)
    pallas_block_t: int = 1
    # interpret-mode flag for the pallas path (CPU test mesh only)
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, carry, xs):
        # carry: (c, h) each (B, H); xs: (B, T, D)
        hidden = self.features
        x_proj = nn.Dense(4 * hidden, use_bias=False, dtype=self.dtype,
                          name="input_proj")(xs)              # (B, T, 4H)
        w_rec = self.param("recurrent_kernel", _block_orthogonal_init(4),
                           (hidden, 4 * hidden))
        bias = self.param("bias", nn.initializers.zeros, (4 * hidden,))
        w_rec = w_rec.astype(self.dtype)
        bias = bias.astype(self.dtype)

        if self.use_pallas and xs.shape[1] > 1:
            from r2d2_tpu.ops.pallas_lstm import lstm_scan_pallas
            # T=1 (the actor's step) stays on the scan path: a one-step
            # kernel dispatch has nothing to fuse.
            xpb = (x_proj + bias).swapaxes(0, 1)              # (T, B, 4H)
            hseq, (c_fin, h_fin) = lstm_scan_pallas(
                xpb, w_rec, carry[0], carry[1],
                interpret=self.pallas_interpret,
                block_t=self.pallas_block_t)
            return (c_fin, h_fin), hseq.swapaxes(0, 1)

        def step(carry, xp):                                  # xp: (B, 4H)
            new_c, new_h = lstm_cell_step(xp, carry[0], carry[1], w_rec, bias)
            return (new_c, new_h), new_h

        carry, outputs = jax.lax.scan(step, carry, x_proj.swapaxes(0, 1),
                                      unroll=self.unroll)
        return carry, outputs.swapaxes(0, 1)                  # (B, T, H)


class R2D2Network(nn.Module):
    """The full recurrent Q-network.

    ``__call__`` is the single entry point: unroll T steps from a packed
    hidden state, returning Q for every step plus the final packed hidden.
    T=1 is the actor's ``step``; T=seq_len is the learner's sequence pass.
    """

    action_dim: int
    config: NetworkConfig

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.config.bf16 else jnp.float32

    @nn.compact
    def __call__(
        self,
        obs_seq: jnp.ndarray,       # (B, T, H, W, stack) normalized [0,1]
        last_action_seq: jnp.ndarray,  # (B, T, action_dim) one-hot f32
        hidden: jnp.ndarray,        # (B, 2, hidden_dim) packed
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        cfg = self.config
        if not isinstance(cfg.space_to_depth, bool):
            # unresolved tri-state string: bool("off") is True — a silent
            # architecture inversion. Direct R2D2Network constructions must
            # go through NetworkApply (which resolves and validates) or
            # pass a concrete bool.
            raise ValueError(
                "R2D2Network requires a resolved (bool) "
                f"config.space_to_depth, got {cfg.space_to_depth!r} — "
                "construct via NetworkApply, which resolves the tri-state")
        dtype = self.compute_dtype
        batch, seq = obs_seq.shape[0], obs_seq.shape[1]

        # Torso over the flattened (B*T) frame batch — one big conv batch is
        # the MXU-friendly shape (vs per-step convs inside the scan).
        # The module names ("torso"/"lstm"/"head") double as the
        # component annotation contract (ISSUE 9): flax emits each as a
        # jax.named_scope, so every HLO op carries the component in its
        # op_name metadata and xprof traces attribute device time per
        # component (telemetry/traceparse.py keys on these exact tokens).
        flat = obs_seq.astype(dtype).reshape(batch * seq, *obs_seq.shape[2:])
        latent = ConvTorso(cfg.cnn_out_dim, cfg.conv_layers, dtype,
                           space_to_depth=cfg.space_to_depth,
                           name="torso")(flat)
        latent = latent.reshape(batch, seq, cfg.cnn_out_dim)

        rnn_in = jnp.concatenate(
            [latent, last_action_seq.astype(dtype)], axis=-1
        )

        # Time-batched LSTM with the input projection hoisted out of the
        # scan (ref model.py:33 — torch nn.LSTM batch_first).
        from r2d2_tpu.ops.pallas_kernels import resolve_pallas_setting
        cell = HoistedLSTM(features=cfg.hidden_dim, dtype=dtype,
                           unroll=cfg.scan_unroll,
                           use_pallas=resolve_pallas_setting(
                               cfg.pallas_lstm, "network.pallas_lstm"),
                           pallas_block_t=cfg.pallas_lstm_block,
                           pallas_interpret=cfg.pallas_lstm_interpret,
                           name="lstm")
        carry = unpack_hidden(hidden.astype(dtype))
        carry, outputs = cell(carry, rnn_in)

        q = DuelingHead(
            self.action_dim, cfg.hidden_dim, cfg.use_dueling, dtype, name="head"
        )(outputs.reshape(batch * seq, cfg.hidden_dim))
        q = q.reshape(batch, seq, self.action_dim)
        return q, pack_hidden(carry).astype(jnp.float32)


def dual_sequence_q(net: "NetworkApply", params_a, params_b,
                    obs_seq: jnp.ndarray, last_action_seq: jnp.ndarray,
                    hidden_a: jnp.ndarray, hidden_b: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Unroll TWO networks (online params_a, target params_b) over the same
    observation sequence with their recurrent chains interleaved in ONE
    ``lax.scan``.

    Two separate ``net.apply`` calls lower to two sequential XLA while
    loops, and XLA cannot overlap across while-loop boundaries — so a
    double-DQN step pays 2x55 SERIAL recurrent matmuls even though the two
    chains are independent. Each (B,512)x(512,2048) recurrent matmul is
    latency-bound, not throughput-bound (PERF.md: batch scaling is flat),
    so interleaving both chains in one scan body lets the scheduler hide
    one chain's latency under the other's. Identical math to two applies —
    the per-chain op sequence is unchanged (parity-tested exactly in
    tests/test_network.py). Gated by ``optim.fused_double_unroll``; only
    reachable when use_double is on.

    Targets the serial-LSTM wall of ref worker.py:335-344's three-unroll
    step (already reduced to two here; this removes the serialization
    between the remaining two).
    """
    cfg = net.config
    dtype = net.module.compute_dtype
    batch, seq = obs_seq.shape[0], obs_seq.shape[1]

    flat = obs_seq.astype(dtype).reshape(batch * seq, *obs_seq.shape[2:])
    torso = ConvTorso(cfg.cnn_out_dim, cfg.conv_layers, dtype,
                      space_to_depth=cfg.space_to_depth)
    # explicit component scopes: unlike the module path, these raw
    # .apply calls carry no flax module names, so the trace→component
    # mapping (telemetry/traceparse.py) would see the fused-dual
    # program's ops as unattributed without them
    with jax.named_scope("torso"):
        lat_a = torso.apply({"params": params_a["params"]["torso"]}, flat)
        lat_b = torso.apply({"params": params_b["params"]["torso"]}, flat)
    la = last_action_seq.astype(dtype)

    def rnn_in(lat):
        return jnp.concatenate([lat.reshape(batch, seq, cfg.cnn_out_dim), la],
                               axis=-1)

    def lstm_bits(p):
        lp = p["params"]["lstm"]
        return (jnp.asarray(lp["input_proj"]["kernel"]).astype(dtype),
                jnp.asarray(lp["recurrent_kernel"]).astype(dtype),
                jnp.asarray(lp["bias"]).astype(dtype))

    wi_a, wr_a, b_a = lstm_bits(params_a)
    wi_b, wr_b, b_b = lstm_bits(params_b)

    def step(carry, xs):
        ca, ha, cb, hb = carry
        xpa, xpb = xs
        ca, ha = lstm_cell_step(xpa, ca, ha, wr_a, b_a)
        cb, hb = lstm_cell_step(xpb, cb, hb, wr_b, b_b)
        return (ca, ha, cb, hb), (ha, hb)

    with jax.named_scope("lstm"):
        xp_a = (rnn_in(lat_a) @ wi_a).swapaxes(0, 1)    # (T, B, 4H)
        xp_b = (rnn_in(lat_b) @ wi_b).swapaxes(0, 1)
        ca, ha = unpack_hidden(hidden_a.astype(dtype))
        cb, hb = unpack_hidden(hidden_b.astype(dtype))
        _, (out_a, out_b) = jax.lax.scan(step, (ca, ha, cb, hb),
                                         (xp_a, xp_b),
                                         unroll=cfg.scan_unroll)

    head = DuelingHead(net.action_dim, cfg.hidden_dim, cfg.use_dueling, dtype)

    def head_q(params, outs):                            # outs: (T, B, H)
        q = head.apply({"params": params["params"]["head"]},
                       outs.swapaxes(0, 1).reshape(batch * seq, cfg.hidden_dim))
        return q.reshape(batch, seq, net.action_dim)

    with jax.named_scope("head"):
        return head_q(params_a, out_a), head_q(params_b, out_b)


# ---------------------------------------------------------------------------
# Quantized inference plane (ISSUE 14): per-channel symmetric int8 / bf16
# weight twins for the ACTING forward. The acting forward is
# weight-streaming-bound at acting batch sizes (tiny per-request FLOPs
# against full param-bytes HBM traffic — the costmodel tables; Podracer,
# arXiv 2104.06272), so shrinking weight bytes is the direct multiplier
# on env-steps/s and serving requests/s. Quantization happens ONCE at
# weight publish (runtime/weights.py ships the twin; no hot-path
# requantization); the forward dequantizes per-channel into the compute
# matmul. The learner never sees any of this — training stays f32/bf16.
# ---------------------------------------------------------------------------

INFERENCE_DTYPES = ("f32", "bf16", "int8")


def quant_compute_dtype():
    """Compute dtype of the quantized forward's matmuls: bf16 on TPU
    (the MXU-native acting dtype — the int8 weights dequantize into it),
    f32 elsewhere (bf16 is emulated and slower on CPU hosts, the
    _force_f32 reasoning; int8 storage still cuts publish bytes there).
    Resolved per-process at trace time, like the sibling tri-states."""
    import jax
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32


def quantize_leaf_int8(w: jnp.ndarray) -> dict:
    """Per-channel symmetric int8 quantization of one kernel: the scale
    is max|w| over all axes but the LAST (the output-channel axis of
    conv/dense/LSTM kernels) / 127, so each output channel keeps its own
    dynamic range — the standard per-channel weight-only scheme. The
    round-trip error is bounded by scale/2 per element (tested)."""
    w = jnp.asarray(w, jnp.float32)
    axes = tuple(range(w.ndim - 1))
    scale = jnp.max(jnp.abs(w), axis=axes, keepdims=True) / 127.0
    scale = jnp.maximum(scale, jnp.float32(1e-12))   # all-zero channels
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def _is_quant_leaf(leaf) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "scale" in leaf


def dequantize_leaf(leaf, dtype):
    """Inverse of quantize_leaf_int8 (or a plain cast for bf16-twin /
    unquantized leaves): int8 -> f32 per-channel rescale -> compute
    dtype. Inside a jitted forward XLA fuses this into the consumer
    matmul's operand read, so HBM weight traffic stays int8."""
    if _is_quant_leaf(leaf):
        return (leaf["q"].astype(jnp.float32) * leaf["scale"]).astype(dtype)
    return jnp.asarray(leaf).astype(dtype)


def dequantize_tree(tree, dtype):
    return jax.tree_util.tree_map(lambda l: dequantize_leaf(l, dtype),
                                  tree, is_leaf=_is_quant_leaf)


def quantize_params(params, inference_dtype: str):
    """The publish-time weight twin for one inference dtype:

      * ``"f32"``  — ``params`` unchanged (identity; the kill switch);
      * ``"bf16"`` — every float leaf cast to bf16 (2x weight bytes);
      * ``"int8"`` — every kernel (float ndim >= 2: conv kernels, dense
        kernels, the LSTM input projection and recurrent kernel) becomes
        a per-channel {"q": int8, "scale": f32} pair (~4x kernel bytes);
         1-D leaves (biases) stay f32 — they are noise against the
        kernels and the LSTM cell math wants them full-precision.
    """
    if inference_dtype == "f32":
        return params
    if inference_dtype == "bf16":
        return jax.tree_util.tree_map(
            lambda w: (jnp.asarray(w).astype(jnp.bfloat16)
                       if jnp.issubdtype(jnp.asarray(w).dtype, jnp.floating)
                       else jnp.asarray(w)), params)
    if inference_dtype != "int8":
        raise ValueError(
            f"inference_dtype must be one of {INFERENCE_DTYPES}, got "
            f"{inference_dtype!r}")

    def one(w):
        w = jnp.asarray(w)
        if w.ndim >= 2 and jnp.issubdtype(w.dtype, jnp.floating):
            return quantize_leaf_int8(w)
        return w.astype(jnp.float32)

    return jax.tree_util.tree_map(one, params)


def is_quant_bundle(tree) -> bool:
    """True for the published {"f32", "quant", "stamp"} bundle (vs a raw
    param tree, whose top level is flax's {"params": ...})."""
    return isinstance(tree, dict) and "quant" in tree and "f32" in tree


def make_inference_bundle(net: "NetworkApply", params, stamp: int = 0):
    """The tree the weight service publishes when
    ``net.config.inference_dtype != "f32"``: the f32 params (the probe's
    reference twin), the quantized twin (the hot path), and the
    publication stamp the twin was built at — so staleness between the
    two halves is impossible by construction and testable (the
    publish-time-twin stamp rides every adoption). For "f32" the raw
    params ARE the published tree (byte-identical plumbing)."""
    mode = net.config.inference_dtype
    if mode == "f32":
        return params
    return {"f32": params,
            "quant": quantize_params(params, mode),
            "stamp": jnp.asarray(stamp, jnp.int32)}


def f32_reference_module(net: "NetworkApply") -> "R2D2Network":
    """The accuracy probe's reference twin: TRUE f32 whatever the
    learner's compute policy — the guard measures quantization against
    the unquantized policy, not against bf16's own rounding. ONE
    definition shared by the host/server forward (make_forward_fn) and
    the anakin segment probe, so the two probes can never measure
    against different references."""
    import dataclasses
    return R2D2Network(action_dim=net.action_dim,
                       config=dataclasses.replace(net.config, bf16=False))


def quantized_inference_apply(net: "NetworkApply", qparams,
                              obs_seq: jnp.ndarray,
                              last_action_seq: jnp.ndarray,
                              hidden: jnp.ndarray,
                              compute_dtype=None
                              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The quantized twin of ``R2D2Network.__call__``: same signature,
    same module components (ConvTorso / DuelingHead via raw .apply, the
    shared ``lstm_cell_step`` — the dual_sequence_q pattern), but the
    weights come dequantized per-channel from the published twin and the
    LSTM CARRY STAYS f32: the recurrent state crosses acting steps
    thousands of times, so carrying it (and the cell math) in f32 keeps
    quantization error per-step instead of compounding — the recurrent
    matmul at acting batch is latency-bound anyway (PERF.md), so the
    f32 promotion costs nothing where this forward runs. Torso, the
    hoisted input projection, and the head run in ``compute_dtype``
    (bf16 on TPU, quant_compute_dtype); Q returns f32 like every other
    forward."""
    cfg = net.config
    dtype = compute_dtype if compute_dtype is not None \
        else quant_compute_dtype()
    qp = qparams["params"]
    batch, seq = obs_seq.shape[0], obs_seq.shape[1]

    flat = obs_seq.astype(dtype).reshape(batch * seq, *obs_seq.shape[2:])
    torso = ConvTorso(cfg.cnn_out_dim, cfg.conv_layers, dtype,
                      space_to_depth=cfg.space_to_depth)
    # explicit component scopes, like dual_sequence_q: raw .apply calls
    # carry no flax module names, and the trace→component mapping
    # (telemetry/traceparse.py) keys on these exact tokens
    with jax.named_scope("torso"):
        latent = torso.apply({"params": dequantize_tree(qp["torso"], dtype)},
                             flat)
    rnn_in = jnp.concatenate(
        [latent.reshape(batch, seq, cfg.cnn_out_dim),
         last_action_seq.astype(dtype)], axis=-1)

    lp = qp["lstm"]
    wi = dequantize_leaf(lp["input_proj"]["kernel"], dtype)
    w_rec = dequantize_leaf(lp["recurrent_kernel"], jnp.float32)
    bias = dequantize_leaf(lp["bias"], jnp.float32)
    with jax.named_scope("lstm"):
        # hoisted input projection in the compute dtype; the serial cell
        # chain in f32 (carry + gates — see docstring)
        xp = (rnn_in @ wi).astype(jnp.float32).swapaxes(0, 1)  # (T, B, 4H)
        carry = unpack_hidden(hidden.astype(jnp.float32))

        def step(c, xpt):
            new_c, new_h = lstm_cell_step(xpt, c[0], c[1], w_rec, bias)
            return (new_c, new_h), new_h

        carry, outputs = jax.lax.scan(step, carry, xp,
                                      unroll=cfg.scan_unroll)

    head = DuelingHead(net.action_dim, cfg.hidden_dim, cfg.use_dueling,
                       dtype)
    with jax.named_scope("head"):
        q = head.apply(
            {"params": dequantize_tree(qp["head"], dtype)},
            outputs.swapaxes(0, 1).reshape(batch * seq,
                                           cfg.hidden_dim).astype(dtype))
    return (q.reshape(batch, seq, net.action_dim),
            pack_hidden(carry).astype(jnp.float32))


def param_tree_bytes(tree) -> int:
    """Total bytes of a (possibly quantized) param tree — the analytic
    weight-streaming denominator the costmodel's quant rows and the
    quant A/B artifact quote (int8 twin vs f32: the >= 3x cut)."""
    import numpy as np
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        # works for jax/np arrays AND ShapeDtypeStruct avals
        total += int(np.prod(leaf.shape) if leaf.shape else 1) * \
            np.dtype(leaf.dtype).itemsize
    return int(total)


class NetworkApply:
    """Thin convenience binding of jitted apply functions to a network spec.

    Pure-functional: holds no parameters, only shapes/config. Used by the
    actor policy (CPU) and the learner (TPU); both call the same module so
    weight exchange is a raw pytree copy, never a format conversion (the
    reference ships state_dicts through Ray's object store instead,
    /root/reference/worker.py:286-290).
    """

    def __init__(self, action_dim: int, config: NetworkConfig,
                 frame_stack: int, frame_height: int, frame_width: int):
        # Resolve the bf16 tri-state here — ONE place — so the module and
        # every consumer of .config see a concrete bool ("auto" = bf16 iff
        # the default backend is TPU, the measured winner there: +28% with
        # the native-dtype decode, PERF.md; CPU backends keep f32, where
        # bf16 is emulated and slower).
        from r2d2_tpu.ops.pallas_kernels import resolve_pallas_setting
        import dataclasses
        if str(config.space_to_depth).lower() == "auto":
            # unlike the compute-only tri-states, this knob changes the
            # PARAMETER LAYOUT — a backend-dependent resolution would build
            # incompatible param trees on heterogeneous hosts (TPU learner
            # vs CPU-pinned actor processes / eval). Explicit only.
            raise ValueError(
                "network.space_to_depth must be 'on' or 'off' ('auto' is "
                "not allowed: the setting changes the parameter layout, so "
                "it must resolve identically on every host)")
        config = dataclasses.replace(
            config, bf16=resolve_pallas_setting(config.bf16, "network.bf16"),
            space_to_depth=resolve_pallas_setting(
                config.space_to_depth, "network.space_to_depth"))
        if config.space_to_depth:
            _, k0, s0 = config.conv_layers[0]
            if frame_height % 2 or frame_width % 2 or k0 % 2 or s0 % 2:
                raise ValueError(
                    "network.space_to_depth requires even frame dims and an "
                    f"even first-conv kernel/stride; got {frame_height}x"
                    f"{frame_width}, kernel {k0}, stride {s0}")
        self.action_dim = action_dim
        self.config = config
        self.obs_hw = (frame_height, frame_width, frame_stack)
        # Validate the conv pyramid against the frame size up front — a
        # zero/negative spatial output otherwise surfaces as an opaque
        # ZeroDivisionError inside flax's variance-scaling initializer.
        h, w = frame_height, frame_width
        for i, (_, kernel, stride) in enumerate(config.conv_layers):
            h = (h - kernel) // stride + 1
            w = (w - kernel) // stride + 1
            if h < 1 or w < 1:
                raise ValueError(
                    f"conv layer {i} (kernel {kernel}, stride {stride}) "
                    f"shrinks the {frame_height}x{frame_width} frame to "
                    f"{h}x{w}; use smaller network.conv_layers for this "
                    "frame size")
        self.module = R2D2Network(action_dim=action_dim, config=config)

    def init(self, key: jax.Array):
        h, w, s = self.obs_hw
        obs = jnp.zeros((1, 1, h, w, s), jnp.float32)
        la = jnp.zeros((1, 1, self.action_dim), jnp.float32)
        hid = initial_hidden(1, self.config.hidden_dim)
        return self.module.init(key, obs, la, hid)

    def apply(self, params, obs_seq, last_action_seq, hidden):
        return self.module.apply(params, obs_seq, last_action_seq, hidden)


def init_network(
    key: jax.Array,
    action_dim: int,
    config: NetworkConfig,
    frame_stack: int = 4,
    frame_height: int = 84,
    frame_width: int = 84,
):
    """Initialize (apply_spec, params)."""
    spec = NetworkApply(action_dim, config, frame_stack, frame_height, frame_width)
    return spec, spec.init(key)
