// Lock-free bounded MPMC ring over a shared-memory region (Vyukov
// per-slot-sequence design): the native experience transport between actor
// processes and the learner. The reference ships experience blocks through
// Ray's plasma object store (C++; /root/reference/worker.py:558,565) — this
// is the framework's equivalent: fixed-shape Block records move host→host
// with ONE memcpy per side and no pickling, through a region created by
// Python's multiprocessing.shared_memory and operated on entirely here.
//
// Layout of the region (64-bit words, 8-byte aligned):
//   [0]  capacity (slots)
//   [1]  slot_bytes (payload bytes per slot)
//   [2]  enqueue_pos   (atomic)
//   [3]  dequeue_pos   (atomic)
//   [4..] per-slot: { atomic<u64> seq; atomic<u64> reserve_ms;
//                     u8 payload[slot_stride-16] }
//
// Cross-process safety: std::atomic<uint64_t> is address-free/lock-free on
// every 64-bit target this builds on (asserted), so the atomics work across
// processes mapping the same region. Multiple producers (actor processes)
// and one-or-more consumers are both safe — the algorithm is full MPMC.
//
// Crash recovery: a producer dying between reserve and commit would wedge
// the ring forever (the head slot never publishes). reserve stamps the slot
// with CLOCK_MONOTONIC ms (shared across processes on Linux); the
// supervisor — after reaping a dead actor process — calls
// ring_recover_stalled() to skip head slots that are reserved-uncommitted
// (enqueue_pos passed them but seq never advanced) AND stale beyond a
// grace, which a live producer's millisecond-scale memcpy can never be.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

static_assert(sizeof(std::atomic<uint64_t>) == 8, "atomic u64 must be 8B");

namespace {

struct Header {
  uint64_t capacity;
  uint64_t slot_bytes;
  std::atomic<uint64_t> enqueue_pos;
  std::atomic<uint64_t> dequeue_pos;
};

inline uint64_t slot_stride(uint64_t slot_bytes) {
  // seq word + reserve-timestamp word + aligned payload
  return 16 + ((slot_bytes + 7) & ~uint64_t(7));
}

inline std::atomic<uint64_t>* slot_seq(void* base, uint64_t idx) {
  auto* h = static_cast<Header*>(base);
  char* slots = static_cast<char*>(base) + sizeof(Header);
  return reinterpret_cast<std::atomic<uint64_t>*>(
      slots + idx * slot_stride(h->slot_bytes));
}

inline std::atomic<uint64_t>* slot_ts(void* base, uint64_t idx) {
  return slot_seq(base, idx) + 1;
}

inline char* slot_payload(void* base, uint64_t idx) {
  return reinterpret_cast<char*>(slot_seq(base, idx)) + 16;
}

inline uint64_t monotonic_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000 + uint64_t(ts.tv_nsec) / 1000000;
}

}  // namespace

extern "C" {

uint64_t ring_required_bytes(uint64_t capacity, uint64_t slot_bytes) {
  return sizeof(Header) + capacity * slot_stride(slot_bytes);
}

void ring_init(void* base, uint64_t capacity, uint64_t slot_bytes) {
  auto* h = static_cast<Header*>(base);
  h->capacity = capacity;
  h->slot_bytes = slot_bytes;
  h->enqueue_pos.store(0, std::memory_order_relaxed);
  h->dequeue_pos.store(0, std::memory_order_relaxed);
  for (uint64_t i = 0; i < capacity; ++i)
    slot_seq(base, i)->store(i, std::memory_order_relaxed);
}

// Reserve/commit: reserve returns the position whose slot the caller may
// read/write EXCLUSIVELY until the matching commit publishes it. Lets the
// Python side serialize Block fields directly into the shared slot (one
// memcpy per side total) instead of staging through a packed buffer.

int64_t ring_reserve_push(void* base) {
  auto* h = static_cast<Header*>(base);
  uint64_t pos = h->enqueue_pos.load(std::memory_order_relaxed);
  for (;;) {
    uint64_t seq = slot_seq(base, pos % h->capacity)
                       ->load(std::memory_order_acquire);
    int64_t dif = int64_t(seq) - int64_t(pos);
    if (dif == 0) {
      // Stamp BEFORE the CAS: a winner must never be observable as
      // reserved with the slot's previous-lap (stale) timestamp, or
      // recover_stalled could reclaim a live reservation. A CAS loser's
      // stray stamp only freshens another writer's ts — recovery just
      // gets more conservative.
      slot_ts(base, pos % h->capacity)
          ->store(monotonic_ms(), std::memory_order_relaxed);
      if (h->enqueue_pos.compare_exchange_weak(pos, pos + 1,
                                               std::memory_order_relaxed)) {
        return int64_t(pos);
      }
    } else if (dif < 0) {
      return -1;  // full
    } else {
      pos = h->enqueue_pos.load(std::memory_order_relaxed);
    }
  }
}

void ring_commit_push(void* base, int64_t pos) {
  auto* h = static_cast<Header*>(base);
  slot_seq(base, uint64_t(pos) % h->capacity)
      ->store(uint64_t(pos) + 1, std::memory_order_release);
}

int64_t ring_reserve_pop(void* base) {
  auto* h = static_cast<Header*>(base);
  uint64_t pos = h->dequeue_pos.load(std::memory_order_relaxed);
  for (;;) {
    uint64_t seq = slot_seq(base, pos % h->capacity)
                       ->load(std::memory_order_acquire);
    int64_t dif = int64_t(seq) - int64_t(pos + 1);
    if (dif == 0) {
      if (h->dequeue_pos.compare_exchange_weak(pos, pos + 1,
                                               std::memory_order_relaxed))
        return int64_t(pos);
    } else if (dif < 0) {
      return -1;  // empty
    } else {
      pos = h->dequeue_pos.load(std::memory_order_relaxed);
    }
  }
}

void ring_commit_pop(void* base, int64_t pos) {
  auto* h = static_cast<Header*>(base);
  slot_seq(base, uint64_t(pos) % h->capacity)
      ->store(uint64_t(pos) + h->capacity, std::memory_order_release);
}

// Byte offset of a reserved position's payload from the region base.
uint64_t ring_payload_offset(void* base, int64_t pos) {
  auto* h = static_cast<Header*>(base);
  return uint64_t(slot_payload(base, uint64_t(pos) % h->capacity) -
                  static_cast<char*>(base));
}

// Skip head slots wedged by a crashed producer: reserved (enqueue_pos is
// past them) but uncommitted (seq never advanced) and stale for more than
// ``stale_ms``. Call ONLY after reaping a dead producer — the staleness
// grace is what protects a live producer mid-memcpy. Returns slots freed.
uint64_t ring_recover_stalled(void* base, uint64_t stale_ms) {
  auto* h = static_cast<Header*>(base);
  uint64_t freed = 0;
  for (;;) {
    uint64_t pos = h->dequeue_pos.load(std::memory_order_relaxed);
    uint64_t enq = h->enqueue_pos.load(std::memory_order_acquire);
    if (enq <= pos) break;  // nothing in flight
    auto* seq_w = slot_seq(base, pos % h->capacity);
    uint64_t seq = seq_w->load(std::memory_order_acquire);
    if (seq != pos) break;  // head slot is committed (or already recycled)
    uint64_t ts = slot_ts(base, pos % h->capacity)
                      ->load(std::memory_order_relaxed);
    if (monotonic_ms() - ts < stale_ms) break;  // give a live writer time
    if (h->dequeue_pos.compare_exchange_strong(pos, pos + 1,
                                               std::memory_order_relaxed)) {
      seq_w->store(pos + h->capacity, std::memory_order_release);
      ++freed;
    }
  }
  return freed;
}

// Approximate occupancy (racy by nature; fine for monitoring).
uint64_t ring_size(void* base) {
  auto* h = static_cast<Header*>(base);
  uint64_t e = h->enqueue_pos.load(std::memory_order_relaxed);
  uint64_t d = h->dequeue_pos.load(std::memory_order_relaxed);
  return e > d ? e - d : 0;
}

}  // extern "C"
