"""ctypes binding for the native C++ sum tree (see sum_tree.cc).

Builds the shared library on first import via the bundled Makefile (g++ is a
baked-in toolchain dependency); import fails cleanly if the toolchain is
absent, and HostReplay falls back to the numpy twin.
"""

import ctypes
import os
import subprocess
from typing import Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libsumtree.so")


def _build() -> None:
    subprocess.run(["make", "-s", "-C", _DIR], check=True,
                   capture_output=True, text=True)


def _load() -> ctypes.CDLL:
    if not os.path.exists(_SO) or (
            os.path.getmtime(_SO) <
            os.path.getmtime(os.path.join(_DIR, "sum_tree.cc"))):
        _build()
    lib = ctypes.CDLL(_SO)
    lib.st_create.argtypes = [ctypes.c_int64]
    lib.st_create.restype = ctypes.c_void_p
    lib.st_destroy.argtypes = [ctypes.c_void_p]
    lib.st_num_layers.argtypes = [ctypes.c_void_p]
    lib.st_num_layers.restype = ctypes.c_int64
    lib.st_total.argtypes = [ctypes.c_void_p]
    lib.st_total.restype = ctypes.c_double
    dptr = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    iptr = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    lib.st_update.argtypes = [ctypes.c_void_p, ctypes.c_double, dptr, iptr,
                              ctypes.c_int64]
    lib.st_sample.argtypes = [ctypes.c_void_p, ctypes.c_double,
                              ctypes.c_int64, dptr, iptr, dptr]
    return lib


_LIB = _load()

_RING_SO = os.path.join(_DIR, "libshmring.so")
_RING_LIB = None


def ring_lib() -> ctypes.CDLL:
    """Lazy-loaded binding for the native shm MPMC ring (shm_ring.cc)."""
    global _RING_LIB
    if _RING_LIB is None:
        if not os.path.exists(_RING_SO) or (
                os.path.getmtime(_RING_SO) <
                os.path.getmtime(os.path.join(_DIR, "shm_ring.cc"))):
            _build()
        lib = ctypes.CDLL(_RING_SO)
        lib.ring_required_bytes.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.ring_required_bytes.restype = ctypes.c_uint64
        lib.ring_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_uint64]
        lib.ring_size.argtypes = [ctypes.c_void_p]
        lib.ring_size.restype = ctypes.c_uint64
        lib.ring_recover_stalled.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ring_recover_stalled.restype = ctypes.c_uint64
        lib.ring_reserve_push.argtypes = [ctypes.c_void_p]
        lib.ring_reserve_push.restype = ctypes.c_int64
        lib.ring_commit_push.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ring_reserve_pop.argtypes = [ctypes.c_void_p]
        lib.ring_reserve_pop.restype = ctypes.c_int64
        lib.ring_commit_pop.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ring_payload_offset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ring_payload_offset.restype = ctypes.c_uint64
        _RING_LIB = lib
    return _RING_LIB


class NativeSumTree:
    """API-compatible with the numpy twin in ops/sum_tree.py."""

    def __init__(self, capacity: int):
        self._handle = _LIB.st_create(capacity)
        self.capacity = capacity
        self.num_layers = int(_LIB.st_num_layers(self._handle))

    def update(self, alpha: float, td_errors: np.ndarray,
               idxes: np.ndarray) -> None:
        td = np.ascontiguousarray(td_errors, np.float64)
        ix = np.ascontiguousarray(idxes, np.int64)
        _LIB.st_update(self._handle, float(alpha), td, ix, len(ix))

    def sample(self, beta: float, n: int, rng: np.random.Generator
               ) -> Tuple[np.ndarray, np.ndarray]:
        jitter = np.ascontiguousarray(rng.uniform(0.0, 1.0, n), np.float64)
        out_idx = np.empty(n, np.int64)
        out_w = np.empty(n, np.float64)
        _LIB.st_sample(self._handle, float(beta), n, jitter, out_idx, out_w)
        return out_idx, out_w

    @property
    def total(self) -> float:
        return float(_LIB.st_total(self._handle))

    def __del__(self):
        try:
            _LIB.st_destroy(self._handle)
        except Exception:
            pass
