// Native host-side priority sum tree — the CPU-feeder fallback for
// host-placement replay (SURVEY §2.1: the reference compiles these two
// kernels with numba→LLVM, /root/reference/priority_tree.py:15-49; numba is
// not a dependency here, so the host path gets a real compiled
// implementation).
//
// Semantics match r2d2_tpu/ops/sum_tree.py's numpy twin bit-for-bit given the
// same stratified jitter: float64 storage, p = |td|^alpha with p(0) = 0,
// stratified prefix-sum descent that never enters a zero-mass right subtree,
// IS weights (p / min_p)^-beta.
//
// C ABI (ctypes-friendly), single-threaded per tree; the caller (HostReplay)
// serializes access under its lock exactly as the reference's buffer lock
// does (/root/reference/worker.py:65).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct SumTree {
  int64_t num_layers;
  int64_t capacity;      // leaves
  std::vector<double> nodes;  // 2^num_layers - 1
};

int64_t layers_for(int64_t capacity) {
  int64_t layers = 1;
  while (capacity > (int64_t(1) << (layers - 1))) ++layers;
  return layers;
}

}  // namespace

extern "C" {

SumTree* st_create(int64_t capacity) {
  auto* t = new SumTree;
  t->num_layers = layers_for(capacity);
  t->capacity = capacity;
  t->nodes.assign((int64_t(1) << t->num_layers) - 1, 0.0);
  return t;
}

void st_destroy(SumTree* t) { delete t; }

int64_t st_num_layers(const SumTree* t) { return t->num_layers; }

double st_total(const SumTree* t) { return t->nodes[0]; }

// Write p = |td|^alpha at the given leaves, then rebuild ancestor sums
// bottom-up (level-synchronous like the numba kernel's np.unique dedup —
// here a simple walk per index; n is <= seqs_per_block or batch_size).
void st_update(SumTree* t, double alpha, const double* td_errors,
               const int64_t* idxes, int64_t n) {
  const int64_t leaf0 = (int64_t(1) << (t->num_layers - 1)) - 1;
  for (int64_t i = 0; i < n; ++i) {
    const double td = td_errors[i];
    const double p = td != 0.0 ? std::pow(std::fabs(td), alpha) : 0.0;
    int64_t node = leaf0 + idxes[i];
    const double delta = p - t->nodes[node];
    t->nodes[node] = p;
    while (node != 0) {
      node = (node - 1) / 2;
      t->nodes[node] += delta;
    }
  }
}

// Stratified proportional sampling. jitter[i] in [0,1) supplies stratum i's
// uniform draw (provided by the caller's RNG so python/numpy/C++ paths can
// share one stream). Returns leaf indices and IS weights (p/min_p)^-beta.
void st_sample(const SumTree* t, double beta, int64_t n, const double* jitter,
               int64_t* out_idxes, double* out_weights) {
  const int64_t leaf0 = (int64_t(1) << (t->num_layers - 1)) - 1;
  const double p_sum = t->nodes[0];
  const double interval = p_sum / static_cast<double>(n);
  double min_p = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    double prefix = (static_cast<double>(i) + jitter[i]) * interval;
    if (prefix > p_sum * (1.0 - 1e-12)) prefix = p_sum * (1.0 - 1e-12);
    int64_t node = 0;
    for (int64_t layer = 0; layer < t->num_layers - 1; ++layer) {
      const double left = t->nodes[2 * node + 1];
      const double right = t->nodes[2 * node + 2];
      if (prefix < left || right <= 0.0) {
        node = 2 * node + 1;
        const double cap = left * (1.0 - 1e-12);
        if (prefix > cap) prefix = cap;
      } else {
        node = 2 * node + 2;
        prefix -= left;
      }
    }
    const double p = t->nodes[node];
    out_idxes[i] = node - leaf0;
    out_weights[i] = p;
    if (i == 0 || p < min_p) min_p = p;
  }
  for (int64_t i = 0; i < n; ++i) {
    out_weights[i] = std::pow(out_weights[i] / min_p, -beta);
  }
}

}  // extern "C"
