"""Tensor (model) parallelism over the 'mp' mesh axis — GSPMD style.

SURVEY §2.2: the reference has no TP (a single 4M-param network on half a
GPU); the promise of the TPU-native design is that model sharding is "a
mesh-axis change, not a rewrite". This module keeps that promise the
jax-idiomatic way: the SAME traceable train step is re-jitted with the
model's wide feature dimensions annotated over 'mp' (conv output channels,
the cnn FC, the hoisted-LSTM input/recurrent projections, the dueling
hidden layers) and the batch over 'dp', and XLA's SPMD partitioner inserts
the collectives. No network or step code changes — exactly the property the
manual shard_map dp path also preserves from the other direction.

At the reference's model scale TP is not a throughput win (the network fits
comfortably in one chip's HBM and the matmuls are small); what this module
buys is capability — the same framework scales to models that do NOT fit
one chip (hidden_dim/cnn_out_dim large enough that feature-sharded layers
matter), with correctness pinned by a parity test against the unsharded
step (tests/test_parallel.py).
"""

from typing import Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from r2d2_tpu.config import OptimConfig
from r2d2_tpu.learner.train_step import TrainState, make_external_batch_step
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.replay.structs import ReplaySpec, SampleBatch


def leaf_partition_spec(shape: Tuple[int, ...], mp: int,
                        min_shard_width: int = 32) -> P:
    """Feature-dim sharding rule for one param/opt-state leaf.

    Shards the trailing (output-feature) axis over 'mp' when it divides
    evenly and each shard would still be at least ``min_shard_width`` wide;
    everything else — small head outputs (action_dim), scalars, odd
    shapes — stays replicated. The optimizer moments follow their params
    automatically because optax mirrors the param tree (same leaf shapes)."""
    if mp <= 1 or not shape:
        return P()
    last = shape[-1]
    if last % mp != 0 or last // mp < min_shard_width:
        return P()
    return P(*([None] * (len(shape) - 1) + ["mp"]))


def state_shardings(train_state: TrainState, mesh: Mesh,
                    min_shard_width: int = 32):
    """NamedSharding tree for a TrainState under ``mesh`` (axes dp, mp)."""
    mp = mesh.shape["mp"]
    return jax.tree_util.tree_map(
        lambda x: NamedSharding(mesh, leaf_partition_spec(
            np.shape(x), mp, min_shard_width)),
        train_state)




def make_tp_external_batch_step(net: NetworkApply, spec: ReplaySpec,
                                optim: OptimConfig, use_double: bool,
                                mesh: Mesh, min_shard_width: int = 32,
                                diag=None, rdiag=None):
    """Returns (step, place_state, place_batch).

    ``place_state(ts)`` / ``place_batch(batch)`` lay host values onto the
    mesh (params feature-sharded over mp, batch over dp); ``step`` is the
    UNMODIFIED external-batch train step — its jit binds no shardings, so
    the compiled program adopts the committed inputs' shardings and GSPMD
    propagates them through the whole fwd/bwd, inserting the
    all-gathers/reduce-scatters TP needs. The sharding lives entirely in
    the placement functions; that is the whole point."""
    dp = mesh.shape["dp"]
    if spec.batch_size % dp:
        raise ValueError(
            f"replay.batch_size={spec.batch_size} is not divisible by the "
            f"mesh dp={dp} — the batch axis cannot shard evenly")
    # diag/rdiag thread through like every other step factory: the TP
    # path must not silently disable the learning diagnostics (or the
    # NaN guard, or the replay pillar's lane counts) that plain host
    # placement carries
    step = make_external_batch_step(net, spec, optim, use_double,
                                    diag=diag, rdiag=rdiag)
    batch_sharding = NamedSharding(mesh, P("dp"))   # device_put broadcasts
                                                    # one sharding over the
                                                    # whole batch pytree
    def place_state(ts: TrainState) -> TrainState:
        return jax.device_put(ts, state_shardings(ts, mesh, min_shard_width))

    def place_batch(batch: SampleBatch) -> SampleBatch:
        return jax.device_put(batch, batch_sharding)

    return step, place_state, place_batch
