"""jax version compatibility for shard_map.

``jax.shard_map`` (with the ``check_vma`` kwarg) is the public API from
jax 0.6+; older jax (this container ships 0.4.x) only has
``jax.experimental.shard_map.shard_map`` whose equivalent kwarg is
``check_rep``. One wrapper exports the NEW surface (``check_vma``) and
translates down when running on the experimental version, so every call
site in this package writes modern-jax code and runs on both.
"""

import functools

try:
    from jax import shard_map as _shard_map  # jax >= 0.6

    _CHECK_KW = "check_vma"
except ImportError:  # jax < 0.6
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
    kwargs[_CHECK_KW] = check_vma
    if f is None:
        return functools.partial(_shard_map, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kwargs)
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)
