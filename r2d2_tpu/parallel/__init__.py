"""Multi-chip parallelism.

The reference has NO learner parallelism — one process on half a GPU, no
collectives anywhere (/root/reference/worker.py:251, SURVEY §2.2). Here
scaling is a mesh-axis change: the fused learner step runs under shard_map
over a ``jax.sharding.Mesh`` 'dp' axis with the replay ring sharded
block-wise per chip, per-shard prioritized sampling, and gradient pmean over
ICI; multi-host extends the same mesh over DCN via jax.distributed.
"""

from r2d2_tpu.parallel.mesh import make_mesh, init_distributed, dp_sharding
from r2d2_tpu.parallel.sharded import (
    make_sharded_learner_step,
    make_sharded_replay_add,
    make_sharded_replay_add_many,
    make_sharded_anakin_act,
    init_sharded_act_carry,
    sharded_replay_init,
    sharded_buffer_steps,
)
from r2d2_tpu.parallel.tensor_parallel import (
    make_tp_external_batch_step,
    state_shardings,
)

__all__ = [
    "make_mesh", "init_distributed", "dp_sharding",
    "make_sharded_learner_step", "make_sharded_replay_add",
    "make_sharded_replay_add_many",
    "make_sharded_anakin_act", "init_sharded_act_carry",
    "sharded_replay_init", "sharded_buffer_steps",
    "make_tp_external_batch_step", "state_shardings",
    "train_multihost", "make_sp_lstm",
]


def __getattr__(name):
    # lazy: these pull in the runtime/model stacks; don't tax `import
    # r2d2_tpu.parallel` for the common single-host case
    if name == "train_multihost":
        from r2d2_tpu.parallel.multihost import train_multihost
        return train_multihost
    if name == "make_sp_lstm":
        from r2d2_tpu.parallel.sequence_parallel import make_sp_lstm
        return make_sp_lstm
    raise AttributeError(name)
