"""Shared loopback launcher for multi-controller validation runs.

One implementation of the subtle part — spawn N worker processes against an
ephemeral-port coordinator, wait on one shared deadline, and kill survivors
on ANY exit path (a crashed coordinator process would otherwise leave its
peer blocked in jax.distributed.initialize as an orphan) — used by both the
bring-up dryrun (multihost_dryrun.py) and the full lockstep-training demo
(multihost.py).
"""

import socket
import subprocess
import sys
import time
from typing import Callable, List


def pick_coordinator() -> str:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return f"127.0.0.1:{s.getsockname()[1]}"


def run_loopback_workers(worker_argv: Callable[[int, str], List[str]],
                         num_processes: int, timeout: float,
                         label: str) -> None:
    """``worker_argv(process_id, coordinator)`` returns the full argv for one
    worker. Raises SystemExit naming ``label`` if any worker fails or times
    out (timed-out workers are killed)."""
    coordinator = pick_coordinator()
    procs = [subprocess.Popen(worker_argv(pid, coordinator))
             for pid in range(num_processes)]
    deadline = time.time() + timeout
    rcs = []
    try:
        for p in procs:
            try:
                rcs.append(p.wait(timeout=max(1.0, deadline - time.time())))
            except subprocess.TimeoutExpired:
                rcs.append(None)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rc != 0 for rc in rcs):
        raise SystemExit(
            f"{label} failed: worker rcs={rcs} (None = timed out after "
            f"{timeout:.0f}s and was killed)")
