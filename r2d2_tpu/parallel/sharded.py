"""Sharded learner: the fused step over a device mesh via shard_map.

Design (SURVEY §5.8, scaling-book recipe — pick a mesh, annotate shardings,
let XLA insert collectives):

  * The replay ring gains a leading ``dp`` axis sharded across chips: each
    chip owns ``num_blocks`` blocks, its own priority sum tree, and its own
    ring pointer. Prioritized sampling is per-shard (stratified within the
    chip's tree) — with round-robin block feeding this factorizes global
    stratified sampling across chips, and priority write-back stays chip-local
    (zero cross-chip traffic on the replay path).
  * Params / optimizer state are replicated; each chip computes gradients on
    its local ``batch_size`` sequences and a single ``pmean`` over ICI makes
    the Adam update identical everywhere — the global batch is
    ``dp * batch_size`` (the reference's learner has no equivalent axis; its
    batch is bounded by half a GPU, worker.py:251).
  * The RNG key is replicated; each shard folds in its axis index for
    sampling, and the carried key stays replicated.

The inner computation is the SAME ``make_loss_fn``/tree code as the
single-chip path — the mesh is an orthogonal layer, exactly the property the
reference's Ray design lacks.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from r2d2_tpu.parallel.compat import shard_map

from r2d2_tpu.config import OptimConfig
from r2d2_tpu.learner.train_step import TrainState, make_loss_fn, make_optimizer
from r2d2_tpu.models.network import NetworkApply
from r2d2_tpu.ops.sum_tree import tree_update
from r2d2_tpu.replay.device_replay import (
    replay_init, replay_sample, replay_add, replay_add_many)
from r2d2_tpu.replay.structs import Block, ReplaySpec, ReplayState


def _shard0(tree):
    """Per-shard view: drop the leading dp axis (local size 1)."""
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _unshard0(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


def sharded_replay_init(spec: ReplaySpec, mesh: Mesh) -> ReplayState:
    """Global replay state with leading dp axis, placed shard-per-chip."""
    from r2d2_tpu.parallel.mesh import dp_sharding
    dp = mesh.shape["dp"]
    state = replay_init(spec)
    state = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[None], (dp,) + x.shape), state)
    sharding = dp_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), state)


def make_sharded_replay_add(spec: ReplaySpec, mesh: Mesh):
    """add(state, block, shard_idx): ring-write ``block`` into one chip's
    shard (host feeder round-robins shard_idx). The block is broadcast and
    non-owners no-op — a few MB over ICI per 400 env steps."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("dp"), P(), P()), out_specs=P("dp"), check_vma=False)
    def add(state: ReplayState, block: Block, shard_idx):
        my = jax.lax.axis_index("dp")
        local = _shard0(state)

        def write(s):
            return replay_add(spec, s, block)

        local = jax.lax.cond(my == shard_idx[0], write, lambda s: s, local)
        return _unshard0(local)

    def add_fn(state, block, shard_idx: int):
        return add(state, block, jnp.asarray([shard_idx], jnp.int32))

    return jax.jit(add_fn, donate_argnums=0)


def _lane_group_size(num_lanes: int, dp: int) -> int:
    """The per-shard lane count, with the ONE divisibility check both
    sharded-anakin entry points share (Config and the loop re-state it
    earlier for explicit/resolved mesh.dp — this is the library-level
    backstop for direct callers)."""
    if num_lanes % dp != 0:
        raise ValueError(
            f"anakin lanes ({num_lanes}) must divide evenly across the "
            f"mesh's dp={dp} shards (lanes % dp == 0)")
    return num_lanes // dp


def init_sharded_act_carry(env, spec: ReplaySpec, num_lanes: int,
                           mesh: Mesh, key):
    """The sharded twin of actor/anakin.py init_act_carry: one fresh
    per-shard carry of ``num_lanes / dp`` lanes per chip, stacked on a
    leading dp axis and placed shard-per-chip. Shard s's RNG chain is
    ``fold_in(key, s)`` — the SAME construction tests reproduce when
    they build the per-shard reference path — so every shard's env
    schedules, ε draws and exploration streams are independent."""
    from r2d2_tpu.actor.anakin import init_act_carry
    from r2d2_tpu.parallel.mesh import dp_sharding
    dp = mesh.shape["dp"]
    lps = _lane_group_size(num_lanes, dp)
    carries = [init_act_carry(env, spec, lps, jax.random.fold_in(key, s))
               for s in range(dp)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *carries)
    return jax.device_put(stacked, dp_sharding(mesh))


def make_sharded_anakin_act(env, net, spec: ReplaySpec, *, mesh: Mesh,
                            num_lanes: int, epsilons, gamma: float,
                            priority, near_greedy_eps: float,
                            priority_eta: float = 0.9,
                            quant_probe: bool = True):
    """The dp-sharded fused acting segment (ISSUE 8 tentpole):

        act(params, carry, replay_state, weight_version)
            -> (carry, replay_state, shard_stats)

    ONE shard_map dispatch: each shard runs the SAME act core as the
    1x1-mesh path (actor/anakin.py make_act_core) over its own lane
    group of ``num_lanes / dp`` lanes — pure-JAX env steps, policy
    forward, ε-greedy, auto-reset, in-graph block assembly — then
    ring-writes its group's blocks STRAIGHT into its local replay shard
    via ``replay_add_many``. No host round-trip, no cross-shard block
    traffic: the only replicated inputs are the params and the publish
    clock, and nothing is reduced across shards (stats come back
    per-shard).

    Semantics vs dp=1:

      * the Ape-X ε ladder spans the GLOBAL lane count — shard s gets
        the contiguous slice [s*lps, (s+1)*lps) of the ``num_lanes``-
        wide ladder, exactly like a vector-actor fleet's lane split
        (config.vector_lane_epsilons), so dp changes WHERE lanes run,
        never the exploration schedule;
      * per-shard RNG chains come from the carry built by
        ``init_sharded_act_carry`` (fold_in(key, shard)) — shards
        explore and reset independently;
      * ``shard_stats`` carries (dp,)-shaped per-shard reductions
        (episodes, reported episodes/return sums, env steps) so the
        telemetry layer can surface per-shard balance without a
        cross-shard reduce inside the program.

    Carry and replay state are donated (the multi-GB obs buffers update
    in place, per shard)."""
    from r2d2_tpu.actor.anakin import make_act_core
    import numpy as np
    dp = mesh.shape["dp"]
    eps_list = [float(e) for e in epsilons]
    if len(eps_list) != num_lanes:
        raise ValueError(
            f"need one epsilon per GLOBAL lane: got {len(eps_list)} for "
            f"{num_lanes} lanes (the ladder spans all shards)")
    lps = _lane_group_size(num_lanes, dp)
    if lps > spec.num_blocks:
        raise ValueError(
            f"per-shard lane group ({lps} = {num_lanes} lanes / dp={dp}) "
            f"must be <= num_blocks ({spec.num_blocks}): each segment "
            "ring-writes one block per lane into the shard's local ring, "
            "whose scatter rows must not alias")
    eps_shards = jnp.asarray(eps_list, jnp.float32).reshape(dp, lps)
    report_shards = jnp.asarray(
        np.asarray([e <= near_greedy_eps for e in eps_list],
                   bool).reshape(dp, lps))
    core = make_act_core(env, net, spec, num_lanes=lps, gamma=gamma,
                         priority=priority, priority_eta=priority_eta,
                         quant_probe=quant_probe)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P("dp"), P("dp"), P(), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P("dp")), check_vma=False)
    def step(params, carry, replay_global, weight_version, eps, report):
        local_carry = _shard0(carry)
        local_replay = _shard0(replay_global)
        # lane provenance (ISSUE 10): shard s owns the contiguous slice
        # [s*lps, (s+1)*lps) of the GLOBAL ladder — the same layout the
        # eps reshape above encodes — so the stamps are derivable from
        # the axis index, no extra input
        my_lanes = (jax.lax.axis_index("dp") * lps
                    + jnp.arange(lps, dtype=jnp.int32))
        new_carry, blocks, stats = core(params, local_carry,
                                        weight_version, eps[0], report[0],
                                        lanes=my_lanes)
        local_replay = replay_add_many(spec, local_replay, blocks)
        shard_stats = {k: v[None] for k, v in stats.items()}
        # measured from the blocks that actually entered this shard's
        # ring, NOT a trace-time constant: under today's lockstep
        # program every shard emits full blocks every segment (so the
        # downstream imbalance ratio reads exactly 1.0 — asserted in
        # tests), but the signal follows the DATA, so a composition
        # that emits ragged/partial blocks per shard skews it for real
        shard_stats["env_steps"] = jnp.sum(
            blocks.learning_steps).astype(jnp.int32)[None]
        return (_unshard0(new_carry), _unshard0(local_replay), shard_stats)

    def act(params, carry, replay_state, weight_version):
        return step(params, carry, replay_state, weight_version,
                    eps_shards, report_shards)

    return jax.jit(act, donate_argnums=(1, 2))


def make_sharded_replay_add_many(spec: ReplaySpec, mesh: Mesh):
    """add_many(state, blocks, start_shard): ring-write K stacked blocks in
    ONE dispatch, round-robin across the dp shards — parity-exact with K
    sequential ``make_sharded_replay_add`` calls starting at ``start_shard``.

    Block k goes to shard ``(start_shard + k) % dp``; inside the single
    shard_map dispatch each shard scans the broadcast K-block batch and
    ring-writes its own strided subset in feed order (owner-conditional
    writes), so every shard's local pointer advances exactly as under the
    per-block path. The host pays one dispatch + one K-block transfer
    instead of K of each. K is a static shape (one compile per drain size).
    """
    dp = mesh.shape["dp"]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("dp"), P(), P()), out_specs=P("dp"), check_vma=False)
    def add_many(state: ReplayState, blocks: Block, start_shard):
        my = jax.lax.axis_index("dp")
        local = _shard0(state)
        k = blocks.priority.shape[0]

        def body(s, xs):
            blk, i = xs
            owner = (start_shard[0] + i) % dp
            return jax.lax.cond(
                my == owner, lambda st: replay_add(spec, st, blk),
                lambda st: st, s), None

        local, _ = jax.lax.scan(
            body, local, (blocks, jnp.arange(k, dtype=jnp.int32)))
        return _unshard0(local)

    def add_fn(state, blocks, start_shard: int):
        return add_many(state, blocks,
                        jnp.asarray([start_shard], jnp.int32))

    return jax.jit(add_fn, donate_argnums=0)


def _post_gradient_update(tx, optim: OptimConfig, use_double: bool,
                          train_state: TrainState, grads, key, loss,
                          mean_abs_td, mean_q):
    """Everything after the (already-reduced) gradients: Adam update,
    target-net sync schedule, metrics dict, TrainState advance. ONE
    implementation shared by the manual shard_map dp path and the GSPMD
    mp path so their step semantics cannot diverge."""
    updates, opt_state = tx.update(grads, train_state.opt_state,
                                   train_state.params)
    params = optax.apply_updates(train_state.params, updates)

    new_step = train_state.step + 1
    if use_double:
        sync = (new_step % optim.target_net_update_interval) == 0
        target_params = jax.tree_util.tree_map(
            lambda p, t: jnp.where(sync, p, t), params,
            train_state.target_params)
    else:
        target_params = train_state.target_params

    metrics = {
        "loss": loss,
        "mean_abs_td": mean_abs_td,
        "mean_q": mean_q,
        "grad_norm": optax.global_norm(grads),
    }
    train_state = train_state.replace(
        params=params, target_params=target_params,
        opt_state=opt_state, step=new_step, key=key)
    return train_state, metrics


def make_sharded_learner_step(net: NetworkApply, spec: ReplaySpec,
                              optim: OptimConfig, use_double: bool, mesh: Mesh,
                              steps_per_dispatch: int = 1, diag=None,
                              rdiag=None):
    """The dp-sharded fused step. Same contract as make_learner_step.

    ``steps_per_dispatch`` > 1 scans K per-shard steps inside the shard_map
    body (pmean in the scan body is legal under shard_map), so one host
    dispatch buys K sharded training steps — the same amortization
    make_multi_learner_step gives the single-chip path, with identical
    math (same RNG chain, same target-sync schedule; equivalence tested in
    tests/test_parallel.py). Metrics come back stacked (K,) per dispatch.

    ``mesh`` may carry an mp axis > 1 (dp x mp): the body then runs MANUAL
    over dp only and AUTO (GSPMD) over mp — pass the TrainState in with its
    wide feature dims sharded over mp (tensor_parallel.state_shardings) and
    the SPMD partitioner inserts the TP collectives inside the same fused
    sample-in-HBM step; replay stays dp-sharded (mp-replicated). This
    honors the "model sharding is a mesh-axis change" promise on the
    flagship device-replay path (VERDICT r3 #4).

    ``diag`` (telemetry.LearningDiag or None): the learning diagnostics,
    reduced to replicated outputs so they fit the step's P() metric specs —
    histograms psum across shards (one GLOBAL-batch histogram), scalars
    pmean, staleness via reduced pmin/pmax/pmean version stats (the raw
    per-sequence stamp vectors differ per shard and are omitted here).

    ``rdiag`` (telemetry.ReplayDiag or None): the replay-observability
    pillar (ISSUE 10) over the PER-SHARD rings — sample-count /
    eviction accounting stays shard-local, lane bincounts psum to one
    global composition, and the sum-tree snapshots all_gather to
    ``rd/shard_*`` arrays (leading dp axis) so the record carries BOTH
    per-shard and merged tree-health views (the prerequisite
    instrumentation for rebalancing a sharded replay, ROADMAP item 3).
    """
    loss_fn = make_loss_fn(net, spec, optim, use_double)
    tx = make_optimizer(optim)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    k = steps_per_dispatch

    def one_step(train_state: TrainState, replay_state: ReplayState, my):
        key, sample_base = jax.random.split(train_state.key)
        sample_key = jax.random.fold_in(sample_base, my)
        batch = replay_sample(spec, replay_state, sample_key)

        (loss, aux), grads = grad_fn(
            train_state.params, train_state.target_params, batch)
        # gradient allreduce over ICI — the only cross-chip traffic per step
        grads = jax.lax.pmean(grads, "dp")
        loss = jax.lax.pmean(loss, "dp")

        tree = tree_update(spec.tree_layers, replay_state.tree,
                           spec.prio_exponent, aux["priorities"], batch.idxes)
        replay_state = replay_state.replace(tree=tree)

        ld = {}
        if diag is not None:
            import optax as _optax
            from r2d2_tpu.telemetry.learning import fused_diagnostics
            ld = fused_diagnostics(
                net, spec, diag, train_state.step + 1, train_state.params,
                train_state.target_params, batch, aux, grads, loss,
                _optax.global_norm(grads), replay_state=replay_state,
                raw_arrays=False)
            # make every diagnostic replicated (out_specs P()): counts add,
            # scalars average, version extrema take the fleet min/max
            for kk in ("ld/td_hist", "ld/prio_hist", "ld/q_hist"):
                ld[kk] = jax.lax.psum(ld[kk], "dp")
            ld["ld/version_min"] = jax.lax.pmin(ld["ld/version_min"], "dp")
            ld["ld/version_max"] = jax.lax.pmax(ld["ld/version_max"], "dp")
            ld["ld/nonfinite"] = jax.lax.pmax(ld["ld/nonfinite"], "dp")
            for kk in ("ld/version_mean", "ld/unknown_frac",
                       "ld/delta_q_stored", "ld/delta_q_zero",
                       "ld/delta_q_recomputed", "ld/target_dist"):
                ld[kk] = jax.lax.pmean(ld[kk], "dp")
            # grad-group norms are computed from the pmean'd grads —
            # already replicated, no reduction needed

        if rdiag is not None:
            from r2d2_tpu.telemetry.replaydiag import (fused_replay_diag,
                                                       shard_replay_diag)
            replay_state, rd = fused_replay_diag(
                spec, rdiag, train_state.step + 1, replay_state, batch)
            # gather/psum OUTSIDE the lax.cond (off-interval NaNs reduce
            # to NaNs, which the host aggregator skips) so no collective
            # ever sits inside a branch
            ld.update(shard_replay_diag(rd, "dp"))

        train_state, metrics = _post_gradient_update(
            tx, optim, use_double, train_state, grads, key, loss,
            jax.lax.pmean(aux["mean_abs_td"], "dp"),
            jax.lax.pmean(aux["mean_q"], "dp"))
        metrics.update(ld)
        return train_state, replay_state, metrics

    # mp > 1 routes to the fully-GSPMD formulation: a shard_map body that is
    # manual over dp but auto over mp trips XLA's partitioner on the
    # cross-partition allreduce ("must be in (partial) manual partitioning
    # mode", measured round 4), so the composition is expressed without
    # manual collectives instead.
    if mesh.shape.get("mp", 1) > 1:
        return _make_gspmd_learner_step(net, spec, optim, use_double, mesh,
                                        steps_per_dispatch, diag=diag,
                                        rdiag=rdiag)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P("dp")), out_specs=(P(), P("dp"), P()),
        check_vma=False)
    def step(train_state: TrainState, replay_global: ReplayState):
        replay_state = _shard0(replay_global)
        my = jax.lax.axis_index("dp")
        if k == 1:
            ts, rs, metrics = one_step(train_state, replay_state, my)
        else:
            def body(carry, _):
                ts, rs = carry
                ts, rs, m = one_step(ts, rs, my)
                return (ts, rs), m

            (ts, rs), metrics = jax.lax.scan(
                body, (train_state, replay_state), None, length=k)
        return ts, _unshard0(rs), metrics

    return jax.jit(step, donate_argnums=(0, 1))


def _make_gspmd_learner_step(net: NetworkApply, spec: ReplaySpec,
                             optim: OptimConfig, use_double: bool, mesh: Mesh,
                             steps_per_dispatch: int = 1, diag=None,
                             rdiag=None):
    """The dp x mp fused step, expressed entirely in GSPMD terms.

    Identical math and RNG chain to the manual shard_map path (per-shard
    sample keys are ``fold_in(base, shard_index)``; gradients are the mean
    over shards; same target-sync schedule — parity-tested), but the dp
    axis is a vmapped leading dimension whose mean-reduction GSPMD lowers
    to the allreduce, and the mp axis shards the params' wide feature dims
    (tensor_parallel.state_shardings) with the partitioner inserting the TP
    collectives inside the same fused sample-in-HBM program. Used for
    mesh.mp > 1, where a manual-dp/auto-mp shard_map body fails to
    partition (see make_sharded_learner_step).
    """
    loss_fn = make_loss_fn(net, spec, optim, use_double)
    tx = make_optimizer(optim)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    k = steps_per_dispatch
    dp = mesh.shape["dp"]
    replay_sharding = NamedSharding(mesh, P("dp"))

    def one_step(train_state: TrainState, replay_global: ReplayState):
        key, sample_base = jax.random.split(train_state.key)
        keys = jax.vmap(lambda i: jax.random.fold_in(sample_base, i))(
            jnp.arange(dp))    # int32 indices, matching lax.axis_index
        batches = jax.vmap(lambda rs, sk: replay_sample(spec, rs, sk))(
            replay_global, keys)

        (loss_v, aux_v), grads_v = jax.vmap(
            grad_fn, in_axes=(None, None, 0))(
            train_state.params, train_state.target_params, batches)
        grads = jax.tree_util.tree_map(lambda g: g.mean(0), grads_v)

        trees = jax.vmap(
            lambda t, pr, idx: tree_update(spec.tree_layers, t,
                                           spec.prio_exponent, pr, idx))(
            replay_global.tree, aux_v["priorities"], batches.idxes)
        replay_global = replay_global.replace(
            tree=jax.lax.with_sharding_constraint(trees, replay_sharding))

        ld = {}
        if diag is not None:
            import optax as _optax
            from r2d2_tpu.telemetry.learning import fused_diagnostics
            # shard 0's local view: the per-shard idxes index per-shard
            # rings, so the ΔQ context (and with it the whole diagnostic
            # sub-batch) is taken from one shard — documented, and the
            # loss/grads fed in stay GLOBAL
            shard0 = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
            ld = fused_diagnostics(
                net, spec, diag, train_state.step + 1, train_state.params,
                train_state.target_params, shard0(batches), shard0(aux_v),
                grads, loss_v.mean(), _optax.global_norm(grads),
                replay_state=shard0(replay_global))

        if rdiag is not None:
            from r2d2_tpu.telemetry.replaydiag import fused_replay_diag
            # vmap over shards keeps sample-count/eviction accounting
            # shard-local; the (dp, …) outputs ARE the per-shard views
            # (the manual path reaches the same layout via all_gather)
            replay_global, rdm = jax.vmap(
                lambda rs, b: fused_replay_diag(
                    spec, rdiag, train_state.step + 1, rs, b)
            )(replay_global, batches)
            replay_global = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, replay_sharding), replay_global)
            if "rd/lane_counts" in rdm:
                ld["rd/lane_counts"] = rdm.pop("rd/lane_counts").sum(0)
            ld.update({k.replace("rd/", "rd/shard_"): v
                       for k, v in rdm.items()})

        train_state, metrics = _post_gradient_update(
            tx, optim, use_double, train_state, grads, key, loss_v.mean(),
            aux_v["mean_abs_td"].mean(), aux_v["mean_q"].mean())
        metrics.update(ld)
        return train_state, replay_global, metrics

    def step(train_state: TrainState, replay_global: ReplayState):
        if k == 1:
            return one_step(train_state, replay_global)

        def body(carry, _):
            ts, rs = carry
            ts, rs, m = one_step(ts, rs)
            return (ts, rs), m

        (ts, rs), metrics = jax.lax.scan(
            body, (train_state, replay_global), None, length=k)
        return ts, rs, metrics

    return jax.jit(step, donate_argnums=(0, 1))


def sharded_buffer_steps(state: ReplayState) -> int:
    """Total stored learning steps across all shards."""
    return int(jnp.sum(state.learning_steps))
