"""Rank-aware multi-controller training over a multi-host mesh.

This is the production DCN scaling path (SURVEY §5.8): every process
(host) is one JAX controller over its local chips; ``jax.distributed``
stitches them into one global mesh whose 'dp' axis spans all chips. The
reference has no analog — its scaling unit is one learner process on half
a GPU (/root/reference/worker.py:251), and scaling actors beyond one
machine would need a Ray cluster it never configures.

Design:

  * **Each host owns its own actors** (Ape-X ε ladder over the GLOBAL
    actor index), its own feeder queue, and its own weight store. Blocks
    feed only the host's local replay shards — zero cross-host experience
    traffic; the gradient ``pmean`` inside the sharded step is the only
    per-step DCN collective.
  * **Lockstep by construction.** Multi-controller JAX requires every
    process to enter the same compiled programs in the same order. Every
    loop iteration dispatches exactly one ``lockstep_ingest`` program
    (per-shard conditional ring-writes + psum'd global counters + stop
    consensus), reads back its REPLICATED outputs (identical on every
    host by construction), and — iff those say ready — dispatches exactly
    one sharded train step. Every control-flow decision derives from
    replicated values, so every host takes the same branch; host-local
    timing (queue depth, sleeps, signals) only changes iteration *data*,
    never dispatch *order*.
  * **Stop consensus**: each host contributes a local stop flag (signal,
    deadline) to the ingest program; the psum makes any host's stop
    everyone's stop on the same iteration — no host is left blocked in a
    collective whose peers exited.
  * **Rank 0 de-duplicates side effects**: checkpoints and metrics logs
    (params are replicated bit-identically everywhere, so this loses
    nothing).
  * **Fleet observability** (ISSUE 12, ``telemetry.fleet_enabled``):
    the lockstep row carries per-rank step-time gauges (straggler
    argmax in-graph, zero extra DCN dispatches), every rank measures
    compute vs blocked-in-collective time and runs a local AlertEngine
    (ranks > 0: firings -> alerts_host{r}.jsonl), and rank 0's
    FleetAggregator merges host rows into the record's ``fleet`` block
    — see telemetry/fleet.py and README "Fleet observability".

Scope: thread- OR process-mode actors (process mode gives each host a
spawned CPU-pinned actor fleet fed through the native shm ring, exactly
like the single-host orchestrator), device OR host replay placement
(host = one reference-style CPU HostReplay per process feeding the GSPMD
external-batch step per-step, with a tiny psum consensus program instead
of lockstep_ingest — make_lockstep_consensus), single
player, dp x mp meshes (mesh.mp > 1 feature-shards the wide params over
mp via the GSPMD learner step and GSPMD lockstep ingest; mp must divide
each host's device count so every dp row stays host-local). Resume/
warm-start work rank-consistently (every controller restores the same
checkpoint file from the shared filesystem). Unsupported combinations
raise immediately.

Multiplayer population training composes as ONE MULTIHOST JOB PER PLAYER:
set ``multiplayer.player_id`` on each job (player 0's actors host the
games, every other player's actor gidx joins game gidx). Each player's
stack is an independent mesh job; players interact only through the game
engine's host/join sockets, not through collectives — so there is no
cross-player lockstep, and any player job can restart independently.
See README "Multiplayer at pod scale"; the two-job loopback test
(tests/test_parallel.py) runs two concurrent player jobs end-to-end.

Demo / validation (two loopback controllers, virtual CPU devices):

    python -m r2d2_tpu.parallel.multihost            # launcher
"""

import functools
import os
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from r2d2_tpu.config import Config, apex_epsilon
from r2d2_tpu.replay.structs import Block, ReplaySpec, empty_block_np


class LocalActorFleet:
    """One host's actor workers (threads OR spawned processes) with
    PlayerStack-style supervision.

    Restarts are purely host-local (they touch no collective state, so
    lockstep is unaffected) and must NEVER propagate an exception into the
    lockstep learner loop — a host crashing mid-collective abandons every
    peer until the jax.distributed heartbeat timeout, exactly the failure
    the stop consensus exists to prevent. A failed respawn is logged and
    retried on the next supervision tick instead.

    ``queue``: pass the host's BlockQueue when workers are PROCESSES so a
    producer crash between reserve and commit gets its shm ring slot
    reclaimed (RingRecoveryScheduler semantics; no-op for thread fleets
    and non-shm transports).

    ``health``: pass a runtime.feeder.WorkerHealth to enable hang
    detection, restart backoff, and the crash-loop breaker — the SAME
    policy object PlayerStack uses, so single-host and multihost get
    identical supervision semantics. None (default) keeps the plain
    dead-worker scan."""

    def __init__(self, spawn_fn: Callable[[int], object], n: int,
                 restart_dead: bool, stop, queue=None, health=None):
        from r2d2_tpu.runtime.feeder import RingRecoveryScheduler
        self._spawn = spawn_fn
        self._restart = restart_dead
        self._stop = stop
        self._queue = queue
        self.health = health
        self._ring_recovery = RingRecoveryScheduler()
        self._seen_dead: set = set()
        self.threads: List[object] = [spawn_fn(i) for i in range(n)]

    def _respawn(self, i: int):
        """Respawn wrapper: a failure is logged and retried next tick
        (never propagated into the lockstep loop — see class docstring)."""
        import logging
        try:
            return self._spawn(i)
        except Exception:
            logging.getLogger(__name__).exception(
                "actor %d respawn failed; will retry next supervision "
                "tick", i)
            return None

    def supervise(self) -> int:
        """Respawn dead (and, with ``health``, hung) workers; returns the
        number restarted (logged). Ring reclamation runs for newly-failed
        workers regardless of the restart flag (the wedge exists either
        way)."""
        import logging

        from r2d2_tpu.runtime.feeder import supervise_workers
        if self._stop.is_set():
            return 0
        restarted = supervise_workers(
            self.threads, self._seen_dead,
            respawn=self._respawn if self._restart else None,
            ring=self._ring_recovery if self._queue is not None else None,
            health=self.health)
        if self._queue is not None:
            freed = self._ring_recovery.tick(self._queue)
            if self.health is not None:
                self.health.ring_slots_recovered += freed
        if restarted:
            logging.getLogger(__name__).warning(
                "restarted %d dead actor worker(s)", restarted)
        return restarted

    def join(self, timeout: float = 5.0) -> None:
        for t in self.threads:
            t.join(timeout=timeout)
            if t.is_alive() and hasattr(t, "terminate"):   # process worker
                t.terminate()


def make_lockstep_ingest(spec: ReplaySpec, mesh, fleet: bool = False):
    """One jitted program per loop iteration: conditional per-shard block
    writes, global counters, and stop consensus.

    Inputs (global shapes, 'dp'-sharded): replay state; cum_env (dp,) i32
    cumulative ingested learning-steps per shard; blocks stacked with a
    leading dp axis (each host fills only its local shards' rows — at most
    one valid row per host per iteration); valid (dp,) i32; stop (dp,) i32.
    Outputs: new state, new cum_env, and a dict of REPLICATED scalars:
    buffer_steps (live steps in the ring), filled_shards (shards holding
    data — the dp ready-gate), env_steps (cumulative), stop (>0 = any
    host requested stop).

    ``fleet=True`` (ISSUE 12) appends one (dp,) f32 operand — each host
    fills its owned rows with its previous iteration's wall step time —
    and widens the replicated info dict with the skew gauges: the
    all-gathered per-row step-time and cumulative-env-step tables,
    sum/max/min reductions, and a one-hot argmax so every rank learns
    the straggler's dp-row identity in-graph. Same single dispatch —
    zero extra collectives on the DCN critical path. ``fleet=False``
    compiles the exact PR-10 program (the kill-switch contract).

    mp > 1 routes to the GSPMD formulation (vmap over the dp-leading
    state, scalar sums lowering to the allreduces) for the same reason as
    the learner step: a manual-dp/auto-mp shard_map body fails to
    partition. Identical contract; the manual path stays for mp == 1.
    """
    import jax
    import jax.numpy as jnp
    from r2d2_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from r2d2_tpu.parallel.sharded import _shard0, _unshard0
    from r2d2_tpu.replay.device_replay import replay_add

    if mesh.shape.get("mp", 1) > 1:
        return _make_gspmd_lockstep_ingest(spec, mesh, fleet)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P("dp"),) * (6 if fleet else 5),
        out_specs=(P("dp"), P("dp"), P()),
        check_vma=False)
    def ingest(state, cum_env, blocks, valid, stop, *times):
        local = _shard0(state)
        blk = jax.tree_util.tree_map(lambda x: x[0], blocks)
        local = jax.lax.cond(
            valid[0] > 0, lambda s: replay_add(spec, s, blk),
            lambda s: s, local)
        added = jnp.where(valid[0] > 0, blk.learning_steps.sum(), 0)
        cum = cum_env[0] + added.astype(jnp.int32)
        my_steps = local.learning_steps.sum()
        info = {
            "buffer_steps": jax.lax.psum(my_steps, "dp"),
            "filled_shards": jax.lax.psum(
                (my_steps > 0).astype(jnp.int32), "dp"),
            "env_steps": jax.lax.psum(cum, "dp"),
            "stop": jax.lax.psum(stop[0], "dp"),
        }
        if fleet:
            t = times[0][0]
            tmax = jax.lax.pmax(t, "dp")
            onehot = (t >= tmax).astype(jnp.int32)   # 1 on the straggler
            idx = jax.lax.axis_index("dp")
            info.update({
                "step_times": jax.lax.all_gather(t, "dp"),
                "step_time_sum": jax.lax.psum(t, "dp"),
                "step_time_max": tmax,
                "step_time_min": jax.lax.pmin(t, "dp"),
                # one-hot argmax: pmax picks the highest tied row
                "straggler_shard": jax.lax.pmax(
                    jnp.where(onehot > 0, idx, -1), "dp"),
                "env_steps_shards": jax.lax.all_gather(cum, "dp"),
            })
        return _unshard0(local), cum[None], info

    return jax.jit(ingest, donate_argnums=(0, 1))


def _make_gspmd_lockstep_ingest(spec: ReplaySpec, mesh, fleet: bool = False):
    """The dp x mp lockstep ingest: same contract as make_lockstep_ingest
    (incl. the fleet gauge widening — the reductions/argmax lower to
    GSPMD allreduces, the tables to replicating constraints), expressed
    without manual collectives (the replay stays dp-sharded /
    mp-replicated; the scalar reductions become GSPMD allreduces).

    Known trade-off: the vmapped ``lax.cond`` lowers through select, so an
    invalid row still pays its block write's bandwidth before being
    discarded — including no-op spin iterations. This cannot be avoided
    with a second counters-only program: the lockstep invariant requires
    every host to dispatch the SAME program each iteration, and block
    presence is host-local state, so program selection may never depend on
    it. Bounded cost: a few MB per iteration during the fill phase,
    mp > 1 meshes only."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from r2d2_tpu.replay.device_replay import replay_add

    sharding = NamedSharding(mesh, P("dp"))
    replicated = NamedSharding(mesh, P())

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def ingest(state, cum_env, blocks, valid, stop, *times):
        def add_row(s, blk, v):
            return jax.lax.cond(v > 0, lambda ss: replay_add(spec, ss, blk),
                                lambda ss: ss, s)

        state = jax.vmap(add_row)(state, blocks, valid)
        state = jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), state)
        added = jnp.where(
            valid > 0,
            jax.vmap(lambda b: b.learning_steps.sum())(blocks), 0)
        cum_env = cum_env + added.astype(jnp.int32)
        my_steps = jax.vmap(lambda s: s.learning_steps.sum())(state)
        info = {
            "buffer_steps": my_steps.sum(),
            "filled_shards": (my_steps > 0).astype(jnp.int32).sum(),
            "env_steps": cum_env.sum(),
            "stop": stop.sum(),
        }
        if fleet:
            t = times[0]
            # tables replicate (hosts cannot device_get non-addressable
            # dp shards of a multi-controller array — the PR5 lesson)
            info.update({
                "step_times": jax.lax.with_sharding_constraint(
                    t, replicated),
                "step_time_sum": t.sum(),
                "step_time_max": t.max(),
                "step_time_min": t.min(),
                "straggler_shard": jnp.argmax(t).astype(jnp.int32),
                "env_steps_shards": jax.lax.with_sharding_constraint(
                    cum_env, replicated),
            })
        return state, cum_env, info

    return ingest


def _write_host_telemetry_row(writer, rank: int, tele,
                              t_start: float, resources=None,
                              stages=None, fleet_block=None,
                              stage_counts=None, clock_anchor=None,
                              actors_per_rank=None, engine=None) -> None:
    """One per-host aggregated telemetry row per log interval. Rank 0's
    stage summary rides the main TrainMetrics record (it owns the
    player's metrics files); every other rank appends compact rows here so
    a pod-wide view exists without breaking the rank-0-deduplicates-side-
    effects rule — tools/inspect.py reads both. With the resource pillar
    on (ISSUE 7) the row also carries this host's ``resources`` block
    (its own devices + RSS/CPU — resource state is host-local).

    Under the fleet plane (ISSUE 12) the row widens: a ``wall`` clock
    stamp (rank 0 ages other ranks' rows off it — the missing_rank
    signal), this rank's ``fleet`` timing block, its CUMULATIVE
    ``stage_counts`` (mergeable by elementwise add into the rank-0 fleet
    view), the lockstep-iteration-1 ``clock_anchor`` the trace merge
    aligns ranks on, and ``actors_per_rank`` (maps actor span files to
    ranks). ``engine`` runs this rank's local AlertEngine over the row
    itself, so its ``alerts`` block sees the same interval it describes
    and firings land in alerts_host{r}.jsonl. ``stages`` overrides the
    default interval summary (rank 0's interval is consumed by the main
    record, so its own fleet-mode row carries the cumulative summary).
    ``writer`` is a RotatingJsonlWriter — host rows are size-capped."""
    row = {"t": round(time.time() - t_start, 3), "rank": rank,
           "stages": (tele.interval_summary() if stages is None
                      else stages),
           "telemetry_dropped_spans": tele.spans.dropped}
    if resources is not None:
        row["resources"] = resources.block()
    if fleet_block is not None:
        row["wall"] = round(time.time(), 3)
        row["fleet"] = fleet_block
        if stage_counts is not None:
            row["stage_counts"] = stage_counts
        if clock_anchor is not None:
            row["clock_anchor"] = clock_anchor
        if actors_per_rank is not None:
            row["actors_per_rank"] = actors_per_rank
    if engine is not None:
        row["alerts"] = engine.evaluate(row)
    writer.write(row)


def owned_dp_rows(mesh) -> List[int]:
    """dp rows whose devices (all mp columns) live on THIS process.
    Host-local data (experience blocks, host-replay batches) can only feed
    rows this process owns, so an mp-spanning row is a hard scope error."""
    import jax

    rows = mesh.devices.reshape(mesh.shape["dp"], -1)   # (dp, mp)
    me = jax.process_index()
    owners = []
    for r in range(rows.shape[0]):
        procs = {d.process_index for d in rows[r]}
        if len(procs) != 1:
            raise NotImplementedError(
                f"dp row {r} spans processes {sorted(procs)} — with "
                "mesh.mp > 1, mp must divide each host's device count "
                "so every dp row (and its mp replicas) stays on one "
                "host")
        owners.append(procs.pop())
    return [r for r, o in enumerate(owners) if o == me]


def _local_dp_values(arr) -> np.ndarray:
    """This process's rows of a dp-sharded 1-D array, in global-index order
    (= the order this process supplied them to
    ``make_array_from_process_local_data``). mp-replicated shards of the
    same dp row are deduplicated by index."""
    shards = {}
    for s in arr.addressable_shards:
        start = s.index[0].start or 0
        shards.setdefault(start, np.asarray(s.data))
    return np.concatenate([shards[k] for k in sorted(shards)])


def make_lockstep_consensus(mesh, fleet: bool = False):
    """The host-replay twin of lockstep_ingest's counter/stop outputs: a
    tiny psum program every iteration. Each process contributes
    [buffer_steps, env_steps, ready, stop] ONCE (on its first owned dp
    row; zero rows elsewhere); the psum over dp returns the same sums on
    every host, so every control-flow decision downstream is replicated —
    the lockstep invariant with no device replay involved.

    ``fleet=True`` (ISSUE 12) widens the row to 5 columns — col 4 is
    this host's previous-iteration step time in µs — and the program
    additionally all-gathers the raw (dp, 5) row table, so every rank
    reads the full per-rank step-time/env-step picture off the SAME
    dispatch; the sum/max/min/argmax gauges derive from the table over
    each rank's first owned row (the only row a host fills). fleet=False
    compiles the exact PR-10 (dp, 4) psum."""
    import jax
    from r2d2_tpu.parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from r2d2_tpu.telemetry.fleet import mesh_row_ranks, rank_first_rows

    sharding = NamedSharding(mesh, P("dp"))
    local_rows = owned_dp_rows(mesh)
    ncols = 5 if fleet else 4
    if fleet:
        row_ranks = mesh_row_ranks(mesh)
        first_rows = rank_first_rows(row_ranks, len(set(row_ranks)))

        @jax.jit
        def psum_rows(x):                                   # (dp, 5) int32
            def body(v):
                return (jax.lax.psum(v, "dp"),
                        jax.lax.all_gather(v, "dp", axis=0, tiled=True))
            # check_vma off: the all-gathered table IS replicated, the
            # static check just cannot infer it (same waiver as the
            # lockstep ingest program)
            return shard_map(body, mesh=mesh, in_specs=P("dp"),
                             out_specs=(P(), P()), check_vma=False)(x)
    else:
        @jax.jit
        def psum_rows(x):                                   # (dp, 4) int32
            return shard_map(lambda v: jax.lax.psum(v, "dp"),
                             mesh=mesh, in_specs=P("dp"), out_specs=P())(x)

    def consense(buffer_steps: int, env_steps: int, ready: bool,
                 stop_flag: int, step_time_s: float = 0.0) -> dict:
        rows = np.zeros((len(local_rows), ncols), np.int32)
        vals = [buffer_steps, env_steps, int(bool(ready)), int(stop_flag)]
        if fleet:
            # µs in int32: cap at 2000 s so the cast can never overflow
            vals.append(int(min(max(step_time_s, 0.0), 2000.0) * 1e6))
        rows[0] = vals
        x = jax.make_array_from_process_local_data(sharding, rows)
        if fleet:
            summed, table = psum_rows(x)
            out = np.asarray(summed).reshape(-1, ncols)[0]
        else:
            out = np.asarray(psum_rows(x)).reshape(-1, ncols)[0]
        info = {"buffer_steps": int(out[0]), "env_steps": int(out[1]),
                "ready_procs": int(out[2]), "stop": int(out[3])}
        if fleet:
            table = np.asarray(table).reshape(-1, ncols)
            times = table[:, 4].astype(np.float64) / 1e6        # (dp,) s
            per_rank = times[first_rows]
            info.update({
                "step_times": times,
                "step_time_sum": float(per_rank.sum()),
                "step_time_max": float(per_rank.max()),
                "step_time_min": float(per_rank.min()),
                "straggler_shard": int(
                    first_rows[int(np.argmax(per_rank))]),
                "env_steps_shards": table[:, 1].astype(np.int64),
            })
        return info

    return consense


class HostFeed:
    """Builds each iteration's global ingest operands from process-local
    blocks: a (dp,)-leading stacked Block whose rows are zeros except this
    host's round-robin target shard, plus the valid/stop flag vectors.
    Every leaf goes through ``jax.make_array_from_process_local_data`` so
    no host ever needs another host's data."""

    def __init__(self, spec: ReplaySpec, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.spec = spec
        self.sharding = NamedSharding(mesh, P("dp"))
        # row ownership: every dp row's devices (its mp columns) must live
        # on ONE host — blocks are fed host-locally (owned_dp_rows raises
        # on an mp-spanning row)
        self.local_rows = owned_dp_rows(mesh)
        me = jax.process_index()
        if not self.local_rows:
            raise ValueError(
                f"process {me} owns no mesh shards — mesh.dp must cover "
                f"every participating host's devices")
        lo, hi = self.local_rows[0], self.local_rows[-1]
        if self.local_rows != list(range(lo, hi + 1)):
            raise NotImplementedError(
                "non-contiguous per-process mesh rows are not supported "
                f"(process {me} owns {self.local_rows})")
        self.local_dp = len(self.local_rows)
        self._zero = empty_block_np(spec)
        self._rr = 0
        # the all-zero (blocks, valid, stop) triple for block=None, stop=0
        # iterations, built once: ingest_fn does not donate these operands,
        # so reusing them avoids a full zero-block allocation + H2D
        # transfer per no-op iteration (the pre-ready fill phase spins on
        # exactly these)
        self._noop = self._build(None, 0)


    def build(self, block: Optional[Block], stop_flag: int):
        """Returns (blocks, valid, stop) global arrays for lockstep_ingest.
        ``block`` lands in the next local shard (round-robin); None = no-op
        iteration (all-invalid rows, reused from the prebuilt triple)."""
        if block is None and not stop_flag:
            return self._noop
        return self._build(block, stop_flag)

    def times(self, step_time_s: float):
        """The fleet-widened ingest's (dp,) f32 timing operand: every
        owned row carries this host's previous-iteration step time
        (seconds). Built fresh per iteration — it changes every time, so
        there is nothing to reuse (and it is 4 bytes per dp row)."""
        import jax
        arr = np.full((self.local_dp,), step_time_s, np.float32)
        return jax.make_array_from_process_local_data(self.sharding, arr)

    def _build(self, block: Optional[Block], stop_flag: int):
        import jax

        stacked = {}
        target = self._rr
        for name, zero in self._zero.items():
            rows = np.broadcast_to(
                zero[None], (self.local_dp,) + zero.shape).copy()
            if block is not None:
                rows[target] = np.asarray(getattr(block, name))
            stacked[name] = jax.make_array_from_process_local_data(
                self.sharding, rows)
        valid = np.zeros((self.local_dp,), np.int32)
        if block is not None:
            valid[target] = 1
            self._rr = (self._rr + 1) % self.local_dp
        stop = np.full((self.local_dp,), int(stop_flag), np.int32)
        return (Block(**stacked),
                jax.make_array_from_process_local_data(self.sharding, valid),
                jax.make_array_from_process_local_data(self.sharding, stop))


def train_multihost(cfg: Config, *, max_training_steps: Optional[int] = None,
                    max_seconds: Optional[float] = None,
                    actor_mode: str = "thread",
                    log_fn: Callable[[dict], None] = None) -> dict:
    """The rank-aware ``train()``: run this same function on every host of
    the pod (SPMD controllers). Blocks until done; returns a summary dict
    {step, env_steps, buffer_steps, params} for this process.
    """
    import jax

    if actor_mode not in ("thread", "process"):
        raise ValueError(f"actor_mode must be 'thread' or 'process', got "
                         f"{actor_mode!r}")
    if cfg.multiplayer.enabled and cfg.multiplayer.player_id < 0:
        raise NotImplementedError(
            "multihost training runs ONE player's stack per job: set "
            "multiplayer.player_id to this job's player index and launch "
            "one multihost job per player (players interact only through "
            "the game engine's host/join sockets, never through "
            "collectives — README \"Multiplayer at pod scale\"). "
            "multiplayer.player_id=-1 (whole population in-process) is the "
            "single-host orchestrator's mode.")
    if cfg.replay.placement not in ("device", "host"):
        raise ValueError(
            f"unknown replay.placement {cfg.replay.placement!r}")
    host_mode = cfg.replay.placement == "host"
    # fleet observability plane (ISSUE 12): widened lockstep gauges,
    # per-iteration compute-vs-wait timing, the rank-0 fleet block,
    # per-rank alert engines, clock-anchored host rows
    fleet_on = cfg.telemetry.enabled and cfg.telemetry.fleet_enabled
    from r2d2_tpu.telemetry.learning import LearningAggregator, LearningDiag
    # learning diagnostics (ISSUE 5): fused into the lockstep step like
    # the single-host path; only rank 0 aggregates (it owns TrainMetrics)
    learn_diag = LearningDiag.from_config(cfg)
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.learner.train_step import create_train_state
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.parallel.mesh import init_distributed, make_mesh
    from r2d2_tpu.parallel.sharded import (
        make_sharded_learner_step, sharded_replay_init)
    from r2d2_tpu.runtime.checkpoint import apply_restore, save_checkpoint
    from r2d2_tpu.runtime.feeder import BlockQueue
    from r2d2_tpu.runtime.metrics import TrainMetrics
    from r2d2_tpu.runtime.weights import InProcWeightStore

    init_distributed(cfg.mesh)
    rank, nprocs = jax.process_index(), jax.process_count()

    spec = ReplaySpec.from_config(cfg)
    probe = create_env(cfg.env, seed=cfg.runtime.seed)
    action_dim = probe.action_space.n
    probe.close()
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)

    # quantized inference (ISSUE 14): the accuracy-probe aggregator for
    # this host's THREAD actors (process children probe-free, the
    # single-host rule); rank 0 wires it into the record below so the
    # quant block + quant_divergence rule cover fleet mode too
    quant_stats = None
    if cfg.network.inference_dtype != "f32":
        from r2d2_tpu.telemetry import QuantStats
        quant_stats = QuantStats(cfg.network.inference_dtype,
                                 cfg.telemetry.quant_probe_interval)

    # identical seed on every host -> identical initial params; the pmean'd
    # updates keep them identical forever (tested single-host; the loopback
    # demo asserts it cross-process)
    ts = create_train_state(jax.random.PRNGKey(cfg.runtime.seed), net,
                            cfg.optim)
    # Resume/warm-start: every rank restores the SAME checkpoint file
    # (shared filesystem, the normal pod setup): identical host values on
    # every controller, so lockstep and cross-host param equality hold
    # from step one — the same property the fresh-init path gets from the
    # shared seed. The replay ring restarts empty, as in single-host
    # resume. apply_restore is the one shared restore policy (also the
    # single-host Learner's), so the two paths cannot diverge.
    ts, resumed_env = apply_restore(cfg.runtime, ts)
    mesh = make_mesh(cfg.mesh)
    if mesh.shape["mp"] > 1:
        # pod-scale tensor parallelism: wide params feature-sharded over
        # mp, the GSPMD learner step + GSPMD lockstep ingest (both routed
        # automatically by their factories), replay dp-sharded /
        # mp-replicated. HostFeed validates that every dp row stays on one
        # host. Identical init on every rank keeps the mp shards
        # rank-consistent the same way replication does for mp=1.
        from r2d2_tpu.parallel.tensor_parallel import state_shardings
        ts = jax.device_put(ts, state_shardings(ts, mesh))
    dp = mesh.shape["dp"]
    from jax.sharding import NamedSharding, PartitionSpec as P
    if host_mode:
        # Host-placement lockstep (the reference-style CPU replay under the
        # multi-controller loop): each process owns ONE HostReplay fed by
        # its own actors (dp = independent per-host data, like the device
        # path's per-shard rings); every iteration dispatches the tiny
        # consensus psum instead of lockstep_ingest, and — iff the
        # replicated outputs say ready — every process samples its share
        # of the global batch, assembles it dp-sharded, and dispatches the
        # SAME GSPMD external-batch step (gradients reduce over the global
        # batch automatically). Priority write-back stays host-local, with
        # HostReplay's monotonic staleness guard intact. Per-step dispatch
        # (k=1): sampling happens on the host between steps, so there is
        # no k-step scan to fuse — same as the single-host host path.
        from r2d2_tpu.learner.train_step import make_external_batch_step
        from r2d2_tpu.replay.host_replay import HostReplay
        if spec.batch_size % dp:
            raise ValueError(
                f"replay.batch_size={spec.batch_size} is not divisible by "
                f"mesh dp={dp} — the batch axis cannot shard evenly")
        local_rows_n = len(owned_dp_rows(mesh))
        local_batch = spec.batch_size * local_rows_n // dp
        # per-rank seed: each host's replay samples ITS OWN distribution
        host_replay = HostReplay(spec, seed=cfg.runtime.seed + 7919 * rank)
        consense = make_lockstep_consensus(mesh, fleet=fleet_on)
        ext_step = make_external_batch_step(net, spec, cfg.optim,
                                            cfg.network.use_double,
                                            diag=learn_diag)
        batch_sharding = NamedSharding(mesh, P("dp"))
        if mesh.shape["mp"] == 1:
            # replicate the state across the mesh (mp > 1 already placed
            # feature-sharded above); identical host values on every rank
            ts = jax.device_put(ts, NamedSharding(mesh, P()))
        env_local = 0
        if cfg.runtime.steps_per_dispatch > 1:
            # same warning the single-host host path emits: sampling
            # happens on the host between steps, so there is no k-step
            # scan to fuse
            import logging
            logging.getLogger(__name__).warning(
                "runtime.steps_per_dispatch=%d is ignored under "
                "replay.placement='host' (host sampling is per-step)",
                cfg.runtime.steps_per_dispatch)
        k = 1
    else:
        rs = sharded_replay_init(spec, mesh)
        cum_env = jax.device_put(np.zeros((dp,), np.int32),
                                 NamedSharding(mesh, P("dp")))

        k = cfg.runtime.resolved_steps_per_dispatch()
        step_fn = make_sharded_learner_step(
            net, spec, cfg.optim, cfg.network.use_double, mesh,
            steps_per_dispatch=k, diag=learn_diag)
        ingest_fn = make_lockstep_ingest(spec, mesh, fleet=fleet_on)
        feed = HostFeed(spec, mesh)

    # -- local actors (this host's share of the global fleet) --
    # The stop event must be shareable with spawned children in process
    # mode; both Event kinds serve the lockstep loop identically.
    n_local = cfg.actor.num_actors
    publisher = None
    if actor_mode == "process":
        import multiprocessing as mp
        from r2d2_tpu.runtime.actor_main import actor_process_main
        from r2d2_tpu.runtime.weights import WeightPublisher
        ctx = mp.get_context("spawn")
        stop = ctx.Event()
        # quantized inference (ISSUE 14): publish the inference bundle
        # (f32 + quantized twin + stamp) through the same segment — the
        # shared publish-time hook, so the lockstep fleet's actors
        # stream the same publish-time twin single-host actors do
        from r2d2_tpu.runtime.weights import (make_publish_preparer,
                                              wrap_publish)
        prep = make_publish_preparer(net)
        publisher = WeightPublisher(
            prep(ts.params, 1) if prep else ts.params)
        try:
            queue = BlockQueue(
                use_mp=True, ctx=ctx,
                shm_spec=spec if cfg.runtime.shm_transport else None)
        except BaseException:
            # the publisher's /dev/shm segment was already created; don't
            # leak it past a failed ring bring-up (round-4 review) — the
            # try/finally that normally owns both starts only at fleet
            # construction below
            publisher.close()
            raise
        publish = wrap_publish(publisher.publish, prep,
                               lambda: publisher.publish_count)
        # weight fan-out tree (ISSUE 15): this host's relay tier of the
        # fleet-wide tree — the rank's learner publishes ONCE to its
        # root segment, shm relays re-publish, and the host's local
        # actors subscribe to leaf relays (the root sees <= degree
        # readers per host no matter the local fan-out). Relays carry
        # the stamped quant bundle unchanged.
        shm_fanout = None
        if cfg.fleet.fanout_degree >= 2:
            from r2d2_tpu.fleet.fanout import ShmFanout
            try:
                shm_fanout = ShmFanout(
                    publisher.name,
                    prep(ts.params, 0) if prep else ts.params,
                    n_local, cfg.fleet.fanout_degree)
                shm_fanout.pump()   # adopt the construction publish
            except BaseException:
                queue.close()
                publisher.close()
                raise
            _root_publish = publish

            def publish(params, _pub=_root_publish, _f=shm_fanout):
                _pub(params)
                _f.pump()
    else:
        stop = threading.Event()
        shm_fanout = None

    # SIGTERM/SIGINT land on the stop event, which feeds the next
    # iteration's local_stop flag into the psum consensus — the signaled
    # host keeps dispatching until every controller agrees to stop on the
    # SAME iteration, instead of abandoning peers mid-collective (they
    # would wedge until the jax.distributed heartbeat timeout).
    import signal
    prev_handlers = {}
    if threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            stop.set()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):
                pass

    # Per-player-job multiplayer (README "Multiplayer at pod scale"): this
    # job's player index drives the host/join wiring and the seed offset.
    # pid=0 when multiplayer is off, so every single-player formula below
    # is unchanged. Game index = the actor's GLOBAL index (player 0's job
    # hosts games 0..total_actors-1; player p's actor gidx joins game
    # gidx), so all player jobs must configure the same actor fan-out.
    pid = cfg.multiplayer.player_id if cfg.multiplayer.enabled else 0
    # host/join args OBSERVED by this host's envs (thread mode; the fake
    # env records what the factory resolved) — returned in the summary so
    # per-player-job launches can assert the wiring end-to-end. Keyed by
    # actor slot (not appended): supervisor respawns re-record, never
    # duplicate.
    observed_wiring = [None] * n_local

    # Crash-recovery rank-0 twin (ISSUE 18): the single-host learner's
    # durable replay snapshot plane, mirrored into the lockstep loop.
    # Active on the shapes where rank 0 addresses the WHOLE ring (one
    # controller, dp=1, device placement — the single-controller pod and
    # the loop's test reality); wider pods log the gap once and rely on
    # checkpoint resume alone (ROADMAP 4b: dp-sharded snapshot cuts).
    # The ring twin is a host RingAccountant advanced per ingested block
    # — the same mirror discipline as the single-host Learner's.
    snap_writer = None
    snap_ring = None
    capture_plain = None
    if cfg.runtime.snapshot_interval > 0 and rank == 0 and not host_mode:
        import logging
        if nprocs > 1 or dp > 1:
            logging.getLogger(__name__).warning(
                "runtime.snapshot_interval=%d: the rank-0 replay "
                "snapshot twin needs a rank-0-addressable ring "
                "(nprocs=1, dp=1; got nprocs=%d dp=%d) — replay "
                "snapshots are skipped, checkpoint resume still works",
                cfg.runtime.snapshot_interval, nprocs, dp)
        else:
            from r2d2_tpu.replay.snapshot import (SnapshotWriter,
                                                  capture_plain,
                                                  load_snapshot,
                                                  restore_plain)
            from r2d2_tpu.replay.structs import RingAccountant
            snap_ring = RingAccountant(spec.num_blocks)
            snap_writer = SnapshotWriter(cfg.runtime.save_dir or ".", pid)
            if cfg.runtime.resume and cfg.runtime.restore_replay:
                snap = load_snapshot(cfg.runtime.save_dir or ".", pid)
                if snap is not None and snap.get("kind") == "plain":
                    rs0 = jax.tree_util.tree_map(lambda x: x[0], rs)
                    restored0 = restore_plain(spec, rs0, snap_ring, snap)
                    # re-pin the restored plain cut under the dp axis on
                    # the sharded state's own placement
                    rs = jax.tree_util.tree_map(
                        lambda r0, full: jax.device_put(
                            np.asarray(jax.device_get(r0))[None],
                            full.sharding),
                        restored0, rs)
                    logging.getLogger(__name__).warning(
                        "rank-0 twin restored %d replay block(s) from "
                        "the step-%s snapshot", snap_ring.total_adds,
                        snap.get("step"))

    if actor_mode == "process":
        def spawn_actor(i: int):
            # player_idx=pid / actor_idx=gidx reproduces the thread path's
            # seed formula (seed + 10_000*pid + 100*gidx) inside
            # actor_process_main; total_actors sizes the vector ε ladder
            # over the GLOBAL fleet (rank-local num_actors x nprocs)
            gidx = rank * n_local + i
            eps = apex_epsilon(gidx, nprocs * n_local, cfg.actor.base_eps,
                               cfg.actor.eps_alpha)
            heartbeats.reset_slot(i)
            if tele_board is not None:
                tele_board.reset_slot(i)
            seg = (shm_fanout.segment_for(i) if shm_fanout is not None
                   else publisher.name)
            p = ctx.Process(
                target=actor_process_main,
                args=(cfg.to_dict(), pid, gidx, eps, seg,
                      queue._q, stop),
                kwargs={**cfg.multiplayer.env_args(pid, gidx),
                        "total_actors": nprocs * n_local,
                        "health_board": heartbeats, "health_slot": i,
                        "telemetry_board": tele_board},
                daemon=True, name=f"actor-p{pid}h{rank}-{i}")
            p.start()
            return p
    else:
        # quantized inference (ISSUE 14): same publish-time bundle hook
        # as the process path / the single-host orchestrator
        from r2d2_tpu.runtime.weights import (make_publish_preparer,
                                              wrap_publish)
        prep = make_publish_preparer(net)
        store = InProcWeightStore(prep(ts.params, 1) if prep else ts.params)
        publish = wrap_publish(store.publish, prep,
                               lambda: store.publish_count)
        queue = BlockQueue(use_mp=False)

        def spawn_actor(i: int) -> threading.Thread:
            gidx = rank * n_local + i
            eps = apex_epsilon(gidx, nprocs * n_local, cfg.actor.base_eps,
                               cfg.actor.eps_alpha)
            seed = cfg.runtime.seed + 10_000 * pid + 100 * gidx
            # shared scalar/vector construction (runtime/actor_loop.py):
            # env_factory routes through THIS module's create_env symbol,
            # global gidx + fleet total size the vector ε ladder
            from r2d2_tpu.runtime.actor_loop import (make_actor_env,
                                                     make_actor_policy)
            env = make_actor_env(cfg, pid, gidx, seed,
                                 env_factory=create_env,
                                 name=f"p{pid}h{rank}a{i}",
                                 num_players=cfg.multiplayer.num_players,
                                 **cfg.multiplayer.env_args(pid, gidx))
            # vector envs expose lanes; wiring is identical across a
            # worker's lanes, so record lane 0's
            uw = getattr(env, "envs", [env])[0]
            uw = getattr(uw, "unwrapped", uw)
            observed_wiring[i] = getattr(uw, "multiplayer_wiring", None)
            # store.current: the prepared published tree (no per-policy
            # requantization) that is also FRESH on a mid-training
            # respawn — the predecessor consumed this reader's version,
            # so a first poll() would return None against stale params
            policy, run_loop = make_actor_policy(
                cfg, net, store.current(reader_id=i), gidx, seed,
                epsilon=eps, total_actors=nprocs * n_local,
                quant_stats=quant_stats)

            # per-spawn cancel event + instrumented sink: identical health
            # wiring to PlayerStack._spawn_thread_actor
            cancel = threading.Event()

            def should_stop(cancel=cancel):
                return stop.is_set() or cancel.is_set()

            from r2d2_tpu.runtime.actor_loop import instrument_block_sink
            heartbeats.reset_slot(i)
            sink = instrument_block_sink(
                cfg, i,
                lambda b, should_stop=should_stop, slot=i: queue.put_patient(
                    b, should_stop,
                    beat=lambda: heartbeats.touch(slot),
                    telemetry=tele),
                board=heartbeats, telemetry=tele,
                # generation stamp, same contract as the single-host
                # thread spawner (reader_id matches weight_poll below)
                weight_version=lambda reader_id=i:
                    store.reader_version(reader_id),
                # lane provenance (ISSUE 10): gidx is the GLOBAL worker
                # index across the multihost fleet — the ladder layout
                # the ε spread above uses
                lane_base=gidx * cfg.actor.envs_per_actor)

            def loop(env=env, policy=policy, run_loop=run_loop,
                     reader_id=i, sink=sink, should_stop=should_stop):
                # the run loop owns env and closes it on every exit
                run_loop(cfg, env, policy,
                         block_sink=sink,
                         weight_poll=lambda: store.poll(reader_id),
                         should_stop=should_stop,
                         telemetry=tele)

            t = threading.Thread(target=loop, daemon=True,
                                 name=f"actor-h{rank}-{i}")
            t.health_cancel = cancel
            t.start()
            return t

    # worker health is host-local by construction (heartbeats, backoff,
    # breaker touch no collective state) — the same board+policy objects
    # the single-host PlayerStack uses, so supervision semantics are
    # identical across the two paths. Created HERE, immediately before the
    # try that owns its shm segment (the spawn closures above bind late);
    # nothing between this allocation and the finally can raise past it.
    from r2d2_tpu.runtime.feeder import HeartbeatBoard, WorkerHealth
    heartbeats = HeartbeatBoard(n_local)
    health = WorkerHealth.from_runtime(n_local, heartbeats, cfg.runtime)

    # per-rank fleet telemetry (ISSUE 4) — host-local like the health
    # subsystem (no collective state): thread actors observe straight into
    # this rank's Telemetry; process actors publish through the shm board,
    # which interval_summary() differences. Rank 0's summary joins the
    # TrainMetrics record; other ranks append per-host rows.
    # shm allocation ONLY here (no file I/O — that sits inside the try
    # below, whose finally owns these segments' close())
    from r2d2_tpu.telemetry import Telemetry, TelemetryBoard
    tele = Telemetry.from_config(cfg, name=f"learner-h{rank}")
    tele_board = None
    if cfg.telemetry.enabled and actor_mode == "process":
        tele_board = TelemetryBoard(n_local)
        tele.attach_board(tele_board)

    # fleet construction onward sits inside the try: a spawn failure for
    # actor k must not orphan the k-1 already-running actor processes on a
    # live shm ring — the finally unwinds them (round-4 review)
    fleet = None
    resources = None
    compile_mon = None
    try:
        if cfg.telemetry.enabled:
            resume = bool(cfg.runtime.resume)
            if not resume:
                # fresh run: clear this rank's actors' stale span files
                # (the spawned processes APPEND so supervisor respawns
                # keep their predecessors' spans)
                for i in range(n_local):
                    try:
                        os.remove(os.path.join(
                            cfg.runtime.save_dir or ".",
                            f"spans_p{pid}_a{rank * n_local + i}.jsonl"))
                    except OSError:
                        pass
            tele.start_drain(os.path.join(
                cfg.runtime.save_dir or ".", f"spans_host{rank}.jsonl"),
                append=resume)
        fleet = LocalActorFleet(
            spawn_actor, n_local, cfg.runtime.restart_dead_actors, stop,
            queue=queue if actor_mode == "process" else None,
            health=health)

        # pid-keyed logs/checkpoints: per-player jobs sharing a filesystem
        # write train_player{pid}.log and player-pid checkpoint dirs, like
        # the in-process population path (ref worker.py:35-37)
        metrics = (TrainMetrics(pid, cfg.runtime.save_dir,
                                resume=bool(cfg.runtime.resume))
                   if rank == 0 else None)
        if metrics is not None:
            metrics.set_telemetry(tele)   # stages ride the rank-0 record
            if quant_stats is not None:
                # quant accuracy block (ISSUE 14) on the rank-0 record
                metrics.set_quant(quant_stats.interval_block)
        # rank-0 learning aggregation: the 'learning' block (+ NaN
        # forensics) rides the same rank-0 record as everything else
        learn_agg = (LearningAggregator(pid, cfg.runtime.save_dir,
                                        cfg.telemetry.nan_policy,
                                        cfg.optim.lr)
                     if metrics is not None and learn_diag is not None
                     else None)
        # system-health pillar (ISSUE 7), rank-aware: EVERY rank samples
        # its own devices/host/actor-slots (resource state is host-local,
        # like the health and stage telemetry above) and owns its own
        # compile monitor (compile events are process-global per rank
        # process). Rank 0's block + the alert engine ride the main
        # TrainMetrics record — the rank-0-deduplicates-side-effects rule
        # — while other ranks' compact blocks join their per-host
        # telemetry rows.
        if cfg.telemetry.enabled and cfg.telemetry.resources_enabled:
            from r2d2_tpu.telemetry import (AlertEngine, CompileMonitor,
                                            ResourceMonitor, active_monitor,
                                            default_rules)
            from r2d2_tpu.telemetry.resources import (clear_player_buffers,
                                                      pytree_nbytes,
                                                      register_buffer)
            clear_player_buffers(pid)   # previous same-process run's entries
            register_buffer(f"p{pid}/train_state", pytree_nbytes(ts))
            if not host_mode:
                register_buffer(f"p{pid}/replay_ring", pytree_nbytes(rs))
            if cfg.telemetry.compile_enabled and active_monitor() is None:
                compile_mon = CompileMonitor().install()
            resources = ResourceMonitor(
                pid, cfg.runtime.save_dir or ".",
                interval_s=cfg.telemetry.resources_interval_s,
                headroom_warn_frac=(
                    cfg.telemetry.resources_headroom_warn_frac),
                board=tele_board, compile_monitor=compile_mon)
            if metrics is not None:
                metrics.set_resources(resources.block)
                if cfg.telemetry.alerts_enabled:
                    metrics.set_sentinel(AlertEngine(
                        default_rules(cfg.telemetry),
                        jsonl_path=os.path.join(
                            cfg.runtime.save_dir or ".",
                            f"alerts_player{pid}.jsonl"),
                        resume=bool(cfg.runtime.resume)))
        pub_count = ((lambda: publisher.publish_count)
                     if publisher is not None
                     else (lambda: store.publish_count))
        # -- fleet observability plane (ISSUE 12) --
        # Host rows move to the size-capped rotating writer (rotation
        # applies with or without the fleet switch — the unbounded-growth
        # fix stands on its own); rank 0 writes a row too UNDER THE FLEET
        # PLANE ONLY (uniform per-rank inspector panels + the clock
        # anchor), keeping the pre-PR12 file set when it is off. Every
        # rank tracks its lockstep timing in a FleetAggregator; ranks > 0
        # additionally run a local AlertEngine over their own rows
        # (firings -> alerts_host{r}.jsonl) — until now they evaluated no
        # rules at all. Same append-on-resume contract as TrainMetrics.
        from r2d2_tpu.telemetry.fleet import (
            FLEET_INFO_KEYS, FleetAggregator, RotatingJsonlWriter,
            cumulative_stage_matrix, host_alerts_path, host_row_path,
            mesh_row_ranks, stage_counts_dict, summarize_stage_counts)
        host_writer = None
        if tele.enabled and (rank != 0 or fleet_on):
            host_writer = RotatingJsonlWriter(
                host_row_path(cfg.runtime.save_dir or ".", rank),
                max_bytes=cfg.telemetry.fleet_host_row_max_bytes,
                resume=bool(cfg.runtime.resume))
        elif rank == 0 and not cfg.runtime.resume:
            # fleet (or telemetry) off on a FRESH run: a previous
            # fleet-on run's rank-0 host row must not leak into this
            # run's inspector view / trace merge — the pre-PR12
            # file-set contract the kill switch promises
            for suffix in ("", ".1"):
                try:
                    os.remove(host_row_path(
                        cfg.runtime.save_dir or ".", rank) + suffix)
                except OSError:
                    pass
        fleet_mon = None
        host_engine = None
        if fleet_on:
            fleet_mon = FleetAggregator(
                rank, nprocs, mesh_row_ranks(mesh),
                save_dir=cfg.runtime.save_dir or ".",
                missing_age_s=cfg.telemetry.alerts_missing_rank_age_s)
            if (rank != 0 and cfg.telemetry.resources_enabled
                    and cfg.telemetry.alerts_enabled):
                from r2d2_tpu.telemetry import AlertEngine, default_rules
                host_engine = AlertEngine(
                    default_rules(cfg.telemetry),
                    jsonl_path=host_alerts_path(
                        cfg.runtime.save_dir or ".", rank),
                    resume=bool(cfg.runtime.resume))
        # chaos straggler hook (tests only, R2D2_MH_CHAOS_STRAGGLER=
        # "rank:slowxF"): the named rank stretches every iteration's
        # compute phase by ~F (sleep proportional to its own last step
        # time) — the injected straggler the fleet gauges must name
        straggler_factor = 0.0
        chaos_straggler = os.environ.get("R2D2_MH_CHAOS_STRAGGLER", "")
        if chaos_straggler:
            r_s, _, kind = chaos_straggler.partition(":")
            if int(r_s) == rank:
                from r2d2_tpu.tools.chaos import parse_fault_spec
                straggler_factor = parse_fault_spec(f"0:{kind}")[0].factor
        t_run_start = time.time()
        max_steps = max_training_steps or cfg.optim.training_steps
        deadline = time.time() + max_seconds if max_seconds else None
        rt = cfg.runtime
        ratio = cfg.replay.max_env_steps_per_train_step
        step_count = int(ts.step)  # nonzero after resume; max_steps cumulative
        step_base = step_count     # rate-limiter budget counts from THIS
        paused = False             # process's start
        last_ckpt_step = step_count   # last step a checkpoint covered
        pending_losses: list = []
        last_log = last_supervise = time.time()
        info = {"buffer_steps": 0, "env_steps": 0, "filled_shards": 0}

        halt_error: list = []

        def flush_losses():
            if pending_losses and metrics is not None:
                t0 = time.perf_counter()
                arrays = jax.device_get(pending_losses)
                tele.observe("learner/device_sync",
                             time.perf_counter() - t0)
                for arr in arrays:
                    for loss in np.atleast_1d(arr):
                        metrics.on_train_step(float(loss))
            pending_losses.clear()
            if learn_agg is not None:
                # occupancy ages: host placement has the ring mirror right
                # here (this rank's HostReplay accountant); under the
                # device-placement lockstep ingest the stamps live only
                # device-side, so occupancy stays a single-host/host-mode
                # feature — sample ages flow either way
                occ = (host_replay.ring.live_versions() if host_mode
                       else None)
                try:
                    metrics.set_learning(learn_agg.flush(
                        step_count, publish_count=pub_count(),
                        occupancy_versions=occ))
                except RuntimeError as e:
                    if "nan_policy=halt" not in str(e):
                        raise
                    # nan_policy=halt under lockstep: raising out of the
                    # loop on rank 0 alone would abandon the other ranks
                    # mid-collective (they would wedge until the
                    # jax.distributed heartbeat timeout — the same hazard
                    # the SIGTERM path routes around). Feed the shared
                    # stop consensus instead: every rank exits the loop on
                    # the SAME iteration, then rank 0 re-raises after the
                    # clean unwind.
                    halt_error.append(e)
                    stop.set()

        debug = bool(os.environ.get("R2D2_MH_DEBUG"))
        chaos_kill_at = int(os.environ.get("R2D2_MH_CHAOS_KILL_ACTOR", "0"))
        chaos_done = False
        it = 0
        while step_count < max_steps:
            it += 1
            if straggler_factor > 1.0 and fleet_mon is not None:
                # injected compute slowdown (chaos straggler hook):
                # genuinely stretches this rank's iteration by ~factor
                time.sleep(min((straggler_factor - 1.0)
                               * fleet_mon.last_step_s, 0.25))
            local_stop = int(stop.is_set()
                             or (deadline is not None
                                 and time.time() > deadline))
            block = None
            if not paused:
                drained = queue.drain(1)
                block = drained[0] if drained else None
            if host_mode:
                if block is not None:
                    host_replay.add(block)
                    # learning_steps.sum(), not block_length: partial
                    # blocks (episode boundaries) carry zero-step slots —
                    # same accounting as lockstep_ingest's device path
                    env_local += int(np.sum(np.asarray(
                        block.learning_steps)))
                t0 = time.perf_counter()
                info = consense(len(host_replay), env_local,
                                len(host_replay) > 0, local_stop,
                                step_time_s=(fleet_mon.last_step_s
                                             if fleet_mon else 0.0))
                if fleet_mon is not None:
                    t_coll = time.perf_counter() - t0
                    fleet_mon.on_collective(info, t_coll)
                    tele.observe("lockstep/dispatch", t_coll)
                    info = {kk: v for kk, v in info.items()
                            if kk not in FLEET_INFO_KEYS}
            else:
                t0 = time.perf_counter()
                args = feed.build(block, local_stop)
                if fleet_mon is not None:
                    args = args + (feed.times(fleet_mon.last_step_s),)
                rs, cum_env, dev_info = ingest_fn(rs, cum_env, *args)
                fetched = jax.device_get(dev_info)
                t_coll = time.perf_counter() - t0
                info = {kk: int(v) for kk, v in fetched.items()
                        if kk not in FLEET_INFO_KEYS}
                if fleet_mon is not None:
                    # the dispatch+readback is the pod's synchronization
                    # point: blocked time here IS the price of skew
                    fleet_mon.on_collective(fetched, t_coll)
                    tele.observe("lockstep/dispatch", t_coll)
                if block is not None:
                    # only real ingests count — the pre-ready no-op spin
                    # iterations would otherwise dominate the histogram
                    tele.observe("ingest/commit", t_coll)
                    if snap_ring is not None:
                        # ring twin: same accounting replay_add applied
                        # in-graph, kept host-side for the snapshot cut
                        snap_ring.advance(
                            int(np.sum(np.asarray(block.learning_steps))),
                            int(np.asarray(block.weight_version)))
            if debug:
                print(f"[mh rank={rank} it={it}] step={step_count} "
                      f"block={block is not None} {info}", flush=True)
            if metrics is not None and block is not None:
                ret = float(np.asarray(block.sum_reward))
                metrics.on_block(0, None if np.isnan(ret) else ret)
            if info["stop"] > 0:
                break

            # every decision below uses only replicated values -> every
            # host takes the same branch (the lockstep invariant)
            if host_mode:
                ready = (info["ready_procs"] == nprocs
                         and info["buffer_steps"]
                         >= cfg.replay.learning_starts)
            else:
                ready = (info["filled_shards"] == dp
                         and info["buffer_steps"]
                         >= cfg.replay.learning_starts)
            paused = bool(
                ready and ratio > 0
                and info["env_steps"] >= cfg.replay.learning_starts
                    + ratio * max(step_count - step_base, 1))
            if ready:
                prev = step_count
                if host_mode:
                    t0 = time.perf_counter()
                    batch_np, snapshot = host_replay.sample(local_batch)
                    gbatch = jax.tree_util.tree_map(
                        lambda a: jax.make_array_from_process_local_data(
                            batch_sharding, np.asarray(a)), batch_np)
                    t1 = time.perf_counter()
                    tele.observe("learner/sample", t1 - t0)
                    ts, m = ext_step(ts, gbatch)
                    tele.observe("learner/train_dispatch",
                                 time.perf_counter() - t1)
                    # Pin the layout before the per-host split: the step is
                    # sharding-agnostic by design (its compiled output
                    # layout follows GSPMD's choice), so a compiler change
                    # that replicated or resharded priorities would
                    # silently hand _local_dp_values wrong-length data.
                    # device_put is a no-op when the layout already matches
                    # and an explicit reshard when it does not.
                    prios_local = _local_dp_values(
                        jax.device_put(m["priorities"], batch_sharding))
                    if len(prios_local) != len(batch_np.idxes):
                        raise RuntimeError(
                            f"priority write-back shape drift: "
                            f"{len(prios_local)} local priorities for "
                            f"{len(batch_np.idxes)} sampled idxes "
                            "(dp-sharded step output no longer matches "
                            "this host's batch rows)")
                    t0 = time.perf_counter()
                    host_replay.update_priorities(
                        batch_np.idxes, prios_local, snapshot)
                    tele.observe("learner/priority_writeback",
                                 time.perf_counter() - t0)
                    if learn_agg is not None and "ld/weight_versions" in m:
                        # the (B,) stamp/idx passthroughs keep the batch's
                        # global dp sharding, which rank 0 cannot
                        # device_get across hosts — substitute this rank's
                        # LOCAL sampled values (already host numpy; the
                        # same distribution rank 0 trained on). The
                        # reduced histograms/scalars are GSPMD reduction
                        # outputs and fetch fine.
                        m["ld/weight_versions"] = np.asarray(
                            batch_np.weight_version)
                        m["ld/batch_idxes"] = np.asarray(batch_np.idxes)
                else:
                    t0 = time.perf_counter()
                    ts, rs, m = step_fn(ts, rs)
                    tele.observe("learner/train_dispatch",
                                 time.perf_counter() - t0)
                step_count += k
                if metrics is not None:   # only rank 0 flushes; don't
                    pending_losses.append(m["loss"])   # accumulate elsewhere
                if learn_agg is not None:
                    learn_agg.on_dispatch(m)
                boundary = lambda iv: iv and step_count // iv > prev // iv
                if boundary(rt.weight_publish_interval):
                    t0 = time.perf_counter()
                    publish(ts.params)
                    tele.observe("weights/publish",
                                 time.perf_counter() - t0)
                if rank == 0 and boundary(rt.save_interval):
                    save_checkpoint(
                        rt.save_dir, cfg.env.game_name,
                        step_count // rt.save_interval, pid, ts.params,
                        ts.opt_state, ts.target_params, step_count,
                        resumed_env + info["env_steps"],
                        config_json=cfg.to_json())
                    last_ckpt_step = step_count
                    if rt.keep_checkpoints > 0:
                        # retention GC twin (ISSUE 18): same rank-0
                        # dedup rule as the other side effects
                        from r2d2_tpu.runtime.checkpoint import \
                            prune_checkpoints
                        prune_checkpoints(rt.save_dir, cfg.env.game_name,
                                          pid, rt.keep_checkpoints)
                if snap_writer is not None and boundary(
                        rt.snapshot_interval):
                    # async durable replay snapshot off the train path —
                    # capture (device→host) here at the commit boundary,
                    # serialization rides the writer thread
                    rs0 = jax.tree_util.tree_map(lambda x: x[0], rs)
                    snap_writer.submit(capture_plain(
                        spec, rs0, snap_ring, step_count))
            else:
                time.sleep(0.01)

            if (chaos_kill_at and not chaos_done
                    and actor_mode == "process" and it >= chaos_kill_at):
                # chaos hook (tests only, R2D2_MH_CHAOS_KILL_ACTOR=<it>):
                # SIGKILL one actor child mid-run, then tick supervision
                # immediately — the fleet must detect the corpse, reclaim
                # any shm ring slot it held between reserve and commit,
                # and respawn, all without disturbing the lockstep loop
                # (restarts are host-local by design, see LocalActorFleet)
                victim = fleet.threads[0]
                victim.kill()
                victim.join(5.0)
                chaos_restarted = fleet.supervise()
                import json as _json
                with open(os.path.join(rt.save_dir,
                                       f"chaos_kill_r{rank}.json"),
                          "w") as f:
                    _json.dump({"iteration": it,
                                "restarted": chaos_restarted,
                                "victim_exitcode": victim.exitcode}, f)
                chaos_done = True

            now = time.time()
            if now - last_supervise >= rt.supervise_interval_s:
                fleet.supervise()   # every host tends its own actor fleet
                last_supervise = now
                if resources is not None:
                    # resource sampling rides the supervision cadence,
                    # exactly like the single-host PlayerStack
                    resources.maybe_sample(now)
                if compile_mon is not None and step_count > step_base:
                    # this process has trained: the lockstep program (and
                    # the actor policies it feeds) compiled during warm-up
                    compile_mon.mark_warm()
            if now - last_log >= rt.log_interval:
                if metrics is not None:
                    flush_losses()
                    metrics.env_steps = resumed_env + info["env_steps"]
                    metrics.set_buffer_size(info["buffer_steps"])
                    metrics.set_actor_health(health.snapshot())
                    if fleet_mon is not None:
                        # the rank-0 fleet block: local lockstep timing +
                        # the gauge tables + the cross-host merge (other
                        # ranks' host-row ages and stage histograms)
                        metrics.set_fleet(fleet_mon.flush(
                            now=now,
                            local_stage_counts=stage_counts_dict(
                                cumulative_stage_matrix(tele))))
                    record = metrics.log(now - last_log)
                    if fleet_mon is not None and host_writer is not None:
                        # rank 0's own host row (fleet plane only): the
                        # clock anchor + cumulative stage counts for the
                        # per-rank panels — its INTERVAL summary was just
                        # consumed by the record, so the row carries the
                        # cumulative one
                        cum = cumulative_stage_matrix(tele)
                        _write_host_telemetry_row(
                            host_writer, rank, tele, t_run_start,
                            stages=summarize_stage_counts(
                                stage_counts_dict(cum)),
                            fleet_block=record.get("fleet"),
                            stage_counts=stage_counts_dict(cum),
                            clock_anchor=fleet_mon.clock_anchor,
                            actors_per_rank=n_local)
                    if log_fn:
                        log_fn({"rank": rank, **record})
                elif tele.enabled:
                    # ranks > 0 have no TrainMetrics (rank 0 de-duplicates
                    # side effects) but their pipeline still needs
                    # observability: one aggregated per-host row per
                    # interval (plus, under the fleet plane, this rank's
                    # timing block, mergeable stage counts, clock anchor,
                    # and its local alert engine's verdict)
                    fb = sc = None
                    if fleet_mon is not None:
                        fb = fleet_mon.flush(now=now)
                        sc = stage_counts_dict(
                            cumulative_stage_matrix(tele))
                    _write_host_telemetry_row(
                        host_writer, rank, tele, t_run_start,
                        resources=resources, fleet_block=fb,
                        stage_counts=sc,
                        clock_anchor=(fleet_mon.clock_anchor
                                      if fleet_mon else None),
                        actors_per_rank=(n_local if fleet_mon else None),
                        engine=host_engine)
                last_log = now
            if fleet_mon is not None:
                # close the iteration: its duration feeds the NEXT
                # iteration's psum row (a one-iteration lag — irrelevant
                # at alerting cadence) and the lockstep/step histogram.
                # The first call only arms the clock (returns 0.0) and
                # must not count as a sub-µs sample.
                step_s = fleet_mon.on_step()
                if step_s > 0:
                    tele.observe("lockstep/step", step_s)
        flush_losses()
        # preemption-safe final checkpoint (same contract as the
        # single-host Learner.save_final): a clean stop — signal fed
        # through the stop consensus, deadline, or max_steps — between
        # periodic saves writes one last rank-0 checkpoint so the pod
        # resumes from the stop point, not the last interval boundary.
        # Reached only on the clean path (every rank broke out of the
        # loop together), so params are consistent across hosts.
        if (rank == 0 and rt.save_interval
                and step_count > last_ckpt_step):
            save_checkpoint(
                rt.save_dir, cfg.env.game_name,
                step_count // rt.save_interval + 1, pid, ts.params,
                ts.opt_state, ts.target_params, step_count,
                resumed_env + info["env_steps"],
                config_json=cfg.to_json())
            if rt.keep_checkpoints > 0:
                from r2d2_tpu.runtime.checkpoint import prune_checkpoints
                prune_checkpoints(rt.save_dir, cfg.env.game_name, pid,
                                  rt.keep_checkpoints)
        if snap_writer is not None:
            # final synchronous snapshot (Learner.save_final's contract):
            # the stop point's replay contents, not the last interval's
            rs0 = jax.tree_util.tree_map(lambda x: x[0], rs)
            snap_writer.write_now(capture_plain(
                spec, rs0, snap_ring, step_count))
        if halt_error:
            # deferred nan_policy=halt (see flush_losses): every rank left
            # the loop via the stop consensus; now fail loudly on rank 0
            raise halt_error[0]
    finally:
        stop.set()
        if snap_writer is not None:
            snap_writer.stop()
        for sig, handler in prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass
        if fleet is not None:
            fleet.join(timeout=5.0)
        if shm_fanout is not None:
            # relays close BEFORE the root publisher (each holds a
            # subscriber on the root/parent segment)
            shm_fanout.close()
        if publisher is not None:
            publisher.close()
        queue.close()    # releases/unlinks the shm ring (owner side)
        heartbeats.close()   # releases/unlinks the heartbeat board
        tele.close()         # stops the drain thread, final flush
        if tele_board is not None:
            tele_board.close()
        if compile_mon is not None:
            # restore the pxla logger exactly (level/propagation) and
            # release this rank process's active-monitor slot
            compile_mon.uninstall()

    return {"step": step_count, "env_steps": resumed_env + info["env_steps"],
            "buffer_steps": info["buffer_steps"], "params": ts.params,
            "player_id": pid, "actor_wiring": observed_wiring}


# ---------------------------------------------------------------------------
# Producer-only host (ISSUE 16): actors on a host with NO replay shards
# emit into the usual BlockQueue; this pump drains stacked groups and
# ships them over the replay service's socket rung.  Config validation
# rejects fleet.replay_shards x mesh.multihost (the sharded service is a
# single-controller plane), so a multihost fleet reaches a remote
# ReplayService exclusively through this producer-side wiring — the
# learner host runs the service + ReplayServiceServer, producer hosts
# run their actor loops plus run_replay_producer against it.


def run_replay_producer(queue, host: str, port: int, *,
                        window: int = 1, group: int = 8,
                        stop: Optional[threading.Event] = None,
                        seconds: Optional[float] = None) -> dict:
    """Drain ``queue`` (a runtime.feeder.BlockQueue fed by this host's
    actor fleet) into the remote ReplayService at ``host:port`` until
    ``stop`` is set or ``seconds`` elapse.

    ``group`` is the stacked-frame size (mirrors
    ``fleet.ingest_batch_blocks`` on the serving side: one frame becomes
    one grouped ingest dispatch there) and ``window`` the pipelined
    in-flight frame bound (``fleet.socket_window``).  Blocks ship in
    arrival order, so the server-side routing (round-robin or lane) sees
    the exact sequence a local fleet would have produced.  Returns
    {"blocks_sent", "frames_sent", "blocks_acked"} — acked==sent after
    the final flush unless the connection died."""
    from r2d2_tpu.fleet.replay_service import (RemoteReplayProducer,
                                               ReplayProducerPump)
    producer = RemoteReplayProducer(host, port, window=window)
    pump = ReplayProducerPump(queue, producer, group=group)
    try:
        pump.run(stop=stop, seconds=seconds)
    finally:
        stats = {"blocks_sent": pump.blocks_sent,
                 "frames_sent": producer.frames_sent,
                 "blocks_acked": producer.blocks_acked}
        producer.close()
    return stats


# ---------------------------------------------------------------------------
# Loopback demo/validation: N controller processes on one machine, virtual
# CPU devices, fake env — the full rank-aware loop end-to-end (the test in
# tests/test_parallel.py runs this).

def _demo_config(save_dir: str) -> "Config":
    return Config().replace(**{
        "env.game_name": "Fake",
        "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
        "network.hidden_dim": 16, "network.cnn_out_dim": 32,
        "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
        "sequence.burn_in_steps": 4, "sequence.learning_steps": 5,
        "sequence.forward_steps": 3,
        "replay.capacity": 800, "replay.block_length": 20,
        "replay.batch_size": 4, "replay.learning_starts": 60,
        "actor.num_actors": 1,
        "runtime.save_dir": save_dir, "runtime.save_interval": 4,
        "runtime.log_interval": 2.0, "runtime.weight_publish_interval": 2,
        "runtime.steps_per_dispatch": 2,
        "mesh.multihost": True,
    })


def _demo_worker(process_id: int, num_processes: int, coordinator: str,
                 devices_per_process: int, save_dir: str,
                 max_steps: int, resume: str = "",
                 actor_mode: str = "thread", mp: int = 1,
                 player_id: int = -1, num_players: int = 2,
                 num_actors: int = 1, placement: str = "device",
                 envs_per_actor: int = 1) -> None:
    from r2d2_tpu.utils.platform import pin_cpu_platform
    pin_cpu_platform(devices_per_process)
    import jax

    n_global = num_processes * devices_per_process
    cfg = _demo_config(save_dir).replace(**{
        "mesh.coordinator_address": coordinator,
        "mesh.num_processes": num_processes, "mesh.process_id": process_id,
        "mesh.dp": n_global // mp, "mesh.mp": mp,
        "actor.num_actors": num_actors,
        "actor.envs_per_actor": envs_per_actor,
        "replay.placement": placement,
        **({"runtime.resume": resume} if resume else {}),
        **({"multiplayer.enabled": True, "multiplayer.player_id": player_id,
            "multiplayer.num_players": num_players}
           if player_id >= 0 else {}),
    })
    out = train_multihost(cfg, max_training_steps=max_steps, max_seconds=240,
                          actor_mode=actor_mode)

    # Bit-exactness evidence, asserted in two layers: replicated leaves'
    # local shards identical within this process here (mp-SHARDED leaves
    # carry different slices per device by design, so they digest as the
    # gathered global array), and the full-tree digest identical ACROSS
    # processes by launch_demo (the cross-host invariant README
    # advertises).
    import hashlib
    import json
    os.makedirs(save_dir, exist_ok=True)   # no checkpoint may have created it
    if cfg.mesh.mp > 1:
        # the tp run must GENUINELY shard (a silently-replicated "tp" run
        # would pass every other check)
        assert any(not l.sharding.is_fully_replicated
                   for l in jax.tree_util.tree_leaves(out["params"])), \
            "mp > 1 but every param leaf is replicated"
    digest = hashlib.sha256()
    for path, leaf in sorted(
            jax.tree_util.tree_flatten_with_path(out["params"])[0],
            key=lambda kv: str(kv[0])):
        if leaf.sharding.is_fully_replicated:
            shards = [np.asarray(s.data) for s in leaf.addressable_shards]
            for s in shards[1:]:
                np.testing.assert_array_equal(shards[0], s)
        digest.update(str(path).encode())
        digest.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    with open(os.path.join(save_dir, f"params_digest_r{process_id}.json"),
              "w") as f:
        json.dump({"step": out["step"], "sha256": digest.hexdigest(),
                   "player_id": out["player_id"],
                   "actor_wiring": out["actor_wiring"]}, f)
    print(f"[proc {process_id}] multihost train ok: step={out['step']} "
          f"env_steps={out['env_steps']} sha256={digest.hexdigest()[:16]}",
          flush=True)


def launch_demo(num_processes: int = 2, devices_per_process: int = 2,
                save_dir: str = "/tmp/r2d2_multihost_demo",
                max_steps: int = 8, timeout: float = 300.0,
                resume: str = "", actor_mode: str = "thread",
                mp: int = 1, player_id: int = -1,
                num_players: int = 2, num_actors: int = 1,
                placement: str = "device", envs_per_actor: int = 1) -> list:
    """Spawn the loopback controllers and assert the final params came out
    BIT-IDENTICAL across hosts (each worker writes a digest file covering
    every param leaf; divergence anywhere fails the launch). Returns the
    per-rank digest records ({step, sha256, player_id, actor_wiring}).
    ``player_id >= 0`` runs the job as ONE player of a multiplayer
    population (README "Multiplayer at pod scale"); per-player jobs must
    all configure the same TOTAL actor fan-out (num_processes *
    num_actors), since the game index is the global actor index.
    ``actor_wiring`` is observed from the envs in thread actor mode only —
    process-mode actors build their envs in spawned children, so the
    records carry None there."""
    import glob
    import json
    import sys

    from r2d2_tpu.parallel.loopback import run_loopback_workers

    for stale in glob.glob(os.path.join(save_dir, "params_digest_r*.json")):
        os.remove(stale)
    run_loopback_workers(
        lambda pid, coordinator: [
            sys.executable, "-m", "r2d2_tpu.parallel.multihost",
            f"--process-id={pid}", f"--num-processes={num_processes}",
            f"--coordinator={coordinator}",
            f"--devices-per-process={devices_per_process}",
            f"--save-dir={save_dir}", f"--max-steps={max_steps}",
            f"--resume={resume}", f"--actor-mode={actor_mode}",
            f"--mp={mp}", f"--player-id={player_id}",
            f"--num-players={num_players}", f"--num-actors={num_actors}",
            f"--placement={placement}",
            f"--envs-per-actor={envs_per_actor}",
        ], num_processes, timeout, "multihost train demo")

    digests = []
    for pid in range(num_processes):
        with open(os.path.join(save_dir, f"params_digest_r{pid}.json")) as f:
            digests.append(json.load(f))
    # step + param digest must match on every rank; actor_wiring is
    # rank-local by design (each host's actors own different game ports)
    core = [{k: d[k] for k in ("step", "sha256")} for d in digests]
    if any(c != core[0] for c in core[1:]):
        raise SystemExit(
            f"multihost train demo: params DIVERGED across controllers: "
            f"{digests}")
    print(f"multihost train demo: {num_processes} controllers x "
          f"{devices_per_process} devices ok, params bit-identical "
          f"across hosts", flush=True)
    return digests


def main(argv=None) -> None:
    import argparse
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--num-processes", type=int, default=2)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--devices-per-process", type=int, default=2)
    p.add_argument("--save-dir", default="/tmp/r2d2_multihost_demo")
    p.add_argument("--max-steps", type=int, default=8)
    p.add_argument("--resume", default="")
    p.add_argument("--actor-mode", choices=("thread", "process"),
                   default="thread")
    p.add_argument("--mp", type=int, default=1,
                   help="tensor-parallel axis width (params feature-sharded "
                        "over mp; must divide devices-per-process)")
    p.add_argument("--player-id", type=int, default=-1,
                   help=">= 0: run this job as ONE player of a multiplayer "
                        "population (one multihost job per player)")
    p.add_argument("--num-players", type=int, default=2)
    p.add_argument("--num-actors", type=int, default=1,
                   help="actors per controller; per-player jobs must all "
                        "match on num_processes * num_actors")
    p.add_argument("--envs-per-actor", type=int, default=1,
                   help="env lanes per actor worker (vectorized actor; the "
                        "ε ladder spans num_processes * num_actors * lanes)")
    p.add_argument("--placement", choices=("device", "host"),
                   default="device",
                   help="replay placement: device = HBM rings + lockstep "
                        "ingest; host = per-process CPU HostReplay + "
                        "consensus psum + external-batch step")
    args = p.parse_args(argv)
    if args.process_id is None:
        launch_demo(args.num_processes, args.devices_per_process,
                    args.save_dir, args.max_steps, resume=args.resume,
                    actor_mode=args.actor_mode, mp=args.mp,
                    player_id=args.player_id, num_players=args.num_players,
                    num_actors=args.num_actors, placement=args.placement,
                    envs_per_actor=args.envs_per_actor)
    else:
        _demo_worker(args.process_id, args.num_processes, args.coordinator,
                     args.devices_per_process, args.save_dir, args.max_steps,
                     resume=args.resume, actor_mode=args.actor_mode,
                     mp=args.mp, player_id=args.player_id,
                     num_players=args.num_players,
                     num_actors=args.num_actors, placement=args.placement,
                     envs_per_actor=args.envs_per_actor)


if __name__ == "__main__":
    main()
