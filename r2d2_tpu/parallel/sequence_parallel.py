"""Sequence (context) parallelism for the recurrent core.

The framework's long-context story (SURVEY §5.7): the reference bounds its
sequence dimension by config (55-step windows through a cuDNN LSTM,
/root/reference/model.py:33,103-108) and has no attention to ring over —
for a recurrence, the carry chain IS the sequence dependency. The
TPU-native equivalent of ring/all-to-all sequence parallelism is therefore
a **pipelined time-sharded scan**:

  * The window's time axis is chunked over the mesh's 'sp' axis — device k
    owns ``T/S`` contiguous steps of the input projection (the hoisted
    ``x @ Wi``, the bulk of the FLOPs, is embarrassingly parallel over
    time and never moves).
  * The batch axis is split into M microbatches, and the recurrent carry
    ``(c, h)`` — the ONLY cross-device tensor, ``2 * B_m * H`` floats —
    hops stage-to-stage over ICI via ``ppermute``, exactly once per
    microbatch per chunk boundary. Pipeline efficiency is M/(M+S-1).
  * The cell math is ``models.network.lstm_cell_step`` — the same function
    the in-chip scan uses — so the sharded unroll is the identical
    computation in the identical order: bit-exact against the single-device
    scan (asserted in tests/test_parallel.py).

When it wins: windows long enough that one chip's HBM cannot hold the
window's activations (T in the thousands — recurrent long-context
agents), or where per-chip serial latency dominates; chunking divides the
activation footprint by S at the cost of the (S-1)/(M+S-1) bubble. At the
reference's T=55, chunks of ~7 steps + carry hops LOSE to the single-chip
scan — which is why the production network keeps `lax.scan` and this is a
mesh-axis capability, not a default.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from r2d2_tpu.parallel.compat import shard_map

from r2d2_tpu.models.network import lstm_cell_step


def make_sp_lstm(mesh: Mesh, microbatches: int):
    """Build the pipelined time-sharded LSTM unroll over ``mesh`` axis 'sp'.

    Returns ``run(w_rec, bias, x_proj, carry0) -> (outputs, final_carry)``:
      * ``w_rec`` (H, 4H), ``bias`` (4H,) — replicated cell weights
      * ``x_proj`` (B, T, 4H) — precomputed input projection, sharded over T
      * ``carry0`` (2, B, H) — packed initial (c, h), replicated
      * outputs (B, T, H) sharded over T; final_carry (2, B, H) replicated

    Requires T % S == 0 and B % microbatches == 0.
    """
    S = mesh.shape["sp"]
    M = microbatches

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(None, "sp", None), P()),
        out_specs=(P(None, "sp", None), P()),
        check_vma=False)
    def run(w_rec, bias, x_proj, carry0):
        k = jax.lax.axis_index("sp")
        B, Tc, G = x_proj.shape            # local chunk: T/S steps
        H = w_rec.shape[0]
        Bm = B // M
        xp = x_proj.reshape(M, Bm, Tc, G)
        c0 = carry0[0].reshape(M, Bm, H)
        h0 = carry0[1].reshape(M, Bm, H)

        def chunk_scan(carry, xp_m):
            def step(c_h, x_t):
                new = lstm_cell_step(x_t, c_h[0], c_h[1], w_rec, bias)
                return new, new[1]
            (c, h), ys = jax.lax.scan(step, carry, xp_m.swapaxes(0, 1))
            return (c, h), ys.swapaxes(0, 1)   # (Bm, Tc, H)

        right = [(i, (i + 1) % S) for i in range(S)]

        def round_body(r, state):
            outs, finals, c_prev, h_prev = state
            # the carry each stage consumes this round: stage 0 reads the
            # initial carry of microbatch r; stage k>0 receives stage k-1's
            # carry-out from the previous round over ICI
            c_in = jax.lax.ppermute(c_prev, "sp", right)
            h_in = jax.lax.ppermute(h_prev, "sp", right)
            m = r - k                      # this stage's active microbatch
            mb = jnp.clip(m, 0, M - 1)
            c_in = jnp.where(k == 0, c0[mb], c_in)
            h_in = jnp.where(k == 0, h0[mb], h_in)

            xp_m = jax.lax.dynamic_index_in_dim(xp, mb, 0, keepdims=False)
            (c_out, h_out), ys = chunk_scan((c_in, h_in), xp_m)

            active = jnp.logical_and(m >= 0, m < M)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(active, ys,
                                jax.lax.dynamic_index_in_dim(
                                    outs, mb, 0, keepdims=False)),
                mb, 0)
            # the LAST stage's carry-out is the window's final state
            write_final = jnp.logical_and(active, k == S - 1)
            fin = jnp.where(
                write_final,
                jnp.stack([c_out, h_out]),
                jax.lax.dynamic_index_in_dim(finals, mb, 0, keepdims=False))
            finals = jax.lax.dynamic_update_index_in_dim(finals, fin, mb, 0)
            return outs, finals, c_out, h_out

        outs = jnp.zeros((M, Bm, Tc, H), x_proj.dtype)
        finals = jnp.zeros((M, 2, Bm, H), x_proj.dtype)
        zeros = jnp.zeros((Bm, H), x_proj.dtype)
        outs, finals, _, _ = jax.lax.fori_loop(
            0, M + S - 1, round_body, (outs, finals, zeros, zeros))

        # finals live only on the last stage; psum replicates (others zero)
        finals = jax.lax.psum(
            jnp.where(k == S - 1, finals, jnp.zeros_like(finals)), "sp")
        final_carry = jnp.concatenate(
            [finals[:, 0].reshape(1, B, H), finals[:, 1].reshape(1, B, H)])
        return outs.reshape(B, Tc, H), final_carry

    def wrapped(w_rec: jnp.ndarray, bias: jnp.ndarray, x_proj: jnp.ndarray,
                carry0: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        B, T, _ = x_proj.shape
        if T % S:
            raise ValueError(f"T={T} not divisible by sp={S}")
        if B % M:
            raise ValueError(f"B={B} not divisible by microbatches={M}")
        # everything runs in x_proj's compute dtype (matching HoistedLSTM's
        # astype of the cell weights under a bf16 policy): f32 stored
        # carry/params would otherwise promote the gates and surface as an
        # opaque dtype mismatch inside the fori_loop body
        carry0 = carry0.astype(x_proj.dtype)
        w_rec = w_rec.astype(x_proj.dtype)
        bias = bias.astype(x_proj.dtype)
        return run(w_rec, bias, x_proj, carry0)

    return jax.jit(wrapped)
