"""Loopback multi-host bring-up dryrun (SURVEY §5.8 DCN story).

Launches ``--num-processes`` worker processes on this machine, each a
separate JAX controller with its own virtual CPU devices, wires them into
one ``jax.distributed`` job over a loopback coordinator, builds the global
('dp','mp') mesh spanning both processes, and runs one fused dp-sharded
training step — the same multi-controller SPMD path a real multi-host TPU
pod uses over DCN (the reference's scaling unit is a single process on half
a GPU; it has no analog, /root/reference/worker.py:251).

    python -m r2d2_tpu.parallel.multihost_dryrun            # launcher
    python -m r2d2_tpu.parallel.multihost_dryrun --process-id=0 ...  # worker
"""

import argparse
import sys


def _worker(process_id: int, num_processes: int, coordinator: str,
            devices_per_process: int) -> None:
    from r2d2_tpu.utils.platform import pin_cpu_platform
    pin_cpu_platform(devices_per_process)

    import jax

    from r2d2_tpu.config import MeshConfig
    from r2d2_tpu.parallel import make_mesh
    from r2d2_tpu.parallel.dryrun import run_tiny_sharded_step
    from r2d2_tpu.parallel.mesh import init_distributed

    init_distributed(MeshConfig(
        multihost=True, coordinator_address=coordinator,
        num_processes=num_processes, process_id=process_id))

    n_global = num_processes * devices_per_process
    assert len(jax.devices()) == n_global, (
        f"global device view: want {n_global}, got {len(jax.devices())}")
    assert len(jax.local_devices()) == devices_per_process

    mesh = make_mesh(MeshConfig(dp=n_global))
    loss = run_tiny_sharded_step(mesh)
    print(f"[proc {process_id}] multihost dryrun ok, loss={loss:.5f}",
          flush=True)


def launch(num_processes: int = 2, devices_per_process: int = 4,
           timeout: float = 300.0) -> None:
    from r2d2_tpu.parallel.loopback import run_loopback_workers

    run_loopback_workers(
        lambda pid, coordinator: [
            sys.executable, "-m", "r2d2_tpu.parallel.multihost_dryrun",
            f"--process-id={pid}", f"--num-processes={num_processes}",
            f"--coordinator={coordinator}",
            f"--devices-per-process={devices_per_process}",
        ], num_processes, timeout, "multihost dryrun")
    print(f"multihost dryrun: {num_processes} processes x "
          f"{devices_per_process} devices ok")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--num-processes", type=int, default=2)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--devices-per-process", type=int, default=4)
    args = p.parse_args(argv)
    if args.process_id is None:
        launch(args.num_processes, args.devices_per_process)
    else:
        _worker(args.process_id, args.num_processes, args.coordinator,
                args.devices_per_process)


if __name__ == "__main__":
    main()
