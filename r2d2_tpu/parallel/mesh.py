"""Device-mesh construction (SURVEY §5.8 TPU-native equivalent).

dp = data parallelism (batch + replay sharding, gradient pmean over ICI);
mp = model parallelism axis, reserved in the mesh so enabling tensor sharding
of the wide layers is a config change, not a rewrite (SURVEY §2.2).
"""

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from r2d2_tpu.config import MeshConfig


def dp_sharding(mesh: Mesh) -> NamedSharding:
    """The leading-dp-axis placement every shard-per-chip pytree uses
    (sharded replay state, the sharded anakin lane carry): one sharding
    construction point so the replay ring and the acting carry cannot
    disagree about the axis layout."""
    return NamedSharding(mesh, PartitionSpec("dp"))


def init_distributed(cfg: MeshConfig) -> None:
    """Multi-host bring-up over DCN (ref has no equivalent; its scaling unit
    is one process on half a GPU, worker.py:251)."""
    if cfg.multihost:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id)


def make_mesh(cfg: Optional[MeshConfig] = None, max_devices: Optional[int] = None
              ) -> Mesh:
    cfg = cfg or MeshConfig()
    devices = jax.devices()
    if max_devices is not None:
        devices = devices[:max_devices]
    # single resolution + validation point — the Learner's sharded-path
    # gate uses the same resolved_dp, so gate and mesh cannot disagree
    mp = max(cfg.mp, 1)
    dp = cfg.resolved_dp(len(devices))
    if dp * mp > len(devices):
        raise ValueError(
            f"mesh.dp={cfg.dp} x mesh.mp={cfg.mp} needs {dp * mp} devices "
            f"but only {len(devices)} are available")
    devices = np.asarray(devices[: dp * mp]).reshape(dp, mp)
    return Mesh(devices, ("dp", "mp"))
