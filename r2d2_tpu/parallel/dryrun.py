"""Shared tiny-shapes validation step for the multichip / multihost dryruns.

Builds the FULL dp-sharded training pipeline — sharded replay ring, one
synthetic block ring-written into every shard, one fused sharded learner
step (sample → unroll → loss → pmean(grads) → Adam → priority write-back) —
at toy sizes, then asserts the loss is finite and the updated params are
bit-identical on every locally-addressable shard. Used by
``__graft_entry__.dryrun_multichip`` (single-process virtual mesh) and by
``r2d2_tpu.parallel.multihost_dryrun`` (two ``jax.distributed`` processes
over a loopback coordinator — the DCN bring-up path of SURVEY §5.8).
"""

import numpy as np

_TINY_BATCH = 4   # _tiny_setup's batch size; the TP dryrun shards it over dp


def tp_dryrun_fits(n_devices: int) -> bool:
    """True when a dp=(n/2) x mp=2 mesh can shard the tiny batch evenly —
    the guard dryrun_multichip uses before attempting the TP step."""
    return n_devices % 2 == 0 and _TINY_BATCH % (n_devices // 2) == 0


def _synthetic_block(spec, rng=None):
    """One full synthetic block at ``spec``'s shapes (deterministic for a
    given rng; rng=None seeds fresh — identical in every process)."""
    from r2d2_tpu.replay.structs import Block

    rng = rng or np.random.default_rng(0)
    S, L = spec.seqs_per_block, spec.learning
    H, W = spec.frame_height, spec.frame_width
    return Block(
        obs_row=rng.integers(0, 255, (spec.obs_row_len, H, W)).astype(np.uint8),
        last_action_row=rng.integers(0, 4, (spec.la_row_len,)).astype(np.int32),
        hidden=rng.normal(size=(S, 2, spec.hidden_dim)).astype(np.float32),
        action=rng.integers(0, 4, (S, L)).astype(np.int32),
        reward=rng.normal(size=(S, L)).astype(np.float32),
        gamma=np.full((S, L), 0.99, np.float32),
        priority=np.ones((S,), np.float32),
        burn_in_steps=np.full((S,), spec.burn_in, np.int32),
        learning_steps=np.full((S,), L, np.int32),
        forward_steps=np.concatenate(
            [np.full((S - 1,), spec.forward), [1]]).astype(np.int32),
        seq_start=(spec.burn_in + L * np.arange(S)).astype(np.int32),
        num_sequences=np.asarray(S, np.int32),
        sum_reward=np.asarray(np.nan, np.float32),
    )


def _tiny_setup():
    """Shared toy-scale (spec, opt, net) for the dryrun steps — one source
    of the shapes so the dp and tp dryruns cannot desynchronize."""
    import jax

    from r2d2_tpu.config import NetworkConfig, OptimConfig
    from r2d2_tpu.models import init_network
    from r2d2_tpu.replay.structs import ReplaySpec

    spec = ReplaySpec(
        num_blocks=4, seqs_per_block=2, block_length=10, burn_in=4,
        learning=5, forward=3, frame_stack=2, frame_height=20, frame_width=20,
        hidden_dim=16, batch_size=_TINY_BATCH, prio_exponent=0.9,
        is_exponent=0.6)
    ncfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32,
                         conv_layers=((8, 4, 2), (16, 3, 1)), use_double=True)
    opt = OptimConfig(target_net_update_interval=2)
    net, _ = init_network(jax.random.PRNGKey(0), 4, ncfg, frame_stack=2,
                          frame_height=20, frame_width=20)
    return spec, opt, net


def run_tiny_sharded_step(mesh) -> float:
    """Run one sharded step over ``mesh`` (axis 'dp'); returns the loss."""
    import jax

    from r2d2_tpu.learner import create_train_state
    from r2d2_tpu.parallel import make_sharded_learner_step, sharded_replay_init
    from r2d2_tpu.parallel.sharded import make_sharded_replay_add

    n_shards = mesh.shape["dp"]
    spec, opt, net = _tiny_setup()

    ts = create_train_state(jax.random.PRNGKey(1), net, opt)
    rs = sharded_replay_init(spec, mesh)

    # one synthetic block per shard (full sequences, unit priorities);
    # seeded identically in every process so multi-controller SPMD holds
    rng = np.random.default_rng(0)
    add = make_sharded_replay_add(spec, mesh)
    for d in range(n_shards):
        rs = add(rs, _synthetic_block(spec, rng), d)

    step = make_sharded_learner_step(net, spec, opt, use_double=True, mesh=mesh)
    ts, rs, metrics = step(ts, rs)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss), f"non-finite loss {loss}"
    # params replicated identically on every locally-addressable shard
    leaf = jax.tree_util.tree_leaves(ts.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    return loss


def run_tiny_sp_step(n_devices: int) -> float:
    """One pipelined sequence-parallel LSTM unroll over an ('sp',) mesh
    spanning all devices (parallel/sequence_parallel.py), checked exact
    against the in-chip scan. Returns the |outputs| sum."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from r2d2_tpu.models.network import HoistedLSTM
    from r2d2_tpu.parallel.sequence_parallel import make_sp_lstm

    B, T, D, H = 8, 2 * n_devices, 10, 8
    key = jax.random.PRNGKey(0)
    xs = jax.random.normal(key, (B, T, D))
    c0 = jnp.zeros((B, H))
    lstm = HoistedLSTM(features=H)
    params = lstm.init(jax.random.PRNGKey(1), (c0, c0), xs)
    (c_ref, h_ref), out_ref = lstm.apply(params, (c0, c0), xs)

    p = params["params"]
    sp = make_sp_lstm(Mesh(np.array(jax.devices()[:n_devices]), ("sp",)),
                      microbatches=4)
    out, final = sp(p["recurrent_kernel"], p["bias"],
                    xs @ p["input_proj"]["kernel"], jnp.stack([c0, c0]))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out_ref))
    np.testing.assert_array_equal(np.asarray(final[1]), np.asarray(h_ref))
    return float(jnp.abs(out).sum())


def run_tiny_device_mp_step(mesh) -> float:
    """One fused DEVICE-replay training step over a ('dp','mp') mesh with
    mp > 1: replay dp-sharded, wide params feature-sharded over mp, GSPMD
    collectives inside the sample-in-HBM step (parallel/sharded.py's GSPMD
    formulation — VERDICT r3 #4). Returns the loss."""
    import jax

    from r2d2_tpu.learner import create_train_state
    from r2d2_tpu.parallel import make_sharded_learner_step, sharded_replay_init
    from r2d2_tpu.parallel.sharded import make_sharded_replay_add
    from r2d2_tpu.parallel.tensor_parallel import state_shardings

    spec, opt, net = _tiny_setup()
    ts = create_train_state(jax.random.PRNGKey(1), net, opt)
    ts = jax.device_put(ts, state_shardings(ts, mesh, min_shard_width=8))
    rs = sharded_replay_init(spec, mesh)
    add = make_sharded_replay_add(spec, mesh)
    rng = np.random.default_rng(0)
    for d in range(mesh.shape["dp"]):
        rs = add(rs, _synthetic_block(spec, rng), d)
    step = make_sharded_learner_step(net, spec, opt, use_double=True,
                                     mesh=mesh)
    ts, rs, metrics = step(ts, rs)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss), f"non-finite device-mp loss {loss}"
    # at least one wide param leaf genuinely sharded across mp
    sharded = [l for l in jax.tree_util.tree_leaves(ts.params)
               if l.ndim >= 1
               and l.addressable_shards[0].data.shape[-1] != l.shape[-1]]
    assert sharded, "no param leaf sharded over mp in the device-mp dryrun"
    return loss


def run_tiny_tp_step(mesh) -> float:
    """One tensor-parallel training step over a ('dp','mp') mesh: params
    feature-sharded over mp, batch over dp, GSPMD collectives
    (parallel/tensor_parallel.py). Returns the loss."""
    import jax

    from r2d2_tpu.learner import create_train_state
    from r2d2_tpu.parallel.tensor_parallel import make_tp_external_batch_step
    from r2d2_tpu.replay.device_replay import (
        replay_add, replay_init, replay_sample)

    spec, opt, net = _tiny_setup()

    rs = replay_init(spec)
    rs = replay_add(spec, rs, _synthetic_block(spec))
    batch = replay_sample(spec, rs, jax.random.PRNGKey(3))

    step, place_state, place_batch = make_tp_external_batch_step(
        net, spec, opt, use_double=True, mesh=mesh, min_shard_width=8)
    ts = place_state(create_train_state(jax.random.PRNGKey(1), net, opt))
    ts, metrics = step(ts, place_batch(batch))
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss), f"non-finite tp loss {loss}"
    return loss


def run_tiny_plstm_step() -> float:
    """One SINGLE-device fused learner step with the pallas LSTM time-scan
    kernel (ops/pallas_lstm.py) in interpret mode: the driver's multichip
    artifact then carries an execution of the kernel's exact semantics —
    lean forward for the target unroll, residual-saving forward + custom-
    VJP backward for the online unroll, inside the jitted step — on any
    backend, even though Mosaic only compiles it on TPU. Returns the loss."""
    import dataclasses

    import jax

    from r2d2_tpu.learner import create_train_state, make_learner_step
    from r2d2_tpu.models import init_network
    from r2d2_tpu.replay.device_replay import replay_add, replay_init

    spec, opt, net = _tiny_setup()
    ncfg = dataclasses.replace(net.config, pallas_lstm="on",
                               pallas_lstm_interpret=True)
    net_pl, _ = init_network(jax.random.PRNGKey(0), 4, ncfg, frame_stack=2,
                             frame_height=20, frame_width=20)
    ts = create_train_state(jax.random.PRNGKey(1), net_pl, opt)
    rs = replay_init(spec)
    rng = np.random.default_rng(0)
    for _ in range(spec.num_blocks):
        rs = replay_add(spec, rs, _synthetic_block(spec, rng))
    step = make_learner_step(net_pl, spec, opt, use_double=True)
    ts, rs, metrics = step(ts, rs)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss), f"non-finite plstm loss {loss}"
    return loss
