"""Shared tiny-shapes validation step for the multichip / multihost dryruns.

Builds the FULL dp-sharded training pipeline — sharded replay ring, one
synthetic block ring-written into every shard, one fused sharded learner
step (sample → unroll → loss → pmean(grads) → Adam → priority write-back) —
at toy sizes, then asserts the loss is finite and the updated params are
bit-identical on every locally-addressable shard. Used by
``__graft_entry__.dryrun_multichip`` (single-process virtual mesh) and by
``r2d2_tpu.parallel.multihost_dryrun`` (two ``jax.distributed`` processes
over a loopback coordinator — the DCN bring-up path of SURVEY §5.8).
"""

import numpy as np


def run_tiny_sharded_step(mesh) -> float:
    """Run one sharded step over ``mesh`` (axis 'dp'); returns the loss."""
    import jax

    from r2d2_tpu.config import NetworkConfig, OptimConfig
    from r2d2_tpu.learner import create_train_state
    from r2d2_tpu.models import init_network
    from r2d2_tpu.parallel import make_sharded_learner_step, sharded_replay_init
    from r2d2_tpu.parallel.sharded import make_sharded_replay_add
    from r2d2_tpu.replay.structs import Block, ReplaySpec

    n_shards = mesh.shape["dp"]
    spec = ReplaySpec(
        num_blocks=4, seqs_per_block=2, block_length=10, burn_in=4,
        learning=5, forward=3, frame_stack=2, frame_height=20, frame_width=20,
        hidden_dim=16, batch_size=4, prio_exponent=0.9, is_exponent=0.6)
    ncfg = NetworkConfig(hidden_dim=16, cnn_out_dim=32,
                         conv_layers=((8, 4, 2), (16, 3, 1)), use_double=True)
    opt = OptimConfig(target_net_update_interval=2)
    net, _ = init_network(jax.random.PRNGKey(0), 4, ncfg, frame_stack=2,
                          frame_height=20, frame_width=20)

    ts = create_train_state(jax.random.PRNGKey(1), net, opt)
    rs = sharded_replay_init(spec, mesh)

    # one synthetic block per shard (full sequences, unit priorities);
    # seeded identically in every process so multi-controller SPMD holds
    rng = np.random.default_rng(0)
    add = make_sharded_replay_add(spec, mesh)
    for d in range(n_shards):
        S, L = spec.seqs_per_block, spec.learning
        blk = Block(
            obs_row=rng.integers(0, 255, (spec.obs_row_len, 20, 20)).astype(np.uint8),
            last_action_row=rng.integers(0, 4, (spec.la_row_len,)).astype(np.int32),
            hidden=rng.normal(size=(S, 2, 16)).astype(np.float32),
            action=rng.integers(0, 4, (S, L)).astype(np.int32),
            reward=rng.normal(size=(S, L)).astype(np.float32),
            gamma=np.full((S, L), 0.99, np.float32),
            priority=np.ones((S,), np.float32),
            burn_in_steps=np.full((S,), spec.burn_in, np.int32),
            learning_steps=np.full((S,), L, np.int32),
            forward_steps=np.concatenate(
                [np.full((S - 1,), spec.forward), [1]]).astype(np.int32),
            seq_start=(spec.burn_in + L * np.arange(S)).astype(np.int32),
            num_sequences=np.asarray(S, np.int32),
            sum_reward=np.asarray(np.nan, np.float32),
        )
        rs = add(rs, blk, d)

    step = make_sharded_learner_step(net, spec, opt, use_double=True, mesh=mesh)
    ts, rs, metrics = step(ts, rs)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss), f"non-finite loss {loss}"
    # params replicated identically on every locally-addressable shard
    leaf = jax.tree_util.tree_leaves(ts.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    return loss
