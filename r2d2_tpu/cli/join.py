"""Join (or leave) a RUNNING fleet from a fresh process (ROADMAP 2c).

Dials the supervisor's lease API (``fleet.lease_transport=socket``;
the orchestrator logs its ``host:port`` at startup) and asks it to
admit a worker — acting or serving — through the SAME slot-adoption
plumbing the in-process join schedule uses (``PlayerStack.join_actor``
for actors, ``ServerFleet.grow_server`` for the serving fleet):

    python -m r2d2_tpu.cli.join --port 6100                # admit an actor
    python -m r2d2_tpu.cli.join --port 6100 --slot 3       # that slot only
    python -m r2d2_tpu.cli.join --port 6100 --leave 3      # retire slot 3
    python -m r2d2_tpu.cli.join --port 6100 --role serve          # grow
    python -m r2d2_tpu.cli.join --port 6100 --role serve --leave 2  # shrink
    python -m r2d2_tpu.cli.join --port 6100 --info         # fleet snapshot

The reply (the adopted lease for joins — slot, generation, lane range,
replay shard key — or the membership/serving snapshot for ``--info``)
prints as one JSON object on stdout; a refused op (fleet at full width,
slot still ACTIVE, serving not sharded) exits 1 with the supervisor's
message on stderr.
"""

import argparse
import json
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--host", default="127.0.0.1",
                   help="lease API host (the supervisor logs it)")
    p.add_argument("--port", type=int, required=True,
                   help="lease API port")
    p.add_argument("--role", choices=("actor", "serve"), default="actor",
                   help="what to admit: an acting worker (default) or one "
                        "more serving-fleet server")
    p.add_argument("--slot", type=int, default=None,
                   help="request a specific slot (actors: must be parked "
                        "or free; default: longest-parked, then spare)")
    p.add_argument("--leave", type=int, default=None, metavar="SLOT",
                   help="retire this slot instead of joining (actors "
                        "park it; serving rehomes its cache shards)")
    p.add_argument("--info", action="store_true",
                   help="print the fleet snapshot and exit")
    p.add_argument("--timeout", type=float, default=30.0,
                   help="dial/round-trip timeout in seconds")
    args = p.parse_args(argv)

    from r2d2_tpu.fleet.membership import lease_call
    try:
        if args.info:
            reply = lease_call(args.host, args.port, "info",
                               timeout_s=args.timeout)
        elif args.role == "actor":
            if args.leave is not None:
                reply = lease_call(args.host, args.port, "leave",
                                   timeout_s=args.timeout, slot=args.leave)
            else:
                reply = lease_call(args.host, args.port, "join",
                                   timeout_s=args.timeout, slot=args.slot)
        else:
            if args.leave is not None:
                reply = lease_call(args.host, args.port, "shrink_serve",
                                   timeout_s=args.timeout, slot=args.leave)
            else:
                reply = lease_call(args.host, args.port, "grow_serve",
                                   timeout_s=args.timeout)
    except (RuntimeError, ConnectionError, OSError) as e:
        print(f"join failed: {e}", file=sys.stderr)
        return 1
    reply.pop("ok", None)
    print(json.dumps(reply), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
