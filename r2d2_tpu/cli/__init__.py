"""Command-line entry points (the reference's train.py / test.py / plot.py
scripts, SURVEY §1 L6), all configured by ``--section.field=value`` overrides."""
