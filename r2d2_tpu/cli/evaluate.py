"""Evaluation / replay CLI (ref /root/reference/test.py).

Two paths, matching the reference:

  * sweep (default): iterate saved checkpoints ``{game}{k}_player{p}``,
    evaluate ``--rounds`` greedy episodes each (ε = runtime.test_epsilon,
    ref test.py:79, config.py:61), print a table and plot reward vs training
    steps and vs environment steps (ref test.py:18-62 — which is broken in
    the reference: it passes a nonexistent ``noop_start`` parameter).
  * --play CKPT: load specific checkpoint(s) and run visible rollouts; for
    multiplayer pass one checkpoint per player and the first hosts the game
    (ref test.py:91-144).

    python -m r2d2_tpu.cli.evaluate --env.game_name=Fake --rounds 5
    python -m r2d2_tpu.cli.evaluate --play models/Fake3_player0 --rounds 3
"""

import argparse
import sys
from typing import List, Optional, Tuple

import numpy as np


def rollout_episode(env, policy, max_steps: int = 100_000) -> float:
    """One greedy-ish episode (the reference's test_one_case, test.py:64-89)."""
    obs = env.reset()
    policy.observe_reset(obs)
    total = 0.0
    for _ in range(max_steps):
        action, _, _ = policy.act()
        obs, reward, done, _ = env.step(action)
        policy.observe(obs, action)
        total += float(reward)
        if done:
            break
    return total


def evaluate_checkpoint(cfg, ckpt_path: str, rounds: int, *,
                        testing: bool = False, is_host: bool = False,
                        port: int = 5060, seed: int = 0,
                        env_sink: Optional[callable] = None,
                        serve: bool = False, serve_clients: int = 4
                        ) -> Tuple[float, int, int]:
    """Returns (mean_return, training_steps, env_steps) — the pooled view
    of :func:`evaluate_scenarios` (kept for callers that predate the
    per-scenario schema)."""
    res = evaluate_scenarios(cfg, ckpt_path, rounds, testing=testing,
                             is_host=is_host, port=port, seed=seed,
                             env_sink=env_sink, serve=serve,
                             serve_clients=serve_clients)
    return res["mean_return"], res["step"], res["env_steps"]


def evaluate_scenarios(cfg, ckpt_path: str, rounds: int, *,
                       scenarios: Optional[List[str]] = None,
                       testing: bool = False, is_host: bool = False,
                       port: int = 5060, seed: int = 0,
                       env_sink: Optional[callable] = None,
                       serve: bool = False, serve_clients: int = 4) -> dict:
    """Per-scenario evaluation of one checkpoint (ISSUE 20 satellite;
    ROADMAP item 5's scenario-coverage axis shares this schema). Returns

        {"scenarios": [{"scenario", "episodes", "mean_return",
                        "min_return", "max_return"}, ...],
         "mean_return": <episode-pooled>, "step": ..., "env_steps": ...}

    ``scenarios`` names the env kinds (game names) to roll out, each for
    ``rounds`` episodes against the same restored params; default is the
    checkpoint's own env kind — one row. ``env_sink`` receives every
    created env handle so a supervising caller can close it if this
    evaluator is abandoned mid-rollout (--play straggler handling).

    ``serve=True`` (ISSUE 13): evaluation-as-a-service — the checkpoint's
    params load into ONE in-proc PolicyServer per scenario and
    ``serve_clients`` concurrent evaluator threads (each with its own env
    + thin RemotePolicy at the same test ε) split the rounds, so every
    policy forward of the evaluation rides the micro-batcher. Greedy-ish
    math is identical (shared forward factory, client-side ε draws)."""
    import dataclasses

    import jax

    from r2d2_tpu.actor.policy import ActorPolicy
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.runtime.checkpoint import (
        load_checkpoint_config, restore_checkpoint)

    # the Config stored beside the checkpoint supplies the SHAPE-bearing
    # sections (network architecture, env preprocessing, sequence windows) so
    # the trained network reconstructs exactly (the reference instead trusts
    # config.py to still match the .pth); evaluation-time settings —
    # test_epsilon, multiplayer wiring, save_dir — stay with the CLI config
    stored = load_checkpoint_config(ckpt_path)
    if stored is not None:
        cfg = dataclasses.replace(cfg, env=stored.env, network=stored.network,
                                  sequence=stored.sequence)
    names = list(scenarios) if scenarios else [cfg.env.game_name]
    rows: List[dict] = []
    pooled: List[float] = []
    net = params = restored = None
    for si, name in enumerate(names):
        scfg = (cfg if name == cfg.env.game_name else dataclasses.replace(
            cfg, env=dataclasses.replace(cfg.env, game_name=name)))
        env = create_env(scfg.env, clip_rewards=False, testing=testing,
                         is_host=is_host and si == 0, port=port,
                         seed=seed + 1000 * si)
        if env_sink is not None:
            env_sink(env)
        if net is None:
            # restore ONCE against the first scenario's action space (all
            # scenarios share the checkpoint's head — a scenario with a
            # different action_dim cannot be scored by these params)
            net = NetworkApply(env.action_space.n, cfg.network,
                               cfg.env.frame_stack, cfg.env.frame_height,
                               cfg.env.frame_width)
            template = net.init(jax.random.PRNGKey(0))
            restored = restore_checkpoint(ckpt_path)
            params = jax.tree_util.tree_map(
                lambda t, p: np.asarray(p, np.asarray(t).dtype),
                template, restored["params"])
        if serve:
            returns = _serve_rollouts(scfg, net, params, env, rounds,
                                      max(serve_clients, 1), testing,
                                      seed + 1000 * si, env_sink)
        else:
            policy = ActorPolicy(net, params, cfg.runtime.test_epsilon,
                                 seed=seed + 1000 * si)
            returns = [rollout_episode(env, policy)
                       for _ in range(rounds)]
        env.close()
        pooled.extend(returns)
        rows.append({"scenario": name, "episodes": len(returns),
                     "mean_return": float(np.mean(returns)),
                     "min_return": float(np.min(returns)),
                     "max_return": float(np.max(returns))})
    return {"scenarios": rows,
            "mean_return": float(np.mean(pooled)),
            "step": int(restored.get("step", 0)),
            "env_steps": int(restored.get("env_steps", 0))}


def _serve_rollouts(cfg, net, params, first_env, rounds: int, clients: int,
                    testing: bool, seed: int, env_sink) -> list:
    """Evaluation-as-a-service rollouts: one in-proc policy server, N
    concurrent thin clients splitting the rounds (client i reuses the
    caller's env for i=0, fresh seeded envs otherwise)."""
    import threading

    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.serve import InprocEndpoint, PolicyServer, RemotePolicy

    endpoint = InprocEndpoint()
    server = PolicyServer(cfg, net, params, endpoint=endpoint).start()
    clients = min(clients, max(rounds, 1))
    shares = [rounds // clients + (1 if i < rounds % clients else 0)
              for i in range(clients)]
    returns: list = []
    errors: list = []
    lock = threading.Lock()

    def run(i: int, share: int) -> None:
        env = policy = None
        try:
            env = first_env if i == 0 else create_env(
                cfg.env, clip_rewards=False, testing=testing, seed=seed + i)
            if i > 0 and env_sink is not None:
                env_sink(env)
            policy = RemotePolicy(endpoint.connect(), net.action_dim,
                                  cfg.runtime.test_epsilon, seed=seed + i,
                                  client_id=i,
                                  timeout_s=cfg.serve.request_timeout_s,
                                  max_retry_s=cfg.serve.max_retry_s)
            got = [rollout_episode(env, policy) for _ in range(share)]
            with lock:
                returns.extend(got)
        except BaseException as e:     # surfaced below
            errors.append(e)
        finally:
            # a mid-rollout failure must not leak the engine handle
            # (run_actor's finally exists for the same reason)
            if policy is not None:
                policy.close()
            if env is not None and i > 0:
                try:
                    env.close()
                except Exception:
                    pass

    threads = [threading.Thread(target=run, args=(i, share), daemon=True)
               for i, share in enumerate(shares) if share > 0]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        server.stop()
    if errors:
        raise errors[0]
    return returns


def _sweep_worker(cfg_dict: dict, ckpt: str, rounds: int, seed: int,
                  scenarios: Optional[List[str]] = None):
    """Checkpoint-sweep worker, run in a spawned CPU-pinned process (the
    reference's multiprocessing.Pool analog, test.py:23). Module-level so
    it pickles under the spawn start method; the platform pin must run
    before any jax import in the child."""
    import os
    # unconditional (not setdefault): an inherited JAX_PLATFORMS=tpu from a
    # TPU-pinned parent would otherwise have every worker race to open the
    # single-process libtpu
    os.environ["JAX_PLATFORMS"] = "cpu"
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    from r2d2_tpu.config import Config
    return evaluate_scenarios(Config.from_dict(cfg_dict), ckpt, rounds,
                              seed=seed, scenarios=scenarios)


def main(argv=None) -> None:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--play", nargs="*", default=None,
                   help="checkpoint path(s) to replay (one per player)")
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--player", type=int, default=0)
    p.add_argument("--workers", type=int, default=5,
                   help="concurrent checkpoint evaluations (the reference "
                        "uses a 5-way multiprocessing pool, test.py:23)")
    p.add_argument("--serve", action="store_true",
                   help="evaluation-as-a-service (ISSUE 13): load each "
                        "checkpoint into ONE in-proc policy server and "
                        "split its rounds across --serve-clients "
                        "concurrent thin clients — every forward rides "
                        "the micro-batcher (forces the in-process sweep "
                        "path; per-checkpoint servers, identical math)")
    p.add_argument("--serve-clients", type=int, default=4,
                   help="--serve: concurrent evaluator clients per "
                        "checkpoint")
    p.add_argument("--scenarios", default=None,
                   help="comma-separated env kinds (game names) to roll "
                        "each checkpoint through — one return row per "
                        "scenario (default: the checkpoint's own env)")
    p.add_argument("--straggler-window", type=float, default=60.0,
                   help="--play: seconds a peer evaluator may keep running "
                        "after the first one finishes before being "
                        "abandoned (in a shared game all episodes end "
                        "together; a late peer is stuck)")
    p.add_argument("--grace-window", type=float, default=15.0,
                   help="--play: seconds surviving evaluators get to wind "
                        "down after a peer fails before the CLI exits")
    p.add_argument("--out", default="eval_curve.png")
    args, config_overrides = p.parse_known_args(argv)

    from r2d2_tpu.config import Config, parse_overrides
    cfg = parse_overrides(Config(), config_overrides)

    if args.play is not None:
        # Replay path. With several checkpoints (multiplayer) the evaluators
        # must run CONCURRENTLY — the first hosts the live game and stays up
        # while the others join it (the reference launches one `play` Ray
        # task per checkpoint simultaneously, test.py:129-144). A sequential
        # loop can never connect: the host's game would be over before any
        # joiner starts.
        # Every joiner targets multiplayer.base_port: replay runs exactly ONE
        # concurrent game that all players share. This matches the
        # reference's replay usage (test.py:129-144, one host + joiners on a
        # single port); it is the TRAINING side that fans out one game per
        # actor index (orchestrator.py actor_env_args, ref train.py:33-38).
        envs_by_idx: dict = {}

        def play_one(i: int, ckpt: str):
            return evaluate_checkpoint(
                cfg, ckpt, args.rounds, testing=True, is_host=(i == 0),
                port=cfg.multiplayer.base_port, seed=i,
                env_sink=lambda e: envs_by_idx.setdefault(i, []).append(e))

        def close_abandoned(indices) -> None:
            """Tear down envs owned by abandoned evaluator threads — a
            daemon thread blocked inside env.reset/step would otherwise
            keep its engine (a live ViZDoom process for real envs) open
            until interpreter exit."""
            for i in indices:
                for e in envs_by_idx.get(i, ()):  # noqa: B007
                    try:
                        e.close()
                    except Exception:
                        pass

        if len(args.play) <= 1:
            results = [play_one(i, c) for i, c in enumerate(args.play)]
        else:
            # Daemon threads, not a ThreadPoolExecutor: if the host evaluator
            # dies, joiners may be blocked connecting to a game that will
            # never exist — the error must surface and the process must be
            # able to exit rather than join stuck workers forever.
            import threading

            results = [None] * len(args.play)
            errors = []

            def run(i: int, ckpt: str) -> None:
                try:
                    results[i] = play_one(i, ckpt)
                except BaseException as e:  # surfaced below
                    errors.append((i, e))

            import time as time_mod

            threads = [threading.Thread(target=run, args=(i, c), daemon=True)
                       for i, c in enumerate(args.play)]
            for t in threads:
                t.start()
            # No overall deadline while everyone is still working, but once
            # the first evaluator completes the rest get a bounded straggler
            # window — in a shared multiplayer game all players' episodes
            # end together, so a peer still "running" long after another
            # finished is stuck (e.g. blocked joining a dead host).
            straggler_deadline = None
            abandoned = False
            while any(t.is_alive() for t in threads) and not errors:
                for t in threads:
                    t.join(timeout=0.5)
                if straggler_deadline is None:
                    if any(not t.is_alive() for t in threads):
                        straggler_deadline = (time_mod.time()
                                              + args.straggler_window)
                elif time_mod.time() > straggler_deadline:
                    stuck = [args.play[i] for i, t in enumerate(threads)
                             if t.is_alive()]
                    print(f"warning: abandoning stuck evaluator(s) after "
                          f"{args.straggler_window:.0f}s straggler window: "
                          f"{stuck}", file=sys.stderr)
                    # closing a stuck evaluator's env typically wakes its
                    # blocked rollout with an exception — that error is a
                    # consequence of the abandonment, not a failure, so the
                    # error check below is gated on `abandoned`
                    abandoned = True
                    close_abandoned(
                        i for i, t in enumerate(threads) if t.is_alive())
                    break
            if errors and not abandoned:
                # Give surviving evaluators a short grace window to wind
                # down cleanly (exiting immediately would kill daemon
                # threads mid-rollout); a joiner stuck on a dead host is
                # abandoned after the grace period rather than hanging the
                # CLI forever — its env is closed so no engine leaks.
                grace_deadline = time_mod.time() + args.grace_window
                for t in threads:
                    t.join(timeout=max(0.0, grace_deadline - time_mod.time()))
                close_abandoned(
                    i for i, t in enumerate(threads) if t.is_alive())
                i, err = errors[0]
                raise SystemExit(
                    f"evaluator for {args.play[i]} failed: "
                    f"{type(err).__name__}: {err}")
        for ckpt, res in zip(args.play, results):
            if res is None:
                print(f"{ckpt}: no result (evaluator abandoned)")
                continue
            mean_ret, step, env_steps = res
            print(f"{ckpt}: mean return {mean_ret:.2f} over {args.rounds} "
                  f"rounds (step {step}, env steps {env_steps})")
        return

    # checkpoint sweep (ref test.py:18-62)
    from r2d2_tpu.runtime.checkpoint import list_checkpoints
    ckpts = list_checkpoints(cfg.runtime.save_dir, cfg.env.game_name, args.player)
    if not ckpts:
        raise SystemExit(
            f"no checkpoints for game={cfg.env.game_name!r} "
            f"player={args.player} under {cfg.runtime.save_dir!r}")
    # concurrent sweep (ref test.py:23, multiprocessing.Pool(5)): spawned
    # CPU-pinned worker PROCESSES. A thread pool only parallelizes the
    # jitted policy half of each rollout — the env-stepping/numpy half is
    # GIL-bound (round-3 review) — while separate processes parallelize
    # the whole rollout like the reference does. --workers 1 runs
    # in-process (no spawn/jax-import cost for small sweeps).
    scenarios = (args.scenarios.split(",") if args.scenarios else None)
    if args.serve or args.workers <= 1 or len(ckpts) == 1:
        results = [evaluate_scenarios(cfg, c, args.rounds, seed=i,
                                      scenarios=scenarios,
                                      serve=args.serve,
                                      serve_clients=args.serve_clients)
                   for i, c in ckpts]
    else:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor
        from itertools import repeat
        cfg_dict = cfg.to_dict()
        with ProcessPoolExecutor(
                max_workers=min(args.workers, len(ckpts)),
                mp_context=mp.get_context("spawn")) as pool:
            results = list(pool.map(
                _sweep_worker, repeat(cfg_dict), [c for _, c in ckpts],
                repeat(args.rounds), [i for i, _ in ckpts],
                repeat(scenarios)))
    rows = []
    for (idx, _), res in zip(ckpts, results):
        step, env_steps = res["step"], res["env_steps"]
        rows.append((idx, step, env_steps, res["mean_return"]))
        # per-env-kind return rows (ISSUE 20 satellite), the pooled
        # mean last for the curve
        for sc in res["scenarios"]:
            print(f"checkpoint {idx}: scenario={sc['scenario']} "
                  f"episodes={sc['episodes']} "
                  f"mean_return={sc['mean_return']:.2f} "
                  f"[{sc['min_return']:.2f}, {sc['max_return']:.2f}]",
                  flush=True)
        print(f"checkpoint {idx}: step={step} env_steps={env_steps} "
              f"mean_return={res['mean_return']:.2f}", flush=True)

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    rows_np = np.asarray(rows, float)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 4))
    ax1.plot(rows_np[:, 1], rows_np[:, 3], "o-")
    ax1.set_xlabel("training steps")
    ax1.set_ylabel("average reward")
    ax2.plot(rows_np[:, 2], rows_np[:, 3], "o-")
    ax2.set_xlabel("environment steps")
    fig.tight_layout()
    fig.savefig(args.out, dpi=120)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
