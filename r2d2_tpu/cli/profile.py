"""Profile the fused learner step and print per-op time attribution.

    python -m r2d2_tpu.cli.profile --steps 20 --out /tmp/r2d2_prof
    python -m r2d2_tpu.cli.profile --summarize /tmp/r2d2_prof  # re-analyze

Config overrides apply as everywhere (--replay.batch_size=64 ...); the
defaults profile the reference-scale learner on the current backend
(SURVEY §5.1 — the reference has no profiling hooks at all).
"""

import argparse
import sys


def main(argv=None) -> None:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=20,
                   help="train steps inside the trace window")
    p.add_argument("--out", default="/tmp/r2d2_profile",
                   help="trace output directory (tensorboard-compatible)")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--summarize", default=None, metavar="TRACE_DIR",
                   help="skip capture; summarize an existing trace dir")
    args, config_overrides = p.parse_known_args(argv)

    from r2d2_tpu.config import Config, parse_overrides
    from r2d2_tpu.tools.profile_step import (
        capture_step_trace, format_summary, summarize_trace,
        traced_step_count)

    trace_dir = args.summarize
    if trace_dir is not None and config_overrides:
        p.error(f"unrecognized arguments with --summarize: "
                f"{config_overrides} (config overrides only apply to "
                "capture runs)")
    if trace_dir is None:
        cfg = parse_overrides(Config(), config_overrides)
        if not any("replay.capacity" in str(o) for o in config_overrides):
            # bench.py's trimmed-but-realistic default capacity; an
            # explicit --replay.capacity override always wins
            cfg = cfg.replace(
                **{"replay.capacity": min(cfg.replay.capacity, 25_600)})
        trace_dir = capture_step_trace(cfg, args.steps, args.out)
        print(f"trace written to {trace_dir} (tensorboard --logdir works)",
              file=sys.stderr)
    steps = traced_step_count(trace_dir)
    if steps is None:
        steps = args.steps
        print(f"warning: no profile_meta.json in {trace_dir}; ms/step "
              f"assumes --steps={steps}", file=sys.stderr)
    summary = summarize_trace(trace_dir, top=args.top)
    print(format_summary(summary, steps))


if __name__ == "__main__":
    main()
