"""Training-curve plotting CLI (ref /root/reference/plot.py).

    python -m r2d2_tpu.cli.plot --file_path . --show_all --max_time 120 \
        --loss_interpolation

Reads ``train_player{i}.log`` files (reference-compatible key strings),
converts log-interval counts to minutes (interval * 20s / 60, matching
plot.py:42-46), spline-interpolates the reward curve and optionally the loss,
and renders a per-player reward(/loss) grid to ``training_curves.png``.
"""

import argparse
import glob
import os
import re

import numpy as np

from r2d2_tpu.tools.logparse import parse_log


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--file_path", default=".",
                   help="directory containing train_player*.log")
    p.add_argument("--show_all", action="store_true",
                   help="also plot loss on a twin axis")
    p.add_argument("--max_time", type=float, default=None,
                   help="clip the x axis to this many minutes")
    p.add_argument("--loss_interpolation", action="store_true",
                   help="spline-interpolate the loss curve")
    p.add_argument("--log_interval", type=float, default=20.0,
                   help="seconds per log interval (ref config.py:40)")
    p.add_argument("--out", default="training_curves.png")
    p.add_argument("--show", action="store_true")
    args = p.parse_args(argv)

    import matplotlib
    if not args.show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from scipy.interpolate import make_interp_spline

    paths = sorted(glob.glob(os.path.join(args.file_path, "train_player*.log")))
    if not paths:
        raise SystemExit(f"no train_player*.log under {args.file_path!r}")

    fig, axes = plt.subplots(len(paths), 1, squeeze=False,
                             figsize=(10, 4 * len(paths)))
    for ax, path in zip(axes[:, 0], paths):
        player = re.search(r"train_player(\d+)\.log", path).group(1)
        log = parse_log(path)
        minutes = np.asarray(log.return_counts, float) * args.log_interval / 60.0
        rewards = np.asarray(log.returns, float)
        if args.max_time is not None:
            keep = minutes <= args.max_time
            minutes, rewards = minutes[keep], rewards[keep]
        if len(minutes) >= 4:
            xs = np.linspace(minutes.min(), minutes.max(), 300)
            ys = make_interp_spline(minutes, rewards, k=3)(xs)
            ax.plot(xs, ys, label="avg episode return")
            ax.plot(minutes, rewards, ".", alpha=0.4)
        else:
            ax.plot(minutes, rewards, ".-", label="avg episode return")
        ax.set_xlabel("training time (minutes)")
        ax.set_ylabel("average episode return")
        ax.set_title(f"player {player}")
        ax.legend(loc="upper left")

        if args.show_all and log.losses:
            lmin = np.asarray(log.loss_counts, float) * args.log_interval / 60.0
            losses = np.asarray(log.losses, float)
            if args.max_time is not None:
                keep = lmin <= args.max_time
                lmin, losses = lmin[keep], losses[keep]
            ax2 = ax.twinx()
            if args.loss_interpolation and len(lmin) >= 4:
                xs = np.linspace(lmin.min(), lmin.max(), 300)
                ys = make_interp_spline(lmin, losses, k=3)(xs)
                ax2.plot(xs, ys, color="tab:red", alpha=0.7, label="loss")
            else:
                ax2.plot(lmin, losses, color="tab:red", alpha=0.7, label="loss")
            ax2.set_ylabel("loss")
            ax2.legend(loc="upper right")

    fig.tight_layout()
    fig.savefig(args.out, dpi=120)
    print(f"wrote {args.out}")
    if args.show:
        plt.show()


if __name__ == "__main__":
    main()
