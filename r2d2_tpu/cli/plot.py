"""Training-curve plotting CLI (ref /root/reference/plot.py).

    python -m r2d2_tpu.cli.plot --file_path . --show_all --max_time 120 \
        --loss_interpolation

Reads ``train_player{i}.log`` files (reference-compatible key strings),
converts log-interval counts to minutes (interval * 20s / 60, matching
plot.py:42-46), spline-interpolates the reward curve and optionally the loss,
and renders a per-player reward(/loss) grid to ``training_curves.png``.
"""

import argparse
import glob
import os
import re

import numpy as np

from r2d2_tpu.tools.logparse import (fleet_series, learning_series,
                                     parse_jsonl, parse_log,
                                     replay_diag_series)


def plot_learning(file_path: str, out: str, show: bool) -> None:
    """--learning mode: render the learning-diagnostics series (ΔQ
    stored/zero/recomputed, sample-age P50/P95, grad norm — ISSUE 5) from
    each player's ``metrics_player{i}.jsonl`` to one grid."""
    import matplotlib
    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    paths = sorted(glob.glob(os.path.join(file_path,
                                          "metrics_player*.jsonl")))
    series = []
    for path in paths:
        s = learning_series(parse_jsonl(path))
        if s["t"]:
            player = re.search(r"metrics_player(\d+)\.jsonl", path).group(1)
            series.append((player, s))
    if not series:
        raise SystemExit(
            f"no metrics_player*.jsonl with a 'learning' block under "
            f"{file_path!r} — run with telemetry.learning_enabled=true")

    fig, axes = plt.subplots(3, len(series), squeeze=False,
                             figsize=(7 * len(series), 9))
    for col, (player, s) in enumerate(series):
        t = np.asarray([x or 0.0 for x in s["t"]]) / 60.0

        def draw(ax, keys, ylabel):
            for key in keys:
                ys = np.asarray([np.nan if v is None else v for v in s[key]],
                                float)
                if np.isfinite(ys).any():
                    ax.plot(t, ys, ".-", label=key)
            ax.set_ylabel(ylabel)
            ax.legend(loc="upper right", fontsize=8)

        draw(axes[0][col], ["delta_q_stored", "delta_q_zero",
                            "delta_q_recomputed"], "normalized dQ")
        axes[0][col].set_title(f"player {player}")
        draw(axes[1][col], ["sample_age_p50", "sample_age_p95",
                            "replay_age_p50"], "age (weight publishes)")
        draw(axes[2][col], ["grad_norm", "td_p50"], "grad norm / |TD| p50")
        axes[2][col].set_xlabel("training time (minutes)")
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")
    if show:
        plt.show()


def plot_replay_diag(file_path: str, out: str, show: bool) -> None:
    """--replay-diag mode: render the replay-pathology series (sum-tree
    health, never-sampled-before-eviction fraction, lane composition —
    ISSUE 10) from each player's ``metrics_player{i}.jsonl``."""
    import matplotlib
    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    paths = sorted(glob.glob(os.path.join(file_path,
                                          "metrics_player*.jsonl")))
    series = []
    for path in paths:
        s = replay_diag_series(parse_jsonl(path))
        if s["t"]:
            player = re.search(r"metrics_player(\d+)\.jsonl", path).group(1)
            series.append((player, s))
    if not series:
        raise SystemExit(
            f"no metrics_player*.jsonl with a 'replay_diag' block under "
            f"{file_path!r} — run with telemetry.replay_diag_enabled=true")

    fig, axes = plt.subplots(3, len(series), squeeze=False,
                             figsize=(7 * len(series), 9))
    for col, (player, s) in enumerate(series):
        t = np.asarray([x or 0.0 for x in s["t"]]) / 60.0

        def draw(ax, keys, ylabel):
            for key in keys:
                ys = np.asarray([np.nan if v is None else v for v in s[key]],
                                float)
                if np.isfinite(ys).any():
                    ax.plot(t, ys, ".-", label=key)
            ax.set_ylabel(ylabel)
            ax.legend(loc="upper right", fontsize=8)

        # fractions (0..1) share panels; the unbounded lifetime count
        # gets its own axis — on a shared one it would autoscale the
        # never-sampled fraction (THE pathology signal) into a flat line
        draw(axes[0][col], ["ess_frac", "frac_at_max"],
             "sum-tree health (fractions)")
        axes[0][col].set_title(f"player {player}")
        draw(axes[1][col], ["never_sampled_frac", "starved_frac",
                            "max_share"], "pathology fractions")
        draw(axes[2][col], ["mean_lifetime"],
             "eviction lifetime (times sampled)")
        axes[2][col].set_xlabel("training time (minutes)")
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")
    if show:
        plt.show()


def plot_fleet(file_path: str, out: str, show: bool) -> None:
    """--fleet mode: render the fleet-observability series (per-rank
    step time, lockstep-wait fraction, skew / env-step divergence —
    ISSUE 12) from the rank-0 ``metrics_player{i}.jsonl`` streams."""
    import matplotlib
    if not show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    paths = sorted(glob.glob(os.path.join(file_path,
                                          "metrics_player*.jsonl")))
    series = []
    for path in paths:
        s = fleet_series(parse_jsonl(path))
        if s["t"]:
            player = re.search(r"metrics_player(\d+)\.jsonl", path).group(1)
            series.append((player, s))
    if not series:
        raise SystemExit(
            f"no metrics_player*.jsonl with a 'fleet' block under "
            f"{file_path!r} — multihost runs with "
            "telemetry.fleet_enabled=true produce one")

    fig, axes = plt.subplots(3, len(series), squeeze=False,
                             figsize=(7 * len(series), 9))
    for col, (player, s) in enumerate(series):
        t = np.asarray([x or 0.0 for x in s["t"]]) / 60.0

        # per-rank step-time lines: ragged per_rank_ms lists padded with
        # NaN (a record before the first gauge table carries None)
        tables = s["per_rank_ms"]
        nranks = max((len(p) for p in tables if p), default=0)
        ax = axes[0][col]
        for r in range(nranks):
            ys = np.asarray(
                [p[r] if p and len(p) > r else np.nan for p in tables],
                float)
            if np.isfinite(ys).any():
                ax.plot(t, ys, ".-", label=f"rank {r}")
        ax.set_ylabel("per-rank step time (ms)")
        ax.set_title(f"player {player}")
        ax.legend(loc="upper right", fontsize=8)

        def draw(ax, keys, ylabel):
            for key in keys:
                ys = np.asarray([np.nan if v is None else v for v in s[key]],
                                float)
                if np.isfinite(ys).any():
                    ax.plot(t, ys, ".-", label=key)
            ax.set_ylabel(ylabel)
            ax.legend(loc="upper right", fontsize=8)

        draw(axes[1][col], ["wait_frac"], "lockstep wait fraction")
        draw(axes[2][col], ["skew", "divergence"],
             "step-time skew / env divergence")
        axes[2][col].set_xlabel("training time (minutes)")
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")
    if show:
        plt.show()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--file_path", default=".",
                   help="directory containing train_player*.log")
    p.add_argument("--show_all", action="store_true",
                   help="also plot loss on a twin axis")
    p.add_argument("--max_time", type=float, default=None,
                   help="clip the x axis to this many minutes")
    p.add_argument("--loss_interpolation", action="store_true",
                   help="spline-interpolate the loss curve")
    p.add_argument("--log_interval", type=float, default=20.0,
                   help="seconds per log interval (ref config.py:40)")
    p.add_argument("--out", default="training_curves.png")
    p.add_argument("--show", action="store_true")
    p.add_argument("--learning", action="store_true",
                   help="plot the learning-diagnostics series (dQ, "
                        "sample-age, grad norm) from metrics_player*.jsonl "
                        "instead of the reward curves")
    p.add_argument("--replay-diag", action="store_true",
                   help="plot the replay-pathology series (sum-tree "
                        "health, never-sampled fraction, lane "
                        "composition) from metrics_player*.jsonl instead "
                        "of the reward curves")
    p.add_argument("--fleet", action="store_true",
                   help="plot the fleet-observability series (per-rank "
                        "step time, lockstep-wait fraction, skew / "
                        "env-step divergence) from metrics_player*.jsonl "
                        "instead of the reward curves")
    args = p.parse_args(argv)

    if args.learning:
        out = args.out if args.out != "training_curves.png" \
            else "learning_curves.png"
        plot_learning(args.file_path, out, args.show)
        return
    if args.replay_diag:
        out = args.out if args.out != "training_curves.png" \
            else "replay_diag_curves.png"
        plot_replay_diag(args.file_path, out, args.show)
        return
    if args.fleet:
        out = args.out if args.out != "training_curves.png" \
            else "fleet_curves.png"
        plot_fleet(args.file_path, out, args.show)
        return

    import matplotlib
    if not args.show:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from scipy.interpolate import make_interp_spline

    paths = sorted(glob.glob(os.path.join(args.file_path, "train_player*.log")))
    if not paths:
        raise SystemExit(f"no train_player*.log under {args.file_path!r}")

    fig, axes = plt.subplots(len(paths), 1, squeeze=False,
                             figsize=(10, 4 * len(paths)))
    for ax, path in zip(axes[:, 0], paths):
        player = re.search(r"train_player(\d+)\.log", path).group(1)
        log = parse_log(path)
        minutes = np.asarray(log.return_counts, float) * args.log_interval / 60.0
        rewards = np.asarray(log.returns, float)
        if args.max_time is not None:
            keep = minutes <= args.max_time
            minutes, rewards = minutes[keep], rewards[keep]
        if len(minutes) >= 4:
            xs = np.linspace(minutes.min(), minutes.max(), 300)
            ys = make_interp_spline(minutes, rewards, k=3)(xs)
            ax.plot(xs, ys, label="avg episode return")
            ax.plot(minutes, rewards, ".", alpha=0.4)
        else:
            ax.plot(minutes, rewards, ".-", label="avg episode return")
        ax.set_xlabel("training time (minutes)")
        ax.set_ylabel("average episode return")
        ax.set_title(f"player {player}")
        ax.legend(loc="upper left")

        if args.show_all and log.losses:
            lmin = np.asarray(log.loss_counts, float) * args.log_interval / 60.0
            losses = np.asarray(log.losses, float)
            if args.max_time is not None:
                keep = lmin <= args.max_time
                lmin, losses = lmin[keep], losses[keep]
            ax2 = ax.twinx()
            if args.loss_interpolation and len(lmin) >= 4:
                xs = np.linspace(lmin.min(), lmin.max(), 300)
                ys = make_interp_spline(lmin, losses, k=3)(xs)
                ax2.plot(xs, ys, color="tab:red", alpha=0.7, label="loss")
            else:
                ax2.plot(lmin, losses, color="tab:red", alpha=0.7, label="loss")
            ax2.set_ylabel("loss")
            ax2.legend(loc="upper right")

    fig.tight_layout()
    fig.savefig(args.out, dpi=120)
    print(f"wrote {args.out}")
    if args.show:
        plt.show()


if __name__ == "__main__":
    main()
