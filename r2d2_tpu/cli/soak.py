"""CLI alias: ``python -m r2d2_tpu.cli.soak`` — see
r2d2_tpu/tools/soak.py (production-scale sustained-training soak)."""

import sys

from r2d2_tpu.tools.soak import main

if __name__ == "__main__":
    sys.exit(main())
