"""Genetic hyperparameter search CLI (the reference's genetic-branch
capability, README.md:28-32).

Fitness = mean episode return over the final log intervals of a short
training slice on the configured env (default Fake, hermetic).

    python -m r2d2_tpu.cli.genetic --population 6 --generations 3 \
        --slice-steps 200 --env.game_name=Fake
"""

import argparse
import json
import sys

import numpy as np


def make_slice_eval(base_overrides, slice_steps: int, slice_seconds: float):
    from r2d2_tpu.runtime.orchestrator import train

    def eval_fn(cfg) -> float:
        records = []
        try:
            stacks = train(cfg, max_training_steps=slice_steps,
                           max_seconds=slice_seconds, actor_mode="thread",
                           log_fn=records.append)
        except Exception as e:  # invalid genome (e.g. OOM-scale) scores -inf
            print(f"genome failed: {e}", file=sys.stderr)
            return float("-inf")
        returns = [r["avg_episode_return"] for r in records
                   if r.get("avg_episode_return") is not None]
        m = stacks[0].metrics
        if m.num_episodes:
            returns.append(m.episode_reward / m.num_episodes)
        return float(np.mean(returns[-3:])) if returns else float("-inf")

    return eval_fn


def main(argv=None) -> None:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--population", type=int, default=6)
    p.add_argument("--generations", type=int, default=3)
    p.add_argument("--slice-steps", type=int, default=300)
    p.add_argument("--slice-seconds", type=float, default=600.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="genetic_results.json")
    args, config_overrides = p.parse_known_args(argv)

    from r2d2_tpu.config import Config, parse_overrides
    from r2d2_tpu.tools.genetic import run_search

    base = parse_overrides(Config(), config_overrides)
    eval_fn = make_slice_eval(config_overrides, args.slice_steps,
                              args.slice_seconds)

    def log(gen, result):
        genome, fit = result.best
        print(f"generation {gen}: best fitness {fit:.3f} genome {genome}",
              flush=True)

    history = run_search(eval_fn, base=base, population=args.population,
                         generations=args.generations, seed=args.seed, log_fn=log)
    best_genome, best_fit = history[-1].best
    with open(args.out, "w") as f:
        json.dump({"best_genome": best_genome, "best_fitness": best_fit,
                   "generations": [
                       {"genomes": h.genomes, "fitnesses": h.fitnesses}
                       for h in history]}, f, indent=2, default=str)
    print(f"best fitness {best_fit:.3f}; wrote {args.out}")


if __name__ == "__main__":
    main()
