"""Genetic hyperparameter search CLI (the reference's genetic-branch
capability, README.md:28-32).

Two fitness modes (--fitness-mode):
  * "sync" (default): deterministic synchronous collect:learn slice
    (tools/sync_train.py) scored by mean greedy eval return — the same
    genome scores bit-identically, so selection compares genomes, not
    scheduler noise.
  * "slice": the threaded production orchestrator for a short slice,
    scored by mean logged episode return — wall-clock-realistic but
    scheduler-sensitive; the rate limiter is pinned (--slice-ratio) to
    bound the noise (unpinned, PERF.md measured 25-86 return on identical
    invocations).

    python -m r2d2_tpu.cli.genetic --population 6 --generations 3 \
        --slice-steps 200 --env.game_name=Fake
"""

import argparse
import json
import sys

import numpy as np


def _ratio_pin(base_overrides, slice_ratio: float):
    """The ONE pin rule for both fitness modes: pin the rate limiter to
    ``slice_ratio`` unless the user set --replay.max_env_steps_per_train_step
    explicitly (including an explicit 0 — a free-run request) or
    ``slice_ratio`` is 0. Returns ``(user_set_ratio, pin_fn)``."""
    user_set = any("replay.max_env_steps_per_train_step" in str(o)
                   for o in base_overrides)

    def pin(cfg):
        if (slice_ratio > 0 and not user_set
                and cfg.replay.max_env_steps_per_train_step <= 0):
            return cfg.replace(
                **{"replay.max_env_steps_per_train_step": slice_ratio})
        return cfg

    return user_set, pin


def make_slice_eval(base_overrides, slice_steps: int, slice_seconds: float,
                    slice_ratio: float = 2.0):
    """``slice_ratio``: fitness slices run with the rate limiter pinned to
    this collect:learn ratio unless the base config already sets one.
    Free-running actor threads make the interleaving — and the score — a
    function of host scheduling luck (PERF.md measured 25-86 return on
    identical invocations); a pinned ratio makes selection compare
    genomes, not scheduler noise. 0 disables the pin (measured-noisy).
    An explicit --replay.max_env_steps_per_train_step override — including
    an explicit 0 — always wins over the pin."""
    from r2d2_tpu.runtime.orchestrator import train

    _, pin = _ratio_pin(base_overrides, slice_ratio)

    def eval_fn(cfg) -> float:
        cfg = pin(cfg)
        records = []
        try:
            stacks = train(cfg, max_training_steps=slice_steps,
                           max_seconds=slice_seconds, actor_mode="thread",
                           log_fn=records.append)
        except Exception as e:  # invalid genome (e.g. OOM-scale) scores -inf
            print(f"genome failed: {e}", file=sys.stderr)
            return float("-inf")
        returns = [r["avg_episode_return"] for r in records
                   if r.get("avg_episode_return") is not None]
        m = stacks[0].metrics
        if m.num_episodes:
            returns.append(m.episode_reward / m.num_episodes)
        return float(np.mean(returns[-3:])) if returns else float("-inf")

    return eval_fn


def make_sync_eval(base_overrides, slice_steps: int, slice_ratio: float = 2.0,
                   seed: int = 0, max_seconds: float = None):
    """Deterministic fitness: synchronous collect:learn at a pinned ratio,
    scored by mean greedy eval return (tools/sync_train.py). Bit-identical
    across evaluations of the same genome. Sync collection IS the ratio
    schedule, so the effective ratio must be >= 1 — rejected up front
    rather than silently scoring every genome -inf. ``max_seconds`` bounds
    each genome's wall clock (a timed-out genome scores -inf; note that
    makes the score host-speed-dependent at the margin)."""
    from r2d2_tpu.tools.sync_train import sync_fitness

    user_set_ratio, pin = _ratio_pin(base_overrides, slice_ratio)
    if not user_set_ratio and slice_ratio < 1:
        raise ValueError(
            "sync fitness needs a collect:learn ratio >= 1 (sync collection "
            "IS the ratio schedule): raise --slice-ratio, set "
            "--replay.max_env_steps_per_train_step >= 1, or use "
            "--fitness-mode=slice for free-running slices")

    def eval_fn(cfg) -> float:
        cfg = pin(cfg)
        try:
            return sync_fitness(cfg, slice_steps, seed=seed,
                                max_seconds=max_seconds)
        except Exception as e:  # invalid genome (e.g. OOM-scale) scores -inf
            print(f"genome failed: {e}", file=sys.stderr)
            return float("-inf")

    return eval_fn


def main(argv=None) -> None:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--population", type=int, default=6)
    p.add_argument("--generations", type=int, default=3)
    p.add_argument("--slice-steps", type=int, default=300)
    p.add_argument("--slice-seconds", type=float, default=600.0,
                   help="wall-clock bound per fitness slice (both modes; a "
                        "timed-out sync genome scores -inf)")
    p.add_argument("--slice-ratio", type=float, default=2.0,
                   help="pin the collect:learn rate limiter during fitness "
                        "slices (0 disables; default 2.0 — unpinned slices "
                        "score scheduler noise, see PERF.md)")
    p.add_argument("--fitness-mode", choices=("sync", "slice"),
                   default="sync",
                   help="sync: deterministic single-stream slice scored by "
                        "greedy eval (bit-reproducible); slice: threaded "
                        "orchestrator slice (wall-clock-realistic, noisier)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="genetic_results.json")
    args, config_overrides = p.parse_known_args(argv)

    from r2d2_tpu.config import Config, parse_overrides
    from r2d2_tpu.tools.genetic import run_search

    base = parse_overrides(Config(), config_overrides)
    if args.fitness_mode == "sync":
        if base.replay.max_env_steps_per_train_step < 1 and any(
                "replay.max_env_steps_per_train_step" in o
                for o in config_overrides):
            p.error("--fitness-mode=sync needs "
                    "--replay.max_env_steps_per_train_step >= 1 (sync "
                    "collection IS the ratio schedule); use "
                    "--fitness-mode=slice for free-running slices")
        try:
            eval_fn = make_sync_eval(config_overrides, args.slice_steps,
                                     args.slice_ratio, seed=args.seed,
                                     max_seconds=args.slice_seconds)
        except ValueError as e:
            p.error(str(e))
    else:
        eval_fn = make_slice_eval(config_overrides, args.slice_steps,
                                  args.slice_seconds, args.slice_ratio)

    def log(gen, result):
        genome, fit = result.best
        print(f"generation {gen}: best fitness {fit:.3f} genome {genome}",
              flush=True)

    history = run_search(eval_fn, base=base, population=args.population,
                         generations=args.generations, seed=args.seed, log_fn=log)
    best_genome, best_fit = history[-1].best
    with open(args.out, "w") as f:
        json.dump({"best_genome": best_genome, "best_fitness": best_fit,
                   "generations": [
                       {"genomes": h.genomes, "fitnesses": h.fitnesses}
                       for h in history]}, f, indent=2, default=str)
    print(f"best fitness {best_fit:.3f}; wrote {args.out}")


if __name__ == "__main__":
    main()
