"""Training CLI (ref /root/reference/train.py).

    python -m r2d2_tpu.cli.train --env.game_name=Fake --actor.num_actors=2
    python -m r2d2_tpu.cli.train --env.game_name=ALE/Boxing --env.env_type=-v5
    python -m r2d2_tpu.cli.train --multiplayer.enabled=true  # self-play stacks

    # fully on-device acting (Anakin): fused env+policy+emit scan colocated
    # with the learner — no actor fleet (README "On-device acting")
    python -m r2d2_tpu.cli.train --env.game_name=Grid --actor.on_device=true \
        --env.episode_len=120 --replay.block_length=40

Extra (non-config) flags:
    --actor-mode=thread|process   actor execution mode (default: process
                                  single-host, thread multihost)
    --max-steps=N                 stop after N learner steps
    --max-seconds=S               wall-clock bound
"""

import sys

from r2d2_tpu.config import Config, parse_overrides
from r2d2_tpu.runtime.orchestrator import train


def main(argv=None) -> None:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    actor_mode, max_steps, max_seconds = None, None, None
    rest = []
    for arg in argv:
        if arg.startswith("--actor-mode="):
            actor_mode = arg.split("=", 1)[1]
        elif arg.startswith("--max-steps="):
            max_steps = int(arg.split("=", 1)[1])
        elif arg.startswith("--max-seconds="):
            max_seconds = float(arg.split("=", 1)[1])
        else:
            rest.append(arg)
    cfg = parse_overrides(Config(), rest)

    def log(record: dict) -> None:
        print(" | ".join(f"{k}={v}" for k, v in record.items() if v is not None),
              flush=True)

    if cfg.runtime.auto_resume:
        # learner supervision (ISSUE 18): run train() as a supervised
        # child process — a crash relaunches from the newest checkpoint
        # (plus the replay snapshot under runtime.snapshot_interval);
        # SIGTERM/SIGINT forward to the child for a clean preemption
        # stop. Raises for multi-process multihost jobs (the cluster
        # scheduler supervises those).
        from r2d2_tpu.runtime.supervisor import supervise_train
        supervise_train(cfg, actor_mode=actor_mode or "process",
                        max_steps=max_steps, max_seconds=max_seconds)
        return

    if cfg.mesh.multihost and cfg.mesh.num_processes > 1:
        # multi-controller pod: run this same CLI on every host with its
        # own --mesh.process_id; the lockstep loop keeps dispatch cadences
        # identical across processes (parallel/multihost.py). Defaults to
        # thread-mode actors there; --actor-mode=process spawns CPU-pinned
        # actor processes fed through the shm ring instead.
        from r2d2_tpu.parallel.multihost import train_multihost
        train_multihost(cfg, max_training_steps=max_steps,
                        max_seconds=max_seconds,
                        actor_mode=actor_mode or "thread", log_fn=log)
        return

    train(cfg, max_training_steps=max_steps, max_seconds=max_seconds,
          actor_mode=actor_mode or "process", log_fn=log)


if __name__ == "__main__":
    main()
