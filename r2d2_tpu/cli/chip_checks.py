"""CLI alias: ``python -m r2d2_tpu.cli.chip_checks`` — see
r2d2_tpu/tools/chip_checks.py (on-chip pallas kernel compile+parity gate)."""

import sys

from r2d2_tpu.tools.chip_checks import main

if __name__ == "__main__":
    sys.exit(main())
