"""Gated checkpoint promotion + one-command rollback (ISSUE 20;
ROADMAP item 2d).

Three modes over the ``fleet/promotion.py`` plane:

  * **gate evaluation** (default): score a CANDIDATE checkpoint against
    the LIVE one offline — per-scenario eval returns through the
    ``cli/evaluate.py`` machinery — and apply the configured promotion
    gates (``fleet.promotion_*``). Prints one JSON verdict; exit 0 means
    every gate cleared (the candidate may be staged/published), exit 1
    means refused. Shadow-divergence evidence, when available (a running
    fleet's quality stream), is supplied via ``--shadow-divergence`` /
    ``--shadow-requests``; without it the shadow gate fails CLOSED
    unless ``--no-shadow-gate`` waives it (an offline gate check has no
    mirror to sample).

        python -m r2d2_tpu.cli.promote --candidate models/Fake7_player0 \\
            --live models/Fake6_player0 --rounds 5 --no-shadow-gate

  * **--rollback**: re-publish the bundle retained under
    ``{save_dir}/promotion/`` by the last ``stage()`` — the one-command
    rollback. The restored tree is the staged-time snapshot,
    bit-identical by construction.

  * **--status**: print the persisted promotion state (or, with
    ``--port``, the RUNNING supervisor's live promotion block via the
    fleet lease API).
"""

import argparse
import json
import sys


def _offline_gates(args, cfg) -> int:
    """Evaluate candidate vs live and apply the gates (no running fleet
    required — the ledger path for a live run feeds decide() instead)."""
    from r2d2_tpu.cli.evaluate import evaluate_scenarios
    from r2d2_tpu.fleet.promotion import PromotionManager

    scenarios = (args.scenarios.split(",") if args.scenarios else None)
    cand = evaluate_scenarios(cfg, args.candidate, args.rounds,
                              scenarios=scenarios, seed=cfg.runtime.seed,
                              serve=args.serve,
                              serve_clients=args.serve_clients)
    live = None
    if args.live:
        live = evaluate_scenarios(cfg, args.live, args.rounds,
                                  scenarios=scenarios,
                                  seed=cfg.runtime.seed, serve=args.serve,
                                  serve_clients=args.serve_clients)

    class _NullStore:
        publish_count = 0

        def current(self, reader_id=None):
            return None

    mgr = PromotionManager(cfg.fleet, _NullStore())
    if args.no_shadow_gate:
        # offline check: no mirror exists to sample — synthesize a
        # passing shadow observation so only eval+calibration gate
        shadow_div, shadow_reqs = 0.0, cfg.fleet.promotion_min_shadow
    else:
        shadow_div, shadow_reqs = args.shadow_divergence, \
            args.shadow_requests
    ok, gates = mgr.decide(
        candidate_return=cand["mean_return"],
        live_return=(live["mean_return"] if live is not None
                     else args.live_return),
        calibration_gap=args.calibration_gap,
        shadow_divergence=shadow_div,
        shadow_requests=shadow_reqs)
    report = {
        "verdict": "promote" if ok else "refuse",
        "gates": gates,
        "candidate": {"checkpoint": args.candidate,
                      "step": cand["step"],
                      "scenarios": cand["scenarios"]},
    }
    if live is not None:
        report["live"] = {"checkpoint": args.live, "step": live["step"],
                          "scenarios": live["scenarios"]}
    print(json.dumps(report, indent=2), flush=True)
    return 0 if ok else 1


def _rollback(args, cfg) -> int:
    from r2d2_tpu.fleet.promotion import PromotionManager
    from r2d2_tpu.runtime.weights import InProcWeightStore

    # the manager's persisted previous.pkl IS the bundle; publishing it
    # into a fresh store exercises the exact rollback code path (a
    # RUNNING run rolls back through its own manager instead —
    # PlayerStack.promotion.rollback() — and every consumer re-adopts)
    store = InProcWeightStore(None)
    mgr = PromotionManager(cfg.fleet, store,
                           save_dir=cfg.runtime.save_dir or ".")
    try:
        stamp = mgr.rollback()
    except RuntimeError as e:
        print(f"rollback failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps({"rolled_back_to_stamp": stamp,
                      "state": mgr.state}), flush=True)
    return 0


def _status(args, cfg) -> int:
    if args.port is not None:
        from r2d2_tpu.fleet.membership import lease_call
        try:
            reply = lease_call(args.host, args.port, "info",
                               timeout_s=args.timeout)
        except (RuntimeError, ConnectionError, OSError) as e:
            print(f"status failed: {e}", file=sys.stderr)
            return 1
        print(json.dumps(reply.get("promotion",
                                   {"state": "unknown"})), flush=True)
        return 0
    import os
    path = os.path.join(cfg.runtime.save_dir or ".", "promotion",
                        "state.json")
    try:
        with open(path) as f:
            print(json.dumps(json.load(f)), flush=True)
    except OSError:
        print(json.dumps({"state": "idle", "note": f"no {path}"}),
              flush=True)
    return 0


def main(argv=None) -> int:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--candidate", default=None,
                   help="candidate checkpoint path to gate")
    p.add_argument("--live", default=None,
                   help="live checkpoint path to compare against")
    p.add_argument("--live-return", type=float, default=None,
                   help="known live mean return (instead of --live)")
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--scenarios", default=None,
                   help="comma-separated env kinds (evaluate.py schema)")
    p.add_argument("--serve", action="store_true",
                   help="evaluate through an in-proc policy server")
    p.add_argument("--serve-clients", type=int, default=4)
    p.add_argument("--calibration-gap", type=float, default=None,
                   help="observed calibration gap_mean (quality stream); "
                        "omitted => the calibration gate passes open")
    p.add_argument("--shadow-divergence", type=float, default=None,
                   help="observed shadow divergence (quality stream)")
    p.add_argument("--shadow-requests", type=int, default=0,
                   help="shadow requests the divergence is over")
    p.add_argument("--no-shadow-gate", action="store_true",
                   help="waive the shadow gate (offline checks have no "
                        "mirror to sample)")
    p.add_argument("--rollback", action="store_true",
                   help="re-publish the retained previous bundle from "
                        "{save_dir}/promotion/")
    p.add_argument("--status", action="store_true",
                   help="print the persisted (or --port: live) promotion "
                        "state")
    p.add_argument("--host", default="127.0.0.1",
                   help="--status: fleet lease API host")
    p.add_argument("--port", type=int, default=None,
                   help="--status: fleet lease API port (live block)")
    p.add_argument("--timeout", type=float, default=30.0)
    args, config_overrides = p.parse_known_args(argv)

    from r2d2_tpu.config import Config, parse_overrides
    cfg = parse_overrides(Config(), config_overrides)

    if args.rollback:
        return _rollback(args, cfg)
    if args.status:
        return _status(args, cfg)
    if not args.candidate:
        p.error("--candidate is required (or use --rollback / --status)")
    return _offline_gates(args, cfg)


if __name__ == "__main__":
    sys.exit(main())
