"""Standalone policy inference server (ISSUE 13): serve a checkpoint's
policy over TCP (and optionally the shm ring for same-host clients).

    python -m r2d2_tpu.cli.serve --ckpt models/Fake3_player0 --port 5999
    python -m r2d2_tpu.cli.serve --seconds 30            # random-init smoke

The server loop owns the device-resident params and the per-client
state cache; clients are ``serve.RemotePolicy``/``RemoteBatchedPolicy``
over a ``SocketChannel`` (or ``ShmServeChannel`` with ``--shm``). A
periodic record with the ``serving`` block (request latency, batch fill,
client churn) appends to ``serve_metrics.jsonl`` in --save-dir, with the
stock alert rules (``serve_latency_slo``, ``serve_batch_starvation``,
``serve_client_churn``) evaluated per record into
``serve_alerts.jsonl`` — the same SLO plumbing the in-training server
rides. SIGTERM/SIGINT stop cleanly.

With ``serve.servers=N`` (N > 1) the process hosts a sharded serving
FLEET instead: N server loops over client-hash cache slices, one TCP
listener per fleet slot, and the printed ``socket_fleet`` spec is what
clients feed a ``RoutingChannel``. ``serve.queue_depth_bound`` arms
admission control (overflow sheds with retry-after; the
``serve_brownout`` rule fires on the shed fraction).
"""

import argparse
import json
import os
import signal
import sys
import time


def main(argv=None) -> int:
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ckpt", default="",
                   help="checkpoint to serve (empty: random init — smoke "
                        "tests and transport bring-up)")
    p.add_argument("--shm", action="store_true",
                   help="also open the same-host shm ring transport; its "
                        "request-ring name is printed for clients")
    p.add_argument("--seconds", type=float, default=0.0,
                   help="stop after this long (0 = run until signaled)")
    p.add_argument("--save-dir", default=".",
                   help="where serve_metrics.jsonl / serve_alerts.jsonl go")
    args, config_overrides = p.parse_known_args(argv)

    import jax
    import numpy as np

    from r2d2_tpu.config import Config, parse_overrides
    from r2d2_tpu.envs.factory import create_env
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.serve import (InprocEndpoint, PolicyServer, ServingStats,
                                ShmServeTransport, SocketServerTransport)
    from r2d2_tpu.telemetry import Telemetry
    from r2d2_tpu.telemetry.alerts import AlertEngine, default_rules

    cfg = parse_overrides(Config(), config_overrides)
    if args.ckpt:
        from r2d2_tpu.runtime.checkpoint import (load_checkpoint_config,
                                                 restore_checkpoint)
        stored = load_checkpoint_config(args.ckpt)
        if stored is not None:
            import dataclasses
            cfg = dataclasses.replace(cfg, env=stored.env,
                                      network=stored.network,
                                      sequence=stored.sequence)
    probe = create_env(cfg.env, seed=cfg.runtime.seed)
    action_dim = probe.action_space.n
    probe.close()
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    params = net.init(jax.random.PRNGKey(cfg.runtime.seed))
    if args.ckpt:
        restored = restore_checkpoint(args.ckpt)
        params = jax.tree_util.tree_map(
            lambda t, p_: np.asarray(p_, np.asarray(t).dtype),
            params, restored["params"])

    quant_stats = None
    if cfg.network.inference_dtype != "f32":
        # quantized serving (ISSUE 14): the server builds the twin at
        # construction and probes per dispatch interval; the quant block
        # (dtype, agreement, |ΔQ|) rides every serve_metrics record so
        # the quant_divergence rule evaluates here too
        from r2d2_tpu.telemetry import QuantStats
        quant_stats = QuantStats(cfg.network.inference_dtype,
                                 cfg.telemetry.quant_probe_interval)

    stats = ServingStats()
    tracing = cfg.telemetry.enabled and cfg.telemetry.tracing_enabled
    if tracing:
        # distributed tracing (ISSUE 19): traced requests' per-hop
        # stamps fold into the serving block's trace sub-block
        from r2d2_tpu.telemetry.tracing import ServeTrace
        stats.trace = ServeTrace()
    telemetry = Telemetry.from_config(cfg, name="serve")
    fleet = None
    endpoint = None
    transports = []
    if cfg.serve.servers > 1:
        # sharded serving fleet (ISSUE 17): N server loops, one TCP
        # listener per fleet slot (parked slots included — their
        # listeners bounce MISROUTED so growth never changes an
        # address). The printed spec is exactly what actor_main's
        # socket_fleet branch consumes to build a RoutingChannel.
        if args.shm:
            p.error("--shm is single-server only (serve.servers > 1 "
                    "rejects the shm rung)")
        from r2d2_tpu.serve import ServerFleet
        fleet = ServerFleet(cfg, net, params, stats=stats,
                            telemetry=telemetry, quant_stats=quant_stats)
        spec_servers = {}
        for slot, ep in fleet.serve_spec_servers().items():
            port = cfg.serve.port + slot if cfg.serve.port else 0
            t = SocketServerTransport(ep.submit, cfg.serve.host, port)
            transports.append(t)
            spec_servers[slot] = [t.host, t.port]
        spec = {"transport": "socket_fleet", "servers": spec_servers,
                "total_shards": fleet.total_shards,
                "assign": [fleet.shard_map.version,
                           list(fleet.shard_map.assignment())]}
        print(f"serving fleet of {cfg.serve.servers} "
              f"(max {fleet.max_servers}) — spec: "
              + json.dumps(spec), flush=True)
    else:
        endpoint = InprocEndpoint()
        transports = [SocketServerTransport(endpoint.submit, cfg.serve.host,
                                            cfg.serve.port)]
        print(f"serving on {transports[0].host}:{transports[0].port} "
              f"(action_dim={action_dim})", flush=True)
        if args.shm:
            shm_t = ShmServeTransport(
                endpoint.submit, (cfg.env.frame_height, cfg.env.frame_width),
                action_dim, cfg.network.hidden_dim,
                request_slots=cfg.serve.request_ring_slots,
                tracing=tracing)
            transports.append(shm_t)
            print(f"shm request ring: {shm_t.request_ring.name}", flush=True)

    os.makedirs(args.save_dir or ".", exist_ok=True)
    metrics_path = os.path.join(args.save_dir or ".", "serve_metrics.jsonl")
    open(metrics_path, "w").close()
    engine = AlertEngine(
        default_rules(cfg.telemetry),
        jsonl_path=os.path.join(args.save_dir or ".", "serve_alerts.jsonl"))
    # process identity + clock anchor (ISSUE 19 satellite): stamped ONCE
    # at announcement (the listener going live IS this plane's lease
    # moment) and carried on every periodic row, so the tower join and
    # the Perfetto merge align this stream without a shared mono clock
    from r2d2_tpu.telemetry.tracing import proc_header
    proc = proc_header("serve")
    telemetry.start_drain(
        os.path.join(args.save_dir or ".", "spans_serve.jsonl"))

    server = None
    if fleet is None:
        server = PolicyServer(cfg, net, params, endpoint=endpoint,
                              stats=stats, telemetry=telemetry,
                              quant_stats=quant_stats).start()

    def _batches() -> int:
        if server is not None:
            return server.batches_dispatched
        return sum(s.batches_dispatched for s in fleet.servers.values())

    def _serving_block():
        if fleet is not None:
            return fleet.interval_block(deadline_ms=cfg.serve.deadline_ms,
                                        max_batch=cfg.serve.max_batch)
        return stats.interval_block(deadline_ms=cfg.serve.deadline_ms,
                                    max_batch=cfg.serve.max_batch)

    stop = {"flag": False}

    def _on_signal(signum, frame):
        stop["flag"] = True

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass

    t0 = time.time()
    last_log = t0
    try:
        while not stop["flag"]:
            if args.seconds and time.time() - t0 >= args.seconds:
                break
            time.sleep(0.2)
            if fleet is not None:
                # fleet supervision on the log-loop cadence: a dead
                # server's shards rehome to survivors (clients re-route
                # off the MISROUTED bounces)
                fleet.supervise()
            now = time.time()
            if now - last_log >= cfg.runtime.log_interval:
                last_log = now
                block = _serving_block()
                record = {"t": round(now - t0, 1),
                          "batches": _batches(), "proc": proc}
                if block is not None:   # the TrainMetrics omission contract
                    record["serving"] = block
                if quant_stats is not None:
                    record["quant"] = quant_stats.interval_block()
                record["alerts"] = engine.evaluate(record)
                with open(metrics_path, "a") as f:
                    f.write(json.dumps(record) + "\n")
    finally:
        final_batches = _batches()
        if server is not None:
            server.stop()
        if fleet is not None:
            fleet.stop()
        for t in transports:
            t.close()
        telemetry.close()
        # final record so short runs still leave evidence
        block = _serving_block()
        record = {"t": round(time.time() - t0, 1),
                  "batches": final_batches, "final": True, "proc": proc}
        if block is not None:
            record["serving"] = block
        if quant_stats is not None:
            record["quant"] = quant_stats.interval_block()
        record["alerts"] = engine.evaluate(record)
        with open(metrics_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        print(f"served {final_batches} batches in "
              f"{time.time() - t0:.1f}s; records in {metrics_path}",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
