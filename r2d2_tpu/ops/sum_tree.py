"""Prioritized-replay sum tree, as data-parallel array ops.

The reference keeps a flat-array binary sum tree updated and sampled by numba
LLVM kernels on the replay-buffer host process
(/root/reference/priority_tree.py:7-49) — every learner step pays a host-side
tree walk. Both kernels are already expressed as whole-array operations
(leaf scatter + bottom-up parent rebuild; batched stratified root-to-leaf
descent), so here they map 1:1 onto jnp scatter/gather with a statically
unrolled layer loop, and run *on device inside the jitted learner step*: the
learner never blocks on a host round-trip for priorities (BASELINE.json north
star). A numpy twin backs the host-feeder fallback path and serves as the test
oracle; the C++ native variant lives in r2d2_tpu/native/.

Layout: a single 1-D array of 2**num_layers - 1 nodes; node 0 is the root
holding the total priority mass, leaves occupy [2**(L-1) - 1, 2**L - 1).
float32 on device (TPU has no fast f64); with <=2**20 leaves and O(1)
priorities the stratified-descent error from f32 accumulation is far below the
sampling jitter itself.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def tree_num_layers(capacity: int) -> int:
    """Smallest L with 2**(L-1) >= capacity leaves (ref priority_tree.py:7-11)."""
    num_layers = 1
    while capacity > 2 ** (num_layers - 1):
        num_layers += 1
    return num_layers


def tree_init(capacity: int, dtype=jnp.float32) -> Tuple[int, jnp.ndarray]:
    num_layers = tree_num_layers(capacity)
    return num_layers, jnp.zeros(2**num_layers - 1, dtype=dtype)


@functools.partial(jax.jit, static_argnums=(0,))
def tree_update(
    num_layers: int,
    tree: jnp.ndarray,
    prio_exponent: float,
    td_errors: jnp.ndarray,
    idxes: jnp.ndarray,
) -> jnp.ndarray:
    """Write p = td**alpha at the given leaves and rebuild ancestor sums.

    alpha = 0 must still give p = 0 for td = 0 so PER can be disabled without a
    code path change (ref priority_tree.py:17). Duplicate parent writes in the
    bottom-up sweep all carry the same recomputed value, so scatter-set is safe.
    """
    # "sum_tree" component scope (ISSUE 9): these scatter/gather chains
    # trace inline into the fused learner step, so without the scope
    # their device time would land in the step's unattributed bucket
    # (telemetry/traceparse.py keys on the token)
    with jax.named_scope("sum_tree_update"):
        td_errors = td_errors.astype(tree.dtype)
        priorities = jnp.where(
            td_errors != 0.0, jnp.abs(td_errors) ** prio_exponent, 0.0
        )
        node = idxes.astype(jnp.int32) + 2 ** (num_layers - 1) - 1
        tree = tree.at[node].set(priorities)
        for _ in range(num_layers - 1):
            node = (node - 1) // 2
            tree = tree.at[node].set(tree[2 * node + 1] + tree[2 * node + 2])
        return tree


@functools.partial(jax.jit, static_argnums=(0, 3))
def tree_sample(
    num_layers: int,
    tree: jnp.ndarray,
    is_exponent: float,
    num_samples: int,
    key: jax.Array,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stratified proportional sampling + importance weights.

    The total mass is split into num_samples equal strata; one uniform draw per
    stratum descends the tree root-to-leaf, the whole batch in lockstep
    (ref priority_tree.py:29-49). Returns (leaf_idxes, is_weights) with
    is_weights = (p / min_p) ** -beta.

    Callers must not sample an empty tree (total mass 0 yields NaN weights);
    training is gated on replay.learning_starts exactly as the reference gates
    on ReplayBuffer.ready (ref worker.py:214-218).
    """
    with jax.named_scope("sum_tree_sample"):
        return _tree_sample_body(num_layers, tree, is_exponent, num_samples,
                                 key)


def _tree_sample_body(num_layers, tree, is_exponent, num_samples, key):
    p_sum = tree[0]
    interval = p_sum / num_samples
    jitter = jax.random.uniform(
        key, (num_samples,), dtype=tree.dtype, minval=0.0, maxval=1.0
    )
    prefixsums = (jnp.arange(num_samples, dtype=tree.dtype) + jitter) * interval
    # f32 rounding can push the top stratum to exactly p_sum (or past a subtree
    # total mid-descent), which would walk into a zero-priority padding leaf and
    # produce NaN weights. Clamp below the total, and never enter a zero-mass
    # right subtree.
    prefixsums = jnp.minimum(prefixsums, p_sum * (1.0 - 1e-6))

    node = jnp.zeros(num_samples, dtype=jnp.int32)
    for _ in range(num_layers - 1):
        left_sum = tree[node * 2 + 1]
        right_sum = tree[node * 2 + 2]
        go_left = (prefixsums < left_sum) | (right_sum <= 0.0)
        node = jnp.where(go_left, node * 2 + 1, node * 2 + 2)
        prefixsums = jnp.where(
            go_left, jnp.minimum(prefixsums, left_sum * (1.0 - 1e-6)), prefixsums - left_sum
        )

    priorities = tree[node]
    min_p = jnp.min(priorities)
    is_weights = jnp.power(priorities / min_p, -is_exponent)
    leaf = node - (2 ** (num_layers - 1) - 1)
    return leaf, is_weights


def tree_total(tree: jnp.ndarray) -> jnp.ndarray:
    return tree[0]


# ---------------------------------------------------------------------------
# numpy twin (host feeder fallback + test oracle)
# ---------------------------------------------------------------------------


def tree_init_np(capacity: int) -> Tuple[int, np.ndarray]:
    num_layers = tree_num_layers(capacity)
    return num_layers, np.zeros(2**num_layers - 1, dtype=np.float64)


def tree_update_np(
    num_layers: int,
    tree: np.ndarray,
    prio_exponent: float,
    td_errors: np.ndarray,
    idxes: np.ndarray,
) -> None:
    priorities = np.where(td_errors != 0.0, np.abs(td_errors) ** prio_exponent, 0.0)
    node = np.asarray(idxes, dtype=np.int64) + 2 ** (num_layers - 1) - 1
    tree[node] = priorities
    for _ in range(num_layers - 1):
        node = np.unique((node - 1) // 2)
        tree[node] = tree[2 * node + 1] + tree[2 * node + 2]


def tree_sample_np(
    num_layers: int,
    tree: np.ndarray,
    is_exponent: float,
    num_samples: int,
    rng: np.random.Generator,
) -> Tuple[np.ndarray, np.ndarray]:
    p_sum = tree[0]
    interval = p_sum / num_samples
    prefixsums = np.arange(num_samples, dtype=np.float64) * interval + rng.uniform(
        0, interval, num_samples
    )
    prefixsums = np.minimum(prefixsums, p_sum * (1.0 - 1e-12))
    node = np.zeros(num_samples, dtype=np.int64)
    for _ in range(num_layers - 1):
        left_sum = tree[node * 2 + 1]
        right_sum = tree[node * 2 + 2]
        go_left = (prefixsums < left_sum) | (right_sum <= 0.0)
        node = np.where(go_left, node * 2 + 1, node * 2 + 2)
        prefixsums = np.where(
            go_left, np.minimum(prefixsums, left_sum * (1.0 - 1e-12)), prefixsums - left_sum
        )
    priorities = tree[node]
    is_weights = np.power(priorities / priorities.min(), -is_exponent)
    return node - (2 ** (num_layers - 1) - 1), is_weights
