"""Pallas TPU kernels for the learner's data-decode hot path.

``stack_frames``: expand a raw uint8 frame row into the frame-stacked,
normalized f32 observation tensor the conv torso consumes:

    obs (B, T+K-1, H, W) uint8  →  (B, T, H, W, K) float32 in [0, 1]
    out[b, t, h, w, k] = obs[b, t + k, h, w] / 255

This is the reference learner's obs_idx gather + /255
(/root/reference/worker.py:310,330-331) — a pure data-movement + elementwise
op. The XLA lowering of the jnp version materializes the (B, T, K, H, W)
uint8 gather, then a transposed f32 copy (5x the input bytes through HBM);
the pallas kernel streams each batch row through VMEM once and emits the
stacked f32 directly, fusing window expansion, transpose, dtype conversion,
and normalization.

Grid: one program per batch row. Per-program working set (defaults
T=55, K=4, 84x84): 409 KB uint8 in + 6.2 MB f32 out — fits VMEM. The window
shifts are static Python offsets, so each shift is a contiguous VMEM slice
(no dynamic gather). No custom VJP is needed: observations carry no
gradient (grads flow to params only).

``stack_frames_reference`` is the jnp twin — the test oracle and the
non-TPU fallback.
"""

import functools

import jax
import jax.numpy as jnp

from r2d2_tpu.ops.indexing import frame_stack_indices


def stack_frames_reference(obs: jnp.ndarray, seq_window: int,
                           frame_stack: int) -> jnp.ndarray:
    """jnp twin: gather + transpose + normalize (XLA-lowered)."""
    fsi = frame_stack_indices(seq_window, frame_stack)       # (T, K)
    stacked = obs[:, fsi]                                     # (B, T, K, H, W)
    return stacked.transpose(0, 1, 3, 4, 2).astype(jnp.float32) / 255.0


def _stack_kernel(seq_window: int, frame_stack: int, in_ref, out_ref):
    # in_ref: (1, T+K-1, H, W) uint8; out_ref: (1, T, H, W, K) f32
    inv = jnp.float32(1.0 / 255.0)
    for k in range(frame_stack):
        window = in_ref[0, k : k + seq_window]               # (T, H, W) u8
        out_ref[0, :, :, :, k] = window.astype(jnp.float32) * inv


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def stack_frames_pallas(obs: jnp.ndarray, seq_window: int, frame_stack: int,
                        interpret: bool = False) -> jnp.ndarray:
    """Pallas implementation; ``interpret=True`` runs it on any backend
    (tests use it on the CPU mesh)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, row_len, height, width = obs.shape
    assert row_len >= seq_window + frame_stack - 1

    kernel = functools.partial(_stack_kernel, seq_window, frame_stack)
    return pl.pallas_call(
        kernel,
        grid=(batch,),
        in_specs=[pl.BlockSpec(
            (1, row_len, height, width),
            lambda b: (b, 0, 0, 0),
            memory_space=pltpu.VMEM,
        )],
        out_specs=pl.BlockSpec(
            (1, seq_window, height, width, frame_stack),
            lambda b: (b, 0, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, seq_window, height, width, frame_stack), jnp.float32),
        interpret=interpret,
    )(obs)


def stack_frames(obs: jnp.ndarray, seq_window: int, frame_stack: int,
                 use_pallas: bool = False) -> jnp.ndarray:
    """Dispatch: pallas on TPU when requested, jnp otherwise."""
    if use_pallas:
        return stack_frames_pallas(obs, seq_window, frame_stack)
    return stack_frames_reference(obs, seq_window, frame_stack)
