"""Pallas TPU kernels for the learner's data-decode hot path.

``stack_frames``: expand a raw uint8 frame row into the frame-stacked,
normalized f32 observation tensor the conv torso consumes:

    obs (B, T+K-1, H, W) uint8  →  (B, T, H, W, K) float32 in [0, 1]
    out[b, t, h, w, k] = obs[b, t + k, h, w] / 255

This is the reference learner's obs_idx gather + /255
(/root/reference/worker.py:310,330-331) — a pure data-movement + elementwise
op. The XLA lowering of the jnp version materializes the (B, T, K, H, W)
uint8 gather, then a transposed f32 copy (5x the input bytes through HBM);
the pallas kernel streams each batch row through VMEM once and emits the
stacked f32 directly, fusing window expansion, transpose, dtype conversion,
and normalization.

Grid: (batch, seq_window), t fastest. The input spec maps every t to the
same uint8 row block, so Pallas's revisiting optimization DMAs each row
into VMEM once per batch index and the K-frame windows are VMEM slices;
the output streams one timestep slab per program.

Layout note (measured, round 3): the kernel emits (B, T, K, H, W) — K
*before* the spatial dims — and the wrapper transposes to the public
(B, T, H, W, K) contract outside the kernel. Emitting K minor-most
directly is catastrophic on TPU: the (8, 128) register tile pads the
trailing (84, 4) dims to (88, 128), inflating the HBM buffer 32x (26 GB
at batch 128) and a full-window VMEM block to 416 MB. With (84, 84)
minor the padding is 1.6x and the per-timestep VMEM slab is ~180 KB; the
explicit transpose lands inside the jitted train step where XLA folds it
into its own layout assignment for the conv torso. No custom VJP is
needed: observations carry no gradient (grads flow to params only).

``stack_frames_reference`` is the jnp twin — the test oracle and the
non-TPU fallback.
"""

import functools

import jax
import jax.numpy as jnp

from r2d2_tpu.ops.indexing import frame_stack_indices


def stack_frames_reference(obs: jnp.ndarray, seq_window: int,
                           frame_stack: int,
                           out_dtype=jnp.float32,
                           out_height=None,
                           out_width=None) -> jnp.ndarray:
    """jnp twin: gather + transpose + normalize (XLA-lowered).
    ``out_dtype``: emit in the network's compute dtype — normalization
    always happens in f32 and rounds once at the end, so a bf16 output is
    bit-identical to XLA's own f32→bf16 cast at the conv boundary (which
    the MXU's default precision inserts anyway); emitting it here skips
    materializing the 4x-larger f32 intermediate.
    ``out_height``/``out_width``: strip tile padding from exact-gather
    storage rows (ReplaySpec.stored_frame_height/_width) — the network
    always sees the true frame shape."""
    fsi = frame_stack_indices(seq_window, frame_stack)       # (T, K)
    stacked = obs[:, fsi]                                     # (B, T, K, H, W)
    if out_height is not None and out_height != obs.shape[2]:
        stacked = stacked[:, :, :, :out_height, :]
    if out_width is not None and out_width != obs.shape[3]:
        stacked = stacked[:, :, :, :, :out_width]
    out = stacked.transpose(0, 1, 3, 4, 2).astype(jnp.float32) / 255.0
    return out.astype(out_dtype)


def _stack_kernel(frame_stack: int, out_dtype, out_height: int,
                  out_width: int, in_ref, out_ref):
    # in_ref: (1, T+K-1, H_stored, W_stored) uint8 (whole row, revisited
    # across t); out_ref: (1, 1, K, out_height, out_width) out_dtype —
    # this program's timestep slab. out_height/out_width < stored strip
    # exact-gather tile padding (static sublane/lane-dim slices).
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    inv = jnp.float32(1.0 / 255.0)
    for k in range(frame_stack):
        frame = in_ref[0, pl.dslice(t + k, 1)]               # (1, H, W) u8
        # Mosaic can't lower uint8 -> float32 directly (BENCH_r02 failure);
        # widen through int32 first, which it can, then convert. The
        # normalization rounds once from f32 into out_dtype — identical to
        # XLA's own cast at the conv boundary under a bf16 policy.
        widened = frame[0, :out_height, :out_width].astype(
            jnp.int32).astype(jnp.float32)
        out_ref[0, 0, k] = (widened * inv).astype(out_dtype)


def _decode_plane(in_ref, t, k, out_height: int, out_width: int):
    """One frame plane, decoded to normalized f32 (H, W) in registers.
    Mosaic can't cast uint8 -> f32 directly (BENCH_r02): widen via i32."""
    from jax.experimental import pallas as pl

    frame = in_ref[0, pl.dslice(t + k, 1)]                   # (1, H, W) u8
    widened = frame[0, :out_height, :out_width].astype(
        jnp.int32).astype(jnp.float32)
    return widened * jnp.float32(1.0 / 255.0)


def _stack_kernel_nhwc32(frame_stack: int, out_dtype, out_height: int,
                         out_width: int, in_ref, out_ref):
    # NHWC-emitting variant for 32-bit out_dtype: interleave K into the
    # LANE dim (out lane index = w*K + k) with one strided store per
    # plane, so the public (B, T, H, W, K) contract is a free reshape of
    # the kernel output — no post-kernel transpose. The relayout happens
    # in VMEM registers per timestep instead of as an HBM round-trip (the
    # 1.6 ms/step layout copy in the round-3 profile). Strided stores are
    # implemented for 32-bit data only (v5e Mosaic), hence the packed
    # 16-bit variant below.
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    for k in range(frame_stack):
        val = _decode_plane(in_ref, t, k, out_height, out_width)
        out_ref[0, 0, :, pl.Slice(k, out_width, frame_stack)] = (
            val.astype(out_dtype))


def _stack_kernel_nhwc16(frame_stack: int, out_dtype, out_height: int,
                         out_width: int, in_ref, out_ref):
    # NHWC-emitting variant for 16-bit out_dtype (the bf16 policy).
    # Mosaic rejects every direct 16-bit relayout route on v5e: bf16
    # minor-dim insertion ("32-bit only"), the (H,W,K)->(H,W*K)
    # lane-merge reshape, and 16-bit strided stores. The working route
    # is PAIR PACKING: bitcast each bf16 plane to u16, pack planes
    # 2p/2p+1 into the low/high halves of one i32 vector, and emit with
    # 32-bit strided stores into an i32 output at lane j = w*(K/2) + p.
    # The wrapper's i32 -> out_dtype bitcast appends a trailing dim of 2
    # indexing [low, high] bits (XLA narrowing convention), so final
    # bf16 lane l = j*2 + e = w*K + 2p + e = w*K + k — exactly NHWC.
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    pairs = frame_stack // 2
    for p in range(pairs):
        lo = jax.lax.bitcast_convert_type(
            _decode_plane(in_ref, t, 2 * p, out_height, out_width)
            .astype(out_dtype), jnp.uint16).astype(jnp.int32)
        hi = jax.lax.bitcast_convert_type(
            _decode_plane(in_ref, t, 2 * p + 1, out_height, out_width)
            .astype(out_dtype), jnp.uint16).astype(jnp.int32)
        packed = jax.lax.bitwise_or(lo, jax.lax.shift_left(hi, 16))
        out_ref[0, 0, :, pl.Slice(p, out_width, pairs)] = packed


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5, 6, 7))
def stack_frames_pallas(obs: jnp.ndarray, seq_window: int, frame_stack: int,
                        interpret: bool = False,
                        out_dtype=jnp.float32,
                        out_height=None,
                        nhwc: bool = False,
                        out_width=None) -> jnp.ndarray:
    """Pallas implementation; ``interpret=True`` runs it on any backend
    (tests use it on the CPU mesh). ``out_height``/``out_width``: emit only
    the first out_height x out_width pixels of each (possibly tile-padded)
    stored frame. ``nhwc``: emit the NHWC layout in-kernel (no post-kernel
    transpose — see _stack_kernel_nhwc); optim.pallas_decode_layout
    selects it."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, row_len, height, width = obs.shape
    assert row_len >= seq_window + frame_stack - 1
    out_height = height if out_height is None else out_height
    out_width = width if out_width is None else out_width

    if nhwc:
        itemsize = jnp.dtype(out_dtype).itemsize
        if itemsize == 2 and frame_stack % 2 == 0:
            # packed route (see _stack_kernel_nhwc16): i32 storage holding
            # bf16 pairs; bitcast back outside the kernel (layout-free)
            kernel = functools.partial(_stack_kernel_nhwc16, frame_stack,
                                       out_dtype, out_height, out_width)
            out_block = (1, 1, out_height, out_width * frame_stack // 2)
            store_dtype = jnp.int32
        elif itemsize == 4:
            kernel = functools.partial(_stack_kernel_nhwc32, frame_stack,
                                       out_dtype, out_height, out_width)
            out_block = (1, 1, out_height, out_width * frame_stack)
            store_dtype = out_dtype
        else:
            raise NotImplementedError(
                f"nhwc decode needs a 32-bit out_dtype or a 16-bit one "
                f"with even frame_stack; got {jnp.dtype(out_dtype).name} "
                f"with frame_stack={frame_stack}")
        out_map = lambda b, t: (b, t, 0, 0)
    else:
        kernel = functools.partial(_stack_kernel, frame_stack, out_dtype,
                                   out_height, out_width)
        out_block = (1, 1, frame_stack, out_height, out_width)
        out_map = lambda b, t: (b, t, 0, 0, 0)
        store_dtype = out_dtype
    out = pl.pallas_call(
        kernel,
        grid=(batch, seq_window),
        in_specs=[pl.BlockSpec(
            (1, row_len, height, width),
            lambda b, t: (b, 0, 0, 0),   # constant in t: one DMA per row
            memory_space=pltpu.VMEM,
        )],
        out_specs=pl.BlockSpec(out_block, out_map,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (batch, seq_window) + out_block[2:], store_dtype),
        interpret=interpret,
    )(obs)
    if nhwc:
        if store_dtype != out_dtype:
            # i32 -> (..., 2) out_dtype; index 0 = low 16 bits (XLA
            # narrowing convention), matching the kernel's pack order
            out = jax.lax.bitcast_convert_type(out, out_dtype)
        # lane index = w*K + k, so this reshape is layout-free
        return out.reshape(batch, seq_window, out_height, out_width,
                           frame_stack)
    return out.transpose(0, 1, 3, 4, 2)                      # (B, T, H, W, K)


def stack_frames_pallas_nhwc(obs: jnp.ndarray, seq_window: int,
                             frame_stack: int, interpret: bool = False,
                             out_dtype=jnp.float32,
                             out_height=None,
                             out_width=None) -> jnp.ndarray:
    """NHWC-emitting decode (stack_frames_pallas with nhwc=True)."""
    return stack_frames_pallas(obs, seq_window, frame_stack, interpret,
                               out_dtype, out_height, nhwc=True,
                               out_width=out_width)


def resolve_pallas_setting(setting, field: str = "pallas setting") -> bool:
    """Resolve a pallas tri-state config knob: "on", "off", or "auto" =
    pallas iff the default backend is TPU (the measured winner there —
    BENCH_r03 — while Mosaic cannot compile for CPU/GPU backends). Accepts
    legacy bools (configs serialized before the tri-state existed) and
    their CLI string spellings (--optim.pallas_obs_decode=true coerces to
    the literal string "true")."""
    if isinstance(setting, bool):
        return setting
    lowered = str(setting).lower()
    if lowered == "auto":
        return jax.default_backend() == "tpu"
    if lowered in ("on", "true", "1", "yes"):
        return True
    if lowered in ("off", "false", "0", "no"):
        return False
    raise ValueError(
        f"{field} must be 'on', 'off', or 'auto'; got {setting!r}")


def resolve_pallas_obs_decode(setting) -> bool:
    return resolve_pallas_setting(setting, "pallas_obs_decode")


def stack_frames(obs: jnp.ndarray, seq_window: int, frame_stack: int,
                 use_pallas: bool = False,
                 out_dtype=jnp.float32,
                 out_height=None,
                 nhwc: bool = False,
                 out_width=None) -> jnp.ndarray:
    """Dispatch: pallas on TPU when requested (``nhwc`` selects the
    transpose-free NHWC-emitting kernel), jnp otherwise."""
    if use_pallas:
        return stack_frames_pallas(obs, seq_window, frame_stack,
                                   out_dtype=out_dtype, out_height=out_height,
                                   nhwc=nhwc, out_width=out_width)
    return stack_frames_reference(obs, seq_window, frame_stack,
                                  out_dtype=out_dtype, out_height=out_height,
                                  out_width=out_width)


# ---------------------------------------------------------------------------
# Replay-sample window gather (the learner-side obs slice of
# /root/reference/worker.py:140-166, which the reference runs as a
# 128-iteration Python loop in the buffer process).


def gather_rows_reference(ring: jnp.ndarray, block_idx: jnp.ndarray,
                          start: jnp.ndarray, window: int) -> jnp.ndarray:
    """vmapped dynamic-slice twin — correct everywhere, but XLA lowers the
    batched start indices to a generic uint8 gather that measures ~5.5 ms
    at the production shape on TPU v5e (BENCH_r03 analysis)."""
    def one(b, t0):
        return jax.lax.dynamic_slice(
            ring[b], (t0, 0, 0), (window,) + ring.shape[2:])
    return jax.vmap(one)(block_idx, start)


@functools.partial(jax.jit, static_argnums=(3, 4))
def gather_rows_pallas(ring: jnp.ndarray, block_idx: jnp.ndarray,
                       start: jnp.ndarray, window: int,
                       interpret: bool = False) -> jnp.ndarray:
    """Scalar-prefetch row gather: out[i] = ring[block_idx[i],
    start[i] : start[i]+window].

    One program per sampled sequence. The prefetched block index drives the
    input BlockSpec, so each program's whole ring row is DMA'd into VMEM
    and the dynamic window offset becomes a VMEM slice. Reads amplify by
    row_len/window (~7x at the production shape) but stay sequential DMAs —
    measured 2.15 ms vs the 5.5 ms XLA gather (2.6x). The exact-read
    variants lose: per-frame blocks pay too many small DMAs (2.8 ms), and
    a raw HBM->HBM async copy is rejected by Mosaic (window slices must be
    tile-aligned; H=84 is not)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    num_rows, row_len, height, width = ring.shape
    batch = block_idx.shape[0]

    def kernel(bi_ref, st_ref, in_ref, out_ref):
        i = pl.program_id(0)
        out_ref[0] = in_ref[0, pl.dslice(st_ref[i], window)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch,),
        in_specs=[pl.BlockSpec(
            (1, row_len, height, width),
            lambda i, bi, st: (bi[i], 0, 0, 0),
        )],
        out_specs=pl.BlockSpec(
            (1, window, height, width),
            lambda i, bi, st: (i, 0, 0, 0),
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (batch, window, height, width), ring.dtype),
        interpret=interpret,
    )(block_idx, start, ring)


@functools.partial(jax.jit, static_argnums=(3, 4))
def gather_rows_exact_pallas(ring: jnp.ndarray, block_idx: jnp.ndarray,
                             start: jnp.ndarray, window: int,
                             interpret: bool = False) -> jnp.ndarray:
    """EXACT-read row gather: one HBM→HBM async copy of just the window
    slice per sampled sequence — no row amplification (gather_rows_pallas
    reads the whole ring row, ~7x the window bytes at the production
    shape).

    Mosaic requires BOTH minor dims of the copied slice to be
    tile-aligned: H=84 was rejected round 3, and an H-only pad was
    rejected round 4 (dim-3 tiling is 128), which is why this variant
    pairs with ``replay.pallas_exact_gather`` (storage padded 84x84 →
    96x128, the uint8 (32, 128) tile). Whether the padded copy
    compiles/wins is a TPU measurement (bench.py's pad-gather cell);
    interpret mode pins the semantics either way."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    num_rows, row_len, height, width = ring.shape
    batch = block_idx.shape[0]

    def kernel(bi_ref, st_ref, hbm_ref, out_ref, sem):
        i = pl.program_id(0)
        copy = pltpu.make_async_copy(
            hbm_ref.at[bi_ref[i], pl.dslice(st_ref[i], window)],
            out_ref.at[i],
            sem)
        copy.start()
        copy.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (batch, window, height, width), ring.dtype),
        interpret=interpret,
    )(block_idx, start, ring)


def gather_rows(ring: jnp.ndarray, block_idx: jnp.ndarray, start: jnp.ndarray,
                window: int, use_pallas: bool = False,
                exact_read: bool = False) -> jnp.ndarray:
    """Dispatch: pallas on TPU when requested (exact_read selects the
    async-copy window gather), vmapped dynamic-slice otherwise."""
    if use_pallas and exact_read:
        return gather_rows_exact_pallas(ring, block_idx, start, window)
    if use_pallas:
        return gather_rows_pallas(ring, block_idx, start, window)
    return gather_rows_reference(ring, block_idx, start, window)
