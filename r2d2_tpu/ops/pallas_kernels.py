"""Pallas TPU kernels for the learner's data-decode hot path.

``stack_frames``: expand a raw uint8 frame row into the frame-stacked,
normalized f32 observation tensor the conv torso consumes:

    obs (B, T+K-1, H, W) uint8  →  (B, T, H, W, K) float32 in [0, 1]
    out[b, t, h, w, k] = obs[b, t + k, h, w] / 255

This is the reference learner's obs_idx gather + /255
(/root/reference/worker.py:310,330-331) — a pure data-movement + elementwise
op. The XLA lowering of the jnp version materializes the (B, T, K, H, W)
uint8 gather, then a transposed f32 copy (5x the input bytes through HBM);
the pallas kernel streams each batch row through VMEM once and emits the
stacked f32 directly, fusing window expansion, transpose, dtype conversion,
and normalization.

Grid: (batch, seq_window), t fastest. The input spec maps every t to the
same uint8 row block, so Pallas's revisiting optimization DMAs each row
into VMEM once per batch index and the K-frame windows are VMEM slices;
the output streams one timestep slab per program.

Layout note (measured, round 3): the kernel emits (B, T, K, H, W) — K
*before* the spatial dims — and the wrapper transposes to the public
(B, T, H, W, K) contract outside the kernel. Emitting K minor-most
directly is catastrophic on TPU: the (8, 128) register tile pads the
trailing (84, 4) dims to (88, 128), inflating the HBM buffer 32x (26 GB
at batch 128) and a full-window VMEM block to 416 MB. With (84, 84)
minor the padding is 1.6x and the per-timestep VMEM slab is ~180 KB; the
explicit transpose lands inside the jitted train step where XLA folds it
into its own layout assignment for the conv torso. No custom VJP is
needed: observations carry no gradient (grads flow to params only).

``stack_frames_reference`` is the jnp twin — the test oracle and the
non-TPU fallback.
"""

import functools

import jax
import jax.numpy as jnp

from r2d2_tpu.ops.indexing import frame_stack_indices


def stack_frames_reference(obs: jnp.ndarray, seq_window: int,
                           frame_stack: int) -> jnp.ndarray:
    """jnp twin: gather + transpose + normalize (XLA-lowered)."""
    fsi = frame_stack_indices(seq_window, frame_stack)       # (T, K)
    stacked = obs[:, fsi]                                     # (B, T, K, H, W)
    return stacked.transpose(0, 1, 3, 4, 2).astype(jnp.float32) / 255.0


def _stack_kernel(frame_stack: int, in_ref, out_ref):
    # in_ref: (1, T+K-1, H, W) uint8 (whole row, revisited across t);
    # out_ref: (1, 1, K, H, W) f32 — this program's timestep slab.
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    inv = jnp.float32(1.0 / 255.0)
    for k in range(frame_stack):
        frame = in_ref[0, pl.dslice(t + k, 1)]               # (1, H, W) u8
        # Mosaic can't lower uint8 -> float32 directly (BENCH_r02 failure);
        # widen through int32 first, which it can, then convert.
        widened = frame[0].astype(jnp.int32).astype(jnp.float32)
        out_ref[0, 0, k] = widened * inv


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def stack_frames_pallas(obs: jnp.ndarray, seq_window: int, frame_stack: int,
                        interpret: bool = False) -> jnp.ndarray:
    """Pallas implementation; ``interpret=True`` runs it on any backend
    (tests use it on the CPU mesh)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, row_len, height, width = obs.shape
    assert row_len >= seq_window + frame_stack - 1

    kernel = functools.partial(_stack_kernel, frame_stack)
    planar = pl.pallas_call(
        kernel,
        grid=(batch, seq_window),
        in_specs=[pl.BlockSpec(
            (1, row_len, height, width),
            lambda b, t: (b, 0, 0, 0),   # constant in t: one DMA per row
            memory_space=pltpu.VMEM,
        )],
        out_specs=pl.BlockSpec(
            (1, 1, frame_stack, height, width),
            lambda b, t: (b, t, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, seq_window, frame_stack, height, width), jnp.float32),
        interpret=interpret,
    )(obs)
    return planar.transpose(0, 1, 3, 4, 2)                   # (B, T, H, W, K)


def resolve_pallas_obs_decode(setting: str) -> bool:
    """Resolve the OptimConfig.pallas_obs_decode tri-state: "on", "off", or
    "auto" = pallas iff the default backend is TPU (the measured winner
    there — BENCH_r03 — while Mosaic cannot compile for CPU/GPU backends).
    Accepts legacy bools (checkpoints/configs serialized before the
    tri-state existed) and their CLI string spellings
    (--optim.pallas_obs_decode=true coerces to the literal string "true")."""
    if isinstance(setting, bool):
        return setting
    lowered = str(setting).lower()
    if lowered == "auto":
        return jax.default_backend() == "tpu"
    if lowered in ("on", "true", "1", "yes"):
        return True
    if lowered in ("off", "false", "0", "no"):
        return False
    raise ValueError(
        f"pallas_obs_decode must be 'on', 'off', or 'auto'; got {setting!r}")


def stack_frames(obs: jnp.ndarray, seq_window: int, frame_stack: int,
                 use_pallas: bool = False) -> jnp.ndarray:
    """Dispatch: pallas on TPU when requested, jnp otherwise."""
    if use_pallas:
        return stack_frames_pallas(obs, seq_window, frame_stack)
    return stack_frames_reference(obs, seq_window, frame_stack)
