"""n-step return math for the actor-side block assembler.

The reference computes these inside ``LocalBuffer.finish``
(/root/reference/worker.py:443-480): an n-step discounted reward via
``np.convolve``, a per-step effective discount ``gamma^n`` whose tail encodes
episode termination (zeroed) or bootstrap shortening — so no ``done`` flag ever
needs to be stored — and initial sequence priorities computed from the actor's
own (slightly stale) Q-values so new experience enters the replay tree with a
meaningful priority before the learner ever sees it.

These run on actor CPUs over one <=400-step block, so they are plain numpy.
"""

from typing import Optional

import numpy as np


def n_step_return(rewards: np.ndarray, gamma: float, n: int) -> np.ndarray:
    """Discounted n-step reward sum per step.

    out[t] = sum_{i=0..n-1} gamma^i * rewards[t+i], with rewards treated as 0
    past the end of the block (matches zero-padding at
    /root/reference/worker.py:463-466).
    """
    rewards = np.asarray(rewards, dtype=np.float64)
    size = rewards.shape[0]
    padded = np.concatenate([rewards, np.zeros(n - 1, dtype=np.float64)])
    kernel = gamma ** np.arange(n - 1, -1, -1, dtype=np.float64)
    return np.convolve(padded, kernel, "valid").astype(np.float32)[:size]


def n_step_gamma(size: int, gamma: float, n: int, bootstrap: bool) -> np.ndarray:
    """Per-step effective discount applied to the bootstrap value.

    For steps with a full n-step window: gamma^n. The final ``min(size, n)``
    steps have a shortened window ending at the block boundary: gamma^m for the
    m steps remaining if the block continues (``bootstrap=True``), or 0 if the
    episode terminated — encoding 'done' in the discount
    (/root/reference/worker.py:445-456).
    """
    max_forward = min(size, n)
    out = np.full(size, gamma**n, dtype=np.float32)
    if bootstrap:
        tail = gamma ** np.arange(max_forward, 0, -1, dtype=np.float64)
    else:
        tail = np.zeros(max_forward, dtype=np.float64)
    out[size - max_forward :] = tail
    return out


def initial_priorities(
    q_values: np.ndarray,
    actions: np.ndarray,
    n_step_rewards: np.ndarray,
    n_step_gammas: np.ndarray,
    n: int,
) -> np.ndarray:
    """Per-step |TD error| from the actor's own Q-values, used to seed replay
    priorities when a block is added (/root/reference/worker.py:475-478).

    q_values has one extra row: the bootstrap Q (zeros when the episode
    terminated). The bootstrap value for step t is max_a Q[t + m] where
    m = min(size, n) for the window-shortened tail, i.e. max Q over rows
    [max_forward:size+1] edge-padded to length size.
    """
    size = actions.shape[0]
    max_forward = min(size, n)
    max_q = q_values[max_forward : size + 1].max(axis=1)
    max_q = np.pad(max_q, (0, max_forward - 1), "edge")
    chosen_q = q_values[np.arange(size), actions]
    return np.abs(n_step_rewards + n_step_gammas * max_q - chosen_q).astype(np.float32)
