"""Invertible value rescaling (Pohlen et al. 2018), used by R2D2 in place of
reward clipping for the n-step target: target = h(r + gamma^n * h^-1(Q')).

Semantics match the reference learner's static methods
(/root/reference/worker.py:383-390); implementation is jnp so it fuses into the
jitted train step.
"""

import jax.numpy as jnp


def value_rescale(value: jnp.ndarray, eps: float = 1e-2) -> jnp.ndarray:
    """h(x) = sign(x) * (sqrt(|x| + 1) - 1) + eps * x"""
    return jnp.sign(value) * (jnp.sqrt(jnp.abs(value) + 1.0) - 1.0) + eps * value


def inverse_value_rescale(value: jnp.ndarray, eps: float = 1e-2) -> jnp.ndarray:
    """h^-1(x) = sign(x) * ((((sqrt(1 + 4*eps*(|x| + 1 + eps)) - 1) / (2*eps))^2) - 1)"""
    temp = (jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(value) + 1.0 + eps)) - 1.0) / (2.0 * eps)
    return jnp.sign(value) * (jnp.square(temp) - 1.0)
