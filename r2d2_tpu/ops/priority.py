"""Per-sequence replay priority from per-step TD errors.

R2D2 mixes max and mean absolute TD error over each sequence's learning steps:
p = eta*max + (1-eta)*mean, eta=0.9 (/root/reference/worker.py:240-249, where
it is a numba kernel over a ragged flat layout).

TPU-native form: the jitted train step produces TD errors as a dense
(batch, learning_steps_max) array with a validity mask — masked max/mean are
two reductions that XLA fuses into the surrounding step, so priority
computation costs no extra device<->host sync (SURVEY.md §2.1). A ragged numpy
twin serves the actor-side initial-priority path.
"""

import jax.numpy as jnp
import numpy as np


def mixed_td_errors_masked(
    td_errors: jnp.ndarray, mask: jnp.ndarray, eta: float = 0.9
) -> jnp.ndarray:
    """td_errors: (B, L) abs TD errors; mask: (B, L) 1.0 where the step is a
    real learning step. Returns (B,) mixed priorities."""
    mask = mask.astype(td_errors.dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype=td_errors.dtype)
    masked_max = jnp.max(jnp.where(mask > 0, td_errors, neg_inf), axis=1)
    count = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    masked_mean = jnp.sum(td_errors * mask, axis=1) / count
    # Sequences with no valid steps (shouldn't happen) get priority 0.
    valid = jnp.sum(mask, axis=1) > 0
    return jnp.where(valid, eta * masked_max + (1.0 - eta) * masked_mean, 0.0)


def mixed_td_errors_ragged(
    td_errors: np.ndarray, learning_steps: np.ndarray, eta: float = 0.9
) -> np.ndarray:
    """Ragged layout: td_errors is the flat concatenation of each sequence's
    learning-step errors; learning_steps gives each sequence's length."""
    out = np.empty(learning_steps.shape, dtype=np.float32)
    start = 0
    for i, steps in enumerate(learning_steps):
        seg = td_errors[start : start + steps]
        out[i] = eta * seg.max() + (1.0 - eta) * seg.mean()
        start += steps
    return out
