"""Pure functional ops: the numerical core of the framework.

Everything here is side-effect free and either a jittable JAX function (device
path) or a plain numpy function (actor/host path). No processes, no devices
required — unit-testable against naive references (SURVEY.md §4).
"""

from r2d2_tpu.ops.value import value_rescale, inverse_value_rescale
from r2d2_tpu.ops.returns import n_step_return, n_step_gamma, initial_priorities
from r2d2_tpu.ops.priority import mixed_td_errors_masked, mixed_td_errors_ragged
from r2d2_tpu.ops.sum_tree import (
    tree_num_layers,
    tree_init,
    tree_update,
    tree_sample,
    tree_total,
)

__all__ = [
    "value_rescale",
    "inverse_value_rescale",
    "n_step_return",
    "n_step_gamma",
    "initial_priorities",
    "mixed_td_errors_masked",
    "mixed_td_errors_ragged",
    "tree_num_layers",
    "tree_init",
    "tree_update",
    "tree_sample",
    "tree_total",
]
