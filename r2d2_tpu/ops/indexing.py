"""Static-shape index math replacing the reference's ragged sequence slicing.

The reference handles variable burn-in / learning / forward step counts with
``pack_padded_sequence`` plus per-sequence Python slice loops
(/root/reference/model.py:103-119,150; /root/reference/worker.py:140-166).
XLA requires static shapes, so the TPU-native design runs every sequence over
the full fixed window of ``seq_len = burn_in_max + learning_max + forward_max``
steps and replaces the slicing with *gather indices* and *validity masks*:

* an LSTM output at time t depends only on inputs <= t, so unrolling past a
  sequence's true end changes nothing we gather from the valid prefix;
* the reference's edge-padding of target-Q positions near episode end
  (repeat the last valid output, /root/reference/model.py:111-118) is exactly
  a clamp of the gather index to the last valid position.

All functions are jnp and shape-polymorphic over the batch; they also accept
numpy inputs for host-side tests.
"""

import jax.numpy as jnp


def frame_stack_indices(seq_len: int, frame_stack: int) -> jnp.ndarray:
    """(seq_len, frame_stack) gather over an unstacked frame row.

    Replay stores raw unstacked frames; stacked observation t is frames
    [t, t+stack) (the learner-side obs_idx gather, /root/reference/worker.py:310,330).
    """
    t = jnp.arange(seq_len)[:, None]
    j = jnp.arange(frame_stack)[None, :]
    return t + j


def online_q_positions(burn_in_steps: jnp.ndarray, learning_max: int) -> jnp.ndarray:
    """Positions of the learning-step outputs in the unrolled window.

    Online Q for learning step j sits right after the burn-in prefix:
    position = burn_in + j (ref model.py:150). Returns (B, learning_max) int32.
    """
    j = jnp.arange(learning_max, dtype=jnp.int32)[None, :]
    return burn_in_steps.astype(jnp.int32)[:, None] + j


def target_q_positions(
    burn_in_steps: jnp.ndarray,
    learning_steps: jnp.ndarray,
    forward_steps: jnp.ndarray,
    learning_max: int,
    forward_max: int,
) -> jnp.ndarray:
    """Positions of the n-step-ahead outputs used for the bootstrap target.

    The reference takes outputs [burn_in + forward_max : burn_in + learning +
    forward] then repeats the last one ``min(forward_max - forward, learning)``
    times (ref model.py:110-118) — i.e. target position for learning step j is
    burn_in + forward_max + j, clamped to the last valid output
    burn_in + learning + forward - 1. Returns (B, learning_max) int32.
    """
    burn_in = burn_in_steps.astype(jnp.int32)[:, None]
    learning = learning_steps.astype(jnp.int32)[:, None]
    forward = forward_steps.astype(jnp.int32)[:, None]
    j = jnp.arange(learning_max, dtype=jnp.int32)[None, :]
    pos = burn_in + forward_max + j
    last_valid = burn_in + learning + forward - 1
    return jnp.minimum(pos, last_valid)


def learning_step_mask(learning_steps: jnp.ndarray, learning_max: int) -> jnp.ndarray:
    """(B, learning_max) float32 mask: 1.0 where step j < learning_steps[b].

    Replaces the ragged concatenation over variable per-sequence learning
    steps (ref worker.py:168,344-346)."""
    j = jnp.arange(learning_max, dtype=jnp.int32)[None, :]
    return (j < learning_steps.astype(jnp.int32)[:, None]).astype(jnp.float32)
