"""Fused Pallas LSTM time-scan — the whole recurrent chain in ONE kernel.

Why: the profiled wall of the learner step is the 55-step serial LSTM
chain (PERF.md "Known remaining headroom"). Under `lax.scan` each step is
a separate XLA while-loop iteration: the (B, H) x (H, 4H) recurrent
matmul plus its gate math pay a loop-boundary's worth of overhead —
fusion breaks, carry round-trips, and the slice-start DMAs staging the
hoisted input projection (the ~2.1 ms span family in the captured
round-3 profile) — every iteration, ~165 times per train step (online
fwd + bwd + target fwd). Measured: ~72 us per iteration against ~10 us
of arithmetic.

This kernel runs the scan as a single Pallas grid over T:

* `Wh` is DMA'd into VMEM once (constant index map → revisiting
  optimization) and stays resident for all T steps.
* `h`/`c` live in f32 VMEM scratch across grid iterations — the carry
  never round-trips HBM.
* The per-step input projection block streams in, and the outputs
  (h sequence + saved activations for the backward pass) stream out,
  through Pallas's pipelined DMA — overlapping with the matmul instead
  of serializing as while-loop boundary copies.
* ``block_t`` processes that many consecutive timesteps per grid
  iteration (T must divide evenly; T=55 → 1, 5, 11): the in-kernel loop
  amortizes per-iteration grid/DMA bookkeeping at the cost of bigger
  VMEM blocks. The right value is a chip measurement — bench.py sweeps
  it in the plstm cells. VMEM budget at the reference shape
  (B=128, H=512, bf16): the backward kernel is the tight side — six
  (bt, 128, 512..2048) streamed blocks plus the revisited f32 (512,
  2048) dWh block and Wh^T; bt=11 sits near ~24 MB of live blocks, so a
  Mosaic VMEM-exceeded failure for the _bt11 cell is a plausible sweep
  outcome (recorded per-cell by the bench, not a kernel bug).

Pre-flight lowering audit (round 5, against the four Mosaic rejection
classes catalogued in PERF.md): every BlockSpec minor dim is
tile-aligned (128/512/2048); gate writes are static contiguous
lane-slice stores at x128 offsets (no lane concat, no strided store);
the only transpose (h_prev.T, backward) runs on f32 — the supported
32-bit sublane/lane path; no sub-32-bit casts outside supported
element-wise converts. First real-Mosaic validation happens in
``cli/chip_checks`` before any bench spend.

The backward pass is a second kernel running the grid in REVERSE
(index maps `i -> nblocks-1-i`), carrying `dh`/`dc` in scratch and
accumulating `dWh` in a revisited f32 output block; both wrapped in
`jax.custom_vjp`. Saved residuals are the post-activation gates and the
c sequence (streamed out by the forward kernel) — no recomputation
matmul in the backward step, matching XLA autodiff's op count. The
non-differentiated path (target-network unrolls) takes a lean forward
variant with no residual traffic.

Numerics: the matmul feeds the MXU in the compute dtype with f32
accumulation; gate math and carries are f32 throughout, rounding once
into the storage dtype per step — at least as accurate as the
`lax.scan` path, which carries bf16 under the bf16 policy (tolerance-
and loss-parity-tested like the bf16 policy itself).

Replaces the serial-chain half of the reference's cuDNN `nn.LSTM`
(/root/reference/model.py:33); the input projection half is already
hoisted into one big MXU matmul by `models/network.py HoistedLSTM`.
Gated by `network.pallas_lstm` (tri-state, default "off" until the TPU
A/B lands — bench cells `bf16_spd16_plstm*`).
"""

import functools

import jax
import jax.numpy as jnp


def lstm_scan_reference(xpb: jnp.ndarray, wh: jnp.ndarray,
                        c0: jnp.ndarray, h0: jnp.ndarray):
    """jnp twin (lax.scan) — the test oracle and non-TPU fallback.

    ``xpb``: (T, B, 4H) input projection WITH bias already folded in;
    ``wh``: (H, 4H); ``c0``/``h0``: (B, H). Gate order i, f, g, o —
    identical to models/network.py lstm_cell_step.
    Returns (h_seq (T, B, H), (c_fin, h_fin)).
    """

    def step(carry, xp):
        c, h = carry
        gates = xp + h @ wh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (c, h), h

    (c, h), hs = jax.lax.scan(step, (c0, h0), xpb)
    return hs, (c, h)


def _cell_math(hidden: int, xp_f32, wh_ref, h_s, c_s):
    """One LSTM step on the f32 VMEM carries; returns the gate activations
    and new carries (all f32 registers) and updates the scratches. Shared
    by the residual-saving and lean forward kernels so they cannot
    diverge."""
    cd = wh_ref.dtype
    gates = xp_f32 + jax.lax.dot_general(
        h_s[:].astype(cd), wh_ref[:],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    i_g = jax.nn.sigmoid(gates[:, :hidden])
    f_g = jax.nn.sigmoid(gates[:, hidden:2 * hidden])
    g_g = jnp.tanh(gates[:, 2 * hidden:3 * hidden])
    o_g = jax.nn.sigmoid(gates[:, 3 * hidden:])
    c_new = f_g * c_s[:] + i_g * g_g
    h_new = o_g * jnp.tanh(c_new)
    c_s[:] = c_new
    h_s[:] = h_new
    return i_g, f_g, g_g, o_g, c_new, h_new


def _fwd_kernel(hidden: int, block_t: int, xpb_ref, wh_ref, c0_ref, h0_ref,
                hseq_ref, cseq_ref, acts_ref, h_s, c_s):
    from jax.experimental import pallas as pl

    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _():
        h_s[:] = h0_ref[:].astype(jnp.float32)
        c_s[:] = c0_ref[:].astype(jnp.float32)

    out_dtype = hseq_ref.dtype
    for j in range(block_t):
        i_g, f_g, g_g, o_g, c_new, h_new = _cell_math(
            hidden, xpb_ref[j].astype(jnp.float32), wh_ref, h_s, c_s)
        hseq_ref[j] = h_new.astype(out_dtype)
        cseq_ref[j] = c_new.astype(out_dtype)
        # four static lane-slice stores, not a lane concat — slice writes
        # at tile-multiple offsets are the Mosaic-safe lowering
        acts_ref[j, :, :hidden] = i_g.astype(out_dtype)
        acts_ref[j, :, hidden:2 * hidden] = f_g.astype(out_dtype)
        acts_ref[j, :, 2 * hidden:3 * hidden] = g_g.astype(out_dtype)
        acts_ref[j, :, 3 * hidden:] = o_g.astype(out_dtype)


def _fwd_kernel_lean(hidden: int, nblocks: int, block_t: int, xpb_ref,
                     wh_ref, c0_ref, h0_ref, hseq_ref, cfin_ref, h_s, c_s):
    # forward-only variant: no backward residuals — the target-network
    # unrolls (and any other non-differentiated call) must not pay the
    # (T, B, 5H) HBM write traffic of cseq + acts they will never read
    from jax.experimental import pallas as pl

    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _():
        h_s[:] = h0_ref[:].astype(jnp.float32)
        c_s[:] = c0_ref[:].astype(jnp.float32)

    c_new = None
    for j in range(block_t):
        _, _, _, _, c_new, h_new = _cell_math(
            hidden, xpb_ref[j].astype(jnp.float32), wh_ref, h_s, c_s)
        hseq_ref[j] = h_new.astype(hseq_ref.dtype)

    @pl.when(blk == nblocks - 1)
    def _():
        cfin_ref[:] = c_new.astype(cfin_ref.dtype)


def _fwd_call(xpb, wh, c0, h0, interpret, block_t, save_residuals=True):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nsteps, batch, gdim = xpb.shape
    hidden = gdim // 4
    dtype = xpb.dtype
    nblocks = nsteps // block_t
    bt = block_t
    if save_residuals:
        kernel = functools.partial(_fwd_kernel, hidden, bt)
        out_specs = [
            pl.BlockSpec((bt, batch, hidden), lambda t: (t, 0, 0)),
            pl.BlockSpec((bt, batch, hidden), lambda t: (t, 0, 0)),
            pl.BlockSpec((bt, batch, gdim), lambda t: (t, 0, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((nsteps, batch, hidden), dtype),
            jax.ShapeDtypeStruct((nsteps, batch, hidden), dtype),
            jax.ShapeDtypeStruct((nsteps, batch, gdim), dtype),
        ]
    else:
        kernel = functools.partial(_fwd_kernel_lean, hidden, nblocks, bt)
        out_specs = [
            pl.BlockSpec((bt, batch, hidden), lambda t: (t, 0, 0)),
            pl.BlockSpec((batch, hidden), lambda t: (0, 0)),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((nsteps, batch, hidden), dtype),
            jax.ShapeDtypeStruct((batch, hidden), dtype),
        ]
    return pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((bt, batch, gdim), lambda t: (t, 0, 0)),
            pl.BlockSpec((hidden, gdim), lambda t: (0, 0)),
            pl.BlockSpec((batch, hidden), lambda t: (0, 0)),
            pl.BlockSpec((batch, hidden), lambda t: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((batch, hidden), jnp.float32),
            pltpu.VMEM((batch, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(xpb, wh, c0, h0)


def _bwd_kernel(hidden: int, nblocks: int, block_t: int,
                dhseq_ref, acts_ref, cseq_ref, cprevb_ref, hprevb_ref,
                wht_ref, c0_ref, h0_ref, dcfin_ref, dhfin_ref,
                dxpb_ref, dwh_ref, dc0_ref, dh0_ref, dh_s, dc_s):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    blk = nblocks - 1 - i                    # blocks processed descending

    @pl.when(i == 0)
    def _():
        dh_s[:] = dhfin_ref[:].astype(jnp.float32)
        dc_s[:] = dcfin_ref[:].astype(jnp.float32)
        dwh_ref[:] = jnp.zeros_like(dwh_ref)

    out_dtype = dxpb_ref.dtype
    cd = wht_ref.dtype
    for j in reversed(range(block_t)):
        acts = acts_ref[j].astype(jnp.float32)
        i_g = acts[:, :hidden]
        f_g = acts[:, hidden:2 * hidden]
        g_g = acts[:, 2 * hidden:3 * hidden]
        o_g = acts[:, 3 * hidden:]
        if j > 0:
            # in-block predecessor: c from the saved sequence; h
            # recomputed as o*tanh(c) (cheaper than streaming hseq twice)
            c_prev = cseq_ref[j - 1].astype(jnp.float32)
            h_prev = (acts_ref[j - 1, :, 3 * hidden:].astype(jnp.float32)
                      * jnp.tanh(c_prev))
        else:
            # block boundary: previous block's LAST element; at t == 0 the
            # prev-block stream is a clamped re-read — select the initial
            # carries instead (both operands resident in VMEM)
            first = blk == 0
            c_prev = jnp.where(first, c0_ref[:].astype(jnp.float32),
                               cprevb_ref[block_t - 1].astype(jnp.float32))
            h_prev = jnp.where(first, h0_ref[:].astype(jnp.float32),
                               hprevb_ref[block_t - 1].astype(jnp.float32))

        dh_total = dhseq_ref[j].astype(jnp.float32) + dh_s[:]
        tanh_c = jnp.tanh(cseq_ref[j].astype(jnp.float32))
        do = dh_total * tanh_c
        dc = dc_s[:] + dh_total * o_g * (1.0 - tanh_c * tanh_c)
        di = dc * g_g
        dg = dc * i_g
        df = dc * c_prev
        # pre-activation gate grads (sigmoid' = s(1-s); tanh' = 1-t^2),
        # written as four static lane-slice stores into the dxpb output
        # block (no lane concat — see the forward kernel), then read back
        # whole for the two dots. The readback rounds through the storage
        # dtype — the same rounding the dots' MXU-dtype cast applies
        # anyway.
        dxpb_ref[j, :, :hidden] = (di * i_g * (1.0 - i_g)).astype(out_dtype)
        dxpb_ref[j, :, hidden:2 * hidden] = (
            df * f_g * (1.0 - f_g)).astype(out_dtype)
        dxpb_ref[j, :, 2 * hidden:3 * hidden] = (
            dg * (1.0 - g_g * g_g)).astype(out_dtype)
        dxpb_ref[j, :, 3 * hidden:] = (
            do * o_g * (1.0 - o_g)).astype(out_dtype)

        dg_cd = dxpb_ref[j].astype(cd)
        dh_s[:] = jax.lax.dot_general(
            dg_cd, wht_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # transpose in f32 (32-bit sublane/lane transpose is the supported
        # Mosaic path on v5e), cast to the MXU dtype after
        dwh_ref[:] += jax.lax.dot_general(
            h_prev.T.astype(cd), dg_cd, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dc_s[:] = dc * f_g

    @pl.when(i == nblocks - 1)
    def _():
        # after the t == 0 update, the scratches hold d h_{-1} / d c_{-1}
        dh0_ref[:] = dh_s[:]
        dc0_ref[:] = dc_s[:]


def _bwd_call(wh, c0, h0, hseq, cseq, acts, dhseq, dcfin, dhfin, interpret,
              block_t):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nsteps, batch, gdim = acts.shape
    hidden = gdim // 4
    wht = wh.T                                            # (4H, H)
    nblocks = nsteps // block_t
    bt = block_t

    def rev(t_idx):
        return lambda i: (t_idx(i), 0, 0)

    last = nblocks - 1
    prev = lambda i: jnp.maximum(last - 1 - i, 0)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, hidden, nblocks, bt),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((bt, batch, hidden), rev(lambda i: last - i)),  # dhseq
            pl.BlockSpec((bt, batch, gdim), rev(lambda i: last - i)),    # acts
            pl.BlockSpec((bt, batch, hidden), rev(lambda i: last - i)),  # c_t
            pl.BlockSpec((bt, batch, hidden), rev(prev)),            # c prevblk
            pl.BlockSpec((bt, batch, hidden), rev(prev)),            # h prevblk
            pl.BlockSpec((gdim, hidden), lambda i: (0, 0)),              # Wh^T
            pl.BlockSpec((batch, hidden), lambda i: (0, 0)),             # c0
            pl.BlockSpec((batch, hidden), lambda i: (0, 0)),             # h0
            pl.BlockSpec((batch, hidden), lambda i: (0, 0)),             # dc_fin
            pl.BlockSpec((batch, hidden), lambda i: (0, 0)),             # dh_fin
        ],
        out_specs=[
            pl.BlockSpec((bt, batch, gdim), rev(lambda i: last - i)),    # dxpb
            pl.BlockSpec((hidden, gdim), lambda i: (0, 0)),              # dWh
            pl.BlockSpec((batch, hidden), lambda i: (0, 0)),             # dc0
            pl.BlockSpec((batch, hidden), lambda i: (0, 0)),             # dh0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nsteps, batch, gdim), dhseq.dtype),
            jax.ShapeDtypeStruct((hidden, gdim), jnp.float32),
            jax.ShapeDtypeStruct((batch, hidden), jnp.float32),
            jax.ShapeDtypeStruct((batch, hidden), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((batch, hidden), jnp.float32),
            pltpu.VMEM((batch, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(dhseq, acts, cseq, cseq, hseq, wht, c0, h0, dcfin, dhfin)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _lstm_scan(interpret, block_t, xpb, wh, c0, h0):
    # the NON-differentiated path (target-network unrolls): lean kernel,
    # no residual traffic. Under jax.grad, _lstm_scan_fwd runs instead.
    hseq, cfin = _fwd_call(xpb, wh, c0, h0, interpret, block_t,
                           save_residuals=False)
    return hseq, (cfin, hseq[-1])


def _lstm_scan_fwd(interpret, block_t, xpb, wh, c0, h0):
    hseq, cseq, acts = _fwd_call(xpb, wh, c0, h0, interpret, block_t)
    out = (hseq, (cseq[-1], hseq[-1]))
    return out, (wh, c0, h0, hseq, cseq, acts)


def _lstm_scan_bwd(interpret, block_t, res, cts):
    wh, c0, h0, hseq, cseq, acts = res
    dhseq, (dcfin, dhfin) = cts
    dxpb, dwh, dc0, dh0 = _bwd_call(
        wh, c0, h0, hseq, cseq, acts, dhseq, dcfin, dhfin, interpret,
        block_t)
    return (dxpb, dwh.astype(wh.dtype), dc0.astype(c0.dtype),
            dh0.astype(h0.dtype))


_lstm_scan.defvjp(_lstm_scan_fwd, _lstm_scan_bwd)


def lstm_scan_pallas(xpb: jnp.ndarray, wh: jnp.ndarray, c0: jnp.ndarray,
                     h0: jnp.ndarray, interpret: bool = False,
                     block_t: int = 1):
    """Fused-kernel LSTM scan (differentiable). Same signature/returns as
    ``lstm_scan_reference``; ``interpret=True`` runs both kernels on any
    backend (the CPU test mesh). ``block_t``: timesteps per grid
    iteration (must divide T; NetworkConfig.pallas_lstm_block)."""
    if xpb.shape[0] % block_t:
        raise ValueError(
            f"block_t={block_t} does not divide the {xpb.shape[0]}-step "
            "sequence — pick a divisor (network.pallas_lstm_block)")
    return _lstm_scan(interpret, block_t, xpb, wh, c0, h0)
