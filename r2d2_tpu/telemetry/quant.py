"""Quantized-inference accuracy aggregation (ISSUE 14).

The quantized acting forward (models/network.py
``quantized_inference_apply``, reached through the ONE shared
``actor.policy.make_forward_fn``) carries an in-graph accuracy probe: on
every ``telemetry.quant_probe_interval``-th tick a ``lax.cond`` branch
also runs the f32 twin on the SAME live batch and emits
max |Q_f32 − Q_quant| plus the greedy-action agreement fraction. This
class is where those probe results (from thread actors, the policy
server's dispatch loop, and the anakin segment probe alike) accumulate
into the periodic record's ``quant`` block — the input of the
``quant_divergence`` alert rule (telemetry/alerts.py).

Thread-safe like ServingStats; ``interval_block`` consumes the interval.
The block is emitted on EVERY record while the knob is on (the active
dtype is run state worth seeing even in a probe-free interval);
``agree_frac``/``dq_max`` are None when no probe fired, which keeps the
alert rule held rather than falsely re-armed. With
``network.inference_dtype = "f32"`` no provider is attached and the
record schema is byte-identical to PR 13 (stability-tested).
"""

import threading
from typing import Optional


class QuantStats:
    """Per-interval accumulator: probes are lane-weighted (a 16-lane
    batched probe counts 16 lanes' agreement against a scalar actor's
    1), ``dq_max`` is the interval max, ``agree_min`` the worst single
    probe. ``publish_stamp`` is the newest adopted publish-time-twin
    stamp (make_inference_bundle) — proof the twin the policy is acting
    with was quantized at that publication, not drifting behind it."""

    def __init__(self, dtype: str, probe_interval: int = 0):
        self.dtype = str(dtype)
        self.probe_interval = int(probe_interval)
        self._lock = threading.Lock()
        self._probes = 0
        self._lanes = 0
        self._agree_sum = 0.0
        self._agree_min: Optional[float] = None
        self._dq_max: Optional[float] = None
        self.publish_stamp = 0

    def on_probe(self, dq_max: float, agree_frac: float,
                 lanes: int = 1) -> None:
        with self._lock:
            self._probes += 1
            self._lanes += int(lanes)
            self._agree_sum += float(agree_frac) * int(lanes)
            self._agree_min = (float(agree_frac) if self._agree_min is None
                               else min(self._agree_min, float(agree_frac)))
            self._dq_max = (float(dq_max) if self._dq_max is None
                            else max(self._dq_max, float(dq_max)))

    def on_stamp(self, stamp: int) -> None:
        with self._lock:
            self.publish_stamp = max(self.publish_stamp, int(stamp))

    def interval_block(self) -> dict:
        """The record's ``quant`` block; consumes the interval."""
        with self._lock:
            block = {
                "dtype": self.dtype,
                "probe_interval": self.probe_interval,
                "probes": self._probes,
                "lanes_probed": self._lanes,
                "dq_max": (round(self._dq_max, 6)
                           if self._dq_max is not None else None),
                "agree_frac": (round(self._agree_sum / self._lanes, 6)
                               if self._lanes else None),
                "agree_min": (round(self._agree_min, 6)
                              if self._agree_min is not None else None),
                "publish_stamp": self.publish_stamp,
            }
            self._probes = 0
            self._lanes = 0
            self._agree_sum = 0.0
            self._agree_min = None
            self._dq_max = None
        return block
