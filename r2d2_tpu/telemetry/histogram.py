"""Streaming percentile timers: fixed-bucket log-scale histograms.

The PR-2 ingestion counters report interval MEANS (``on_ingest_drain``
sums a latency and divides at log time) — which is exactly the statistic
that hides the tail a pipeline stall lives in (Podracer, arXiv
2104.06272, reports per-stage tails for the same reason). A histogram
with geometrically-spaced buckets gives P50/P95/P99 at a fixed, tiny
cost: one integer increment per observation on the hot path, 64 int64
buckets per stage, and MERGEABILITY — counts from every actor process
add elementwise, so one fleet-wide percentile falls out of summing rows
of the shared-memory board (board.py). Resolution is the bucket growth
factor (~33% here: 8 buckets per decade over 1 µs .. 100 s), plenty for
"P99 queue wait jumped 10x", useless for microbenchmarks — bench.py
keeps exact timing.
"""

import math
from typing import Dict, List, Optional

import numpy as np

# Bucket layout — shared by every histogram in the system (local timers,
# the shm board, and the aggregated record all speak this layout, so
# merging is elementwise addition everywhere). Changing it invalidates
# in-flight boards; bump with care.
NBUCKETS = 64
_LO = 1e-6                  # left edge of bucket 0: 1 µs
_DECADES = 8.0              # span: 1 µs .. 100 s
_STEP = _DECADES / NBUCKETS  # log10 width of one bucket (0.125 -> ~33%/bucket)
_INV_STEP = 1.0 / _STEP
_LOG_LO = math.log10(_LO)

# Public aliases for the bucket layout — the device-side bucketize-scatter
# below reproduces bucket_index() inside jit and MUST use the exact same
# constants (parity-tested device vs host).
BUCKET_LO = _LO
BUCKET_LOG_LO = _LOG_LO
BUCKET_INV_STEP = _INV_STEP


# ---------------------------------------------------------------------------
# Device-side twin (jnp; traced into fused steps). ONE implementation of
# the bucketize-scatter shared by the learning diagnostics
# (telemetry/learning.py) and the replay diagnostics
# (telemetry/replaydiag.py) — a third per-pillar copy of the layout math
# would be a parity bug waiting to happen (ISSUE 10 satellite).


def bucketize_values(x):
    """jit twin of bucket_index over |x|: (same-shape) int32 bucket
    indices into the shared 64-bucket log layout. Non-finite values clamp
    into the TOP bucket (the pillars also count them separately) so the
    scatter index stays in range."""
    import jax.numpy as jnp
    ax = jnp.abs(x).astype(jnp.float32)
    i = jnp.floor((jnp.log10(jnp.maximum(ax, BUCKET_LO)) - BUCKET_LOG_LO)
                  * BUCKET_INV_STEP).astype(jnp.int32)
    i = jnp.where(jnp.isfinite(ax), i, NBUCKETS - 1)
    return jnp.clip(i, 0, NBUCKETS - 1)


def value_counts(x, mask=None):
    """(NBUCKETS,) int32 histogram of |x| via bucketize + scatter-add —
    the device-side histogram primitive. ``mask`` (same shape, 0/1)
    excludes padded entries."""
    import jax.numpy as jnp
    idx = bucketize_values(x).reshape(-1)
    ones = (jnp.ones_like(idx) if mask is None
            else mask.reshape(-1).astype(jnp.int32))
    return jnp.zeros((NBUCKETS,), jnp.int32).at[idx].add(ones)


def bucket_index(seconds: float) -> int:
    """Bucket for one duration; durations outside [1 µs, 100 s) clamp to
    the end buckets (they still count, with saturated resolution)."""
    if seconds <= _LO:
        return 0
    i = int((math.log10(seconds) - _LOG_LO) * _INV_STEP)
    return NBUCKETS - 1 if i >= NBUCKETS else i


def value_counts_np(x: np.ndarray, mask=None) -> np.ndarray:
    """Vectorized numpy twin of :func:`value_counts` (same layout, same
    clamping): one log10 + bincount instead of a per-element Python loop
    — what host-side consumers over many values use (HostReplay's leaf
    histogram runs under the replay lock, where a 10^4-iteration Python
    loop would stall sample()/add() every flush)."""
    ax = np.abs(np.asarray(x, np.float64)).reshape(-1)
    # invalid too: floor(NaN).astype(int) warns before the isfinite
    # fallback below replaces the index
    with np.errstate(divide="ignore", invalid="ignore"):
        i = np.floor((np.log10(np.maximum(ax, _LO)) - _LOG_LO)
                     * _INV_STEP).astype(np.int64)
    i = np.where(np.isfinite(ax), i, NBUCKETS - 1)
    i = np.clip(i, 0, NBUCKETS - 1)
    if mask is not None:
        i = i[np.asarray(mask, bool).reshape(-1)]
    return np.bincount(i, minlength=NBUCKETS).astype(np.int64)


def bucket_bounds(i: int) -> tuple:
    """(lo, hi) seconds covered by bucket ``i``."""
    return (10.0 ** (_LOG_LO + i * _STEP), 10.0 ** (_LOG_LO + (i + 1) * _STEP))


def bucket_mid(i: int) -> float:
    """Geometric midpoint of bucket ``i`` — the value a percentile
    reports for observations landing there."""
    return 10.0 ** (_LOG_LO + (i + 0.5) * _STEP)


def percentile(counts: np.ndarray, q: float) -> Optional[float]:
    """The q-quantile (0 < q <= 1) of a counts vector, as the geometric
    midpoint of the bucket where the cumulative count crosses q * total.
    None for an empty histogram."""
    total = int(counts.sum())
    if total == 0:
        return None
    target = q * total
    cum = 0
    for i in range(len(counts)):
        cum += int(counts[i])
        if cum >= target:
            return bucket_mid(i)
    return bucket_mid(len(counts) - 1)


def summarize(counts: np.ndarray) -> Optional[Dict[str, float]]:
    """The aggregated-record entry for one stage: count + P50/P95/P99 in
    milliseconds (rounded to the layout's real resolution). None when the
    interval saw no observations — the stage key is then omitted from the
    record rather than emitting nulls."""
    total = int(counts.sum())
    if total == 0:
        return None
    out = {"count": total}
    for name, q in (("p50_ms", 0.50), ("p95_ms", 0.95), ("p99_ms", 0.99)):
        out[name] = round(percentile(counts, q) * 1e3, 4)
    return out


def value_summary(counts: np.ndarray) -> Optional[Dict[str, float]]:
    """summarize() twin for VALUE-domain histograms (|TD error|, priority,
    |Q| — the learning-diagnostics histograms reuse the duration layout's
    bucket edges, reading 1e-6..100 as raw magnitudes instead of seconds):
    count + P50/P95/P99 in raw units, no ms scaling. None when empty."""
    total = int(np.asarray(counts).sum())
    if total == 0:
        return None
    out = {"count": total}
    for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
        # 6 significant digits (values span 1e-6..100 — fixed-decimal
        # rounding would flatten the small-magnitude buckets)
        out[name] = float(f"{percentile(np.asarray(counts), q):.6g}")
    return out


class LogHistogram:
    """One stage's histogram — a thin wrapper over the shared bucket
    layout for unit tests and ad-hoc use; the runtime's StageTimers keeps
    a (stages, buckets) matrix directly (core.py)."""

    def __init__(self, counts: Optional[np.ndarray] = None):
        self.counts = (np.zeros(NBUCKETS, np.int64) if counts is None
                       else np.asarray(counts, np.int64).copy())
        if self.counts.shape != (NBUCKETS,):
            raise ValueError(
                f"histogram counts must have shape ({NBUCKETS},), got "
                f"{self.counts.shape}")

    def add(self, seconds: float) -> None:
        self.counts[bucket_index(seconds)] += 1

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Elementwise sum — the cross-process aggregation primitive."""
        return LogHistogram(self.counts + other.counts)

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    def percentile(self, q: float) -> Optional[float]:
        return percentile(self.counts, q)

    def summarize(self) -> Optional[Dict[str, float]]:
        return summarize(self.counts)

    def to_list(self) -> List[int]:
        return [int(c) for c in self.counts]
