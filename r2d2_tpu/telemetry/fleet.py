"""Fleet observability plane (ISSUE 12): cross-host aggregation,
lockstep/collective timing, and straggler attribution.

The multihost trainer (parallel/multihost.py) is lockstep by
construction: every controller dispatches the same psum program every
iteration, so the WHOLE POD runs at the slowest rank's pace — yet until
this module nothing measured which rank that was or how much step time
the DCN barrier ate. Three instruments close the gap, all behind
``telemetry.fleet_enabled``:

  * **In-band skew gauges** — the per-iteration lockstep psum row is
    widened (``make_lockstep_ingest`` / ``make_lockstep_consensus``,
    ``fleet=True``) with each rank's previous-iteration step time:
    sum/max/min reductions, a one-hot argmax so every rank learns the
    straggler's identity in-graph, and the all-gathered per-row
    step-time and env-step tables — replicated outputs on the SAME
    dispatch, zero extra DCN collectives.
  * **:class:`FleetAggregator`** — every rank accumulates its local
    lockstep timing (compute vs blocked-in-collective) and the gauge
    tables into a per-interval ``fleet`` block; rank 0 additionally
    merges the other ranks' host rows (stage histograms — mergeable by
    elementwise add by design, PR 4 — resource blocks, row ages) read
    from the shared filesystem, and the block rides the periodic record
    where the ``rank_straggler`` / ``lockstep_wait_frac`` /
    ``fleet_desync`` / ``missing_rank`` alert rules watch it.
  * **Clock anchors** — each rank's host row carries a
    monotonic/wall-clock anchor pair stamped when lockstep iteration 1's
    collective completed (a genuinely pod-synchronized instant), so
    ``tools/inspect.py --export-trace`` can align every rank's span
    files onto rank 0's clock and merge them into one Perfetto timeline
    with per-rank tracks.

:class:`RotatingJsonlWriter` gives the per-host streams size-capped
rotation (``telemetry.fleet_host_row_max_bytes``) consistent with
``logparse.parse_jsonl``'s partial-line tolerance — a pod run's
``telemetry_host{r}.jsonl`` no longer grows unboundedly.

Designed so ISSUE 1's multihost sharded-Anakin loop adopts the same
block unchanged: the gauges are per-dp-row (``row_ranks`` maps rows to
controllers), not tied to the host-actor ingestion path.
"""

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# Keys the fleet-widened lockstep programs add to the replicated info
# dict — the training loop strips these (they are tables/gauges for the
# aggregator, not control-flow scalars).
FLEET_INFO_KEYS = ("step_times", "step_time_sum", "step_time_max",
                   "step_time_min", "straggler_shard", "env_steps_shards")


def host_row_path(save_dir: str, rank: int) -> str:
    return os.path.join(save_dir or ".", f"telemetry_host{rank}.jsonl")


def host_alerts_path(save_dir: str, rank: int) -> str:
    return os.path.join(save_dir or ".", f"alerts_host{rank}.jsonl")


class RotatingJsonlWriter:
    """Size-capped JSONL appender for the per-host telemetry streams.

    When the live file exceeds ``max_bytes`` it is renamed to
    ``{path}.1`` (replacing the previous rotated generation) and writing
    continues on a fresh file — so a long pod run holds at most
    ~2 x max_bytes per rank. Readers keep working mid-rotation:
    ``parse_jsonl`` tolerates partial trailing lines, and a reader that
    opened the old inode simply finishes it. ``max_bytes=0`` disables
    rotation (unbounded, the pre-PR12 behavior)."""

    def __init__(self, path: str, max_bytes: int = 0, resume: bool = False):
        self.path = path
        self.max_bytes = int(max_bytes)
        self.rotations = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if resume:
            try:
                self._size = os.path.getsize(path)
            except OSError:
                self._size = 0
        else:
            # fresh run truncates the live file AND drops the previous
            # run's rotated generation (the TrainMetrics truncate-on-fresh
            # contract; a stale .1 would splice another run's history
            # into this run's reads)
            open(path, "w").close()
            try:
                os.remove(path + ".1")
            except OSError:
                pass
            self._size = 0

    def write(self, row: dict) -> None:
        line = json.dumps(row) + "\n"
        if (self.max_bytes and self._size
                and self._size + len(line) > self.max_bytes):
            # rotate BEFORE the write that would exceed the cap, so the
            # live file always holds the newest row — a reader (rank 0's
            # flush, the trace merge) must never find the stream empty
            # for a whole interval just because it rotated
            try:
                os.replace(self.path, self.path + ".1")
                self.rotations += 1
                self._size = 0
            except OSError:
                pass
        with open(self.path, "a") as f:
            f.write(line)
        self._size += len(line)


def read_last_jsonl_row(path: str,
                        max_scan_bytes: int = 65536) -> Optional[dict]:
    """The newest complete record of a JSONL stream without reading the
    whole file — rank 0 polls every other rank's host row once per log
    interval, so this must stay O(tail), not O(file). Partial trailing
    lines (a writer mid-append) are skipped, like ``parse_jsonl``. Falls
    back to the ``.1`` rotated generation when the live file is missing
    or empty (the instant between a rotation's rename and its write)."""
    for p in (path, path + ".1"):
        row = _read_tail_row(p, max_scan_bytes)
        if row is not None:
            return row
    return None


def _read_tail_row(path: str, max_scan_bytes: int) -> Optional[dict]:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_scan_bytes))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return None


# ---------------------------------------------------------------------------
# Stage-histogram merge: the PR-4 histograms are mergeable by elementwise
# add by design; host rows carry each rank's CUMULATIVE counts (keyed by
# stage name, robust to stage-list growth) and rank 0 sums them into one
# fleet-wide view.


def stage_counts_dict(matrix: np.ndarray) -> Dict[str, List[int]]:
    """Serialize a (stages, buckets) counts matrix to {stage: [counts]},
    keeping only stages with data (host rows stay lean)."""
    from r2d2_tpu.telemetry.core import STAGES
    out = {}
    for i, name in enumerate(STAGES):
        if i < matrix.shape[0] and int(matrix[i].sum()):
            out[name] = [int(c) for c in matrix[i]]
    return out


def merge_stage_counts(dicts: Sequence[Dict[str, Sequence[int]]]
                       ) -> Dict[str, np.ndarray]:
    """Elementwise-add merge of per-rank stage-count dicts."""
    merged: Dict[str, np.ndarray] = {}
    for d in dicts:
        for name, counts in (d or {}).items():
            arr = np.asarray(counts, np.int64)
            if name in merged:
                merged[name] = merged[name] + arr
            else:
                merged[name] = arr.copy()
    return merged


def summarize_stage_counts(counts: Dict[str, Sequence[int]]
                           ) -> Dict[str, Dict[str, float]]:
    """{stage: {count, p50_ms, p95_ms, p99_ms}} from a (merged) counts
    dict — the same summary shape as the record's ``stages`` block."""
    from r2d2_tpu.telemetry.histogram import summarize
    out = {}
    for name in sorted(counts):
        s = summarize(np.asarray(counts[name], np.int64))
        if s is not None:
            out[name] = s
    return out


def cumulative_stage_matrix(tele) -> np.ndarray:
    """This process's cumulative (stages, buckets) counts: the local
    timers plus, when an actor TelemetryBoard is attached, the fleet
    slots' cumulative rows — both non-consuming reads, so this never
    races the interval_summary() consumption the record path owns."""
    m = tele.timers.cumulative()
    board = getattr(tele, "_agg_board", None)
    if board is not None:
        try:
            m = m + board.read().sum(axis=0)
        except (ValueError, OSError):
            pass    # board torn down mid-shutdown: local counts only
    return m


# ---------------------------------------------------------------------------
# Mesh topology helpers: the gauge tables are per dp-ROW; these map rows
# to the controller (process/rank) that owns them.


def mesh_row_ranks(mesh) -> List[int]:
    """Owning process index per dp row (a multi-device host owns several
    consecutive rows; all its rows carry the same host timing)."""
    rows = mesh.devices.reshape(mesh.shape["dp"], -1)
    return [int(rows[r].flat[0].process_index) for r in range(rows.shape[0])]


def rank_first_rows(row_ranks: Sequence[int], nprocs: int) -> List[int]:
    """First dp row owned by each rank, rank order — the row whose gauge
    entry represents that rank (hosts fill all their rows identically on
    the device path and only the first on the host-replay path)."""
    first: Dict[int, int] = {}
    for row, rank in enumerate(row_ranks):
        first.setdefault(int(rank), row)
    missing = [r for r in range(nprocs) if r not in first]
    if missing:
        raise ValueError(
            f"ranks {missing} own no dp rows (row_ranks={list(row_ranks)})")
    return [first[r] for r in range(nprocs)]


class FleetAggregator:
    """Per-rank lockstep-timing accumulator + (on rank 0) the cross-host
    merge behind the periodic record's ``fleet`` block.

    The training loop feeds it twice per iteration:

      * :meth:`on_collective` with the lockstep program's fetched info
        dict (the widened gauge tables) and the seconds this rank spent
        blocked in the dispatch+readback — the collective is the pod's
        synchronization point, so blocked time IS the price of skew;
      * :meth:`on_step` at iteration end (measures the whole iteration
        against its own internal clock; the result feeds the NEXT
        iteration's psum row via :attr:`last_step_s` — a one-iteration
        lag, irrelevant at alerting cadence).

    :meth:`flush` (once per log interval) returns the ``fleet`` block
    and resets the interval accumulators. On rank 0 it additionally
    reads every other rank's newest host row (shared filesystem) for
    row ages (the ``missing_rank`` signal) and the fleet-wide stage
    merge."""

    def __init__(self, rank: int, nprocs: int, row_ranks: Sequence[int],
                 save_dir: Optional[str] = None,
                 missing_age_s: float = 120.0):
        self.rank = int(rank)
        self.nprocs = int(nprocs)
        self.row_ranks = [int(r) for r in row_ranks]
        self.first_rows = rank_first_rows(self.row_ranks, self.nprocs)
        self.save_dir = save_dir
        self.missing_age_s = missing_age_s
        self.clock_anchor: Optional[dict] = None
        self.last_step_s = 0.0
        self._iter_t0: Optional[float] = None
        self._prev_env: Optional[np.ndarray] = None   # per-rank cumulative
        self._collectives_total = 0
        self._reset_interval()

    def _reset_interval(self) -> None:
        self._wait_s = 0.0
        self._step_sum_s = 0.0
        self._iters = 0
        self._collectives = 0
        self._time_rows: Optional[np.ndarray] = None   # per-row sums (s)
        self._env_rows: Optional[np.ndarray] = None    # last cumulative
        self._last_straggler_shard: Optional[int] = None
        self._in_band: Dict[str, float] = {}   # last psum/pmax/pmin gauges

    # -- per-iteration feed points --

    def on_collective(self, info: Dict[str, Any], wait_s: float) -> None:
        self._wait_s += float(wait_s)
        self._collectives += 1
        self._collectives_total += 1
        if self.clock_anchor is None:
            # iteration 1's collective completion: every rank exits the
            # psum at (nearly) the same true instant — the cross-host
            # alignment event the trace merge shifts clocks by
            self.clock_anchor = {"it": self._collectives_total,
                                 "wall": time.time(),
                                 "mono": time.monotonic()}
        st = info.get("step_times")
        if st is not None:
            st = np.asarray(st, np.float64).reshape(-1)
            self._time_rows = (st.copy() if self._time_rows is None
                               else self._time_rows + st)
        for key in ("step_time_sum", "step_time_max", "step_time_min"):
            if info.get(key) is not None:
                self._in_band[key] = float(info[key])
        env = info.get("env_steps_shards")
        if env is not None:
            self._env_rows = np.asarray(env, np.int64).reshape(-1)
        ss = info.get("straggler_shard")
        if ss is not None:
            self._last_straggler_shard = int(ss)

    def on_step(self, step_s: Optional[float] = None) -> float:
        """Close this iteration: returns its duration (seconds) and arms
        :attr:`last_step_s` for the next iteration's psum row.
        ``step_s`` overrides the internal clock (deterministic tests and
        fixture replay)."""
        now = time.perf_counter()
        if step_s is None:
            if self._iter_t0 is None:
                self._iter_t0 = now
                return 0.0
            step_s = now - self._iter_t0
        self._iter_t0 = now
        self.last_step_s = step_s
        self._step_sum_s += step_s
        self._iters += 1
        return step_s

    # -- per-rank collapse --

    def _per_rank(self, rows: Optional[np.ndarray]) -> Optional[np.ndarray]:
        if rows is None:
            return None
        rows = np.asarray(rows)
        if rows.shape[0] < len(self.row_ranks):
            return None
        return rows[self.first_rows]

    def _per_rank_env(self) -> Optional[np.ndarray]:
        """Cumulative env steps per RANK: rows are per-shard counters, a
        multi-row host's total is the sum over its rows (the host-replay
        path only fills the first owned row, summing stays correct)."""
        if self._env_rows is None:
            return None
        out = np.zeros((self.nprocs,), np.int64)
        for row, rank in enumerate(self.row_ranks):
            if row < len(self._env_rows):
                out[rank] += int(self._env_rows[row])
        return out

    # -- the record block --

    def flush(self, now: Optional[float] = None,
              local_stage_counts: Optional[dict] = None) -> dict:
        now = time.time() if now is None else now
        block: Dict[str, Any] = {"ranks": self.nprocs,
                                 "rank": self.rank,
                                 "row_ranks": self.row_ranks,
                                 "iters": self._iters}
        tot = self._step_sum_s
        block["lockstep"] = {
            "dispatches": self._collectives,
            "wait_s": round(self._wait_s, 4),
            "wait_frac": (round(min(self._wait_s / tot, 1.0), 4)
                          if tot > 0 else None),
            "wait_ms_mean": (round(1e3 * self._wait_s / self._collectives, 3)
                             if self._collectives else None),
            "step_ms_mean": (round(1e3 * tot / self._iters, 3)
                             if self._iters else None),
        }
        per_rank_t = self._per_rank(self._time_rows)
        if per_rank_t is not None and self._collectives:
            mean_rows = per_rank_t / self._collectives
            per_ms = [round(1e3 * float(v), 3) for v in mean_rows]
            mean = float(np.mean(mean_rows))
            block["step_time"] = {
                "per_rank_ms": per_ms,
                "mean_ms": round(1e3 * mean, 3),
                "max_ms": round(max(per_ms), 3),
                "min_ms": round(min(per_ms), 3),
                # max/min mean step time (the shard_imbalance
                # convention): 1.0 = perfectly balanced; the
                # rank_straggler rule's metric. NOT max-over-mean — that
                # is bounded by the rank count, so a 2-host pod could
                # never reach a 2x threshold however slow one rank got.
                "skew": (round(max(per_ms) / min(per_ms), 3)
                         if min(per_ms) > 0 else None),
                "straggler_rank": int(np.argmax(mean_rows)),
                # the in-graph one-hot argmax from the LAST collective (a
                # dp-row id; row_ranks maps it to a rank) — every rank
                # saw this without any host-side merge
                "straggler_shard": self._last_straggler_shard,
            }
            if self._in_band:
                # the LAST collective's psum/pmax/pmin gauges — the
                # in-band values every rank read without host math (the
                # interval means above are the alerting metric; these
                # pin the instantaneous picture)
                block["step_time"]["in_band_ms"] = {
                    k.split("step_time_")[-1]: round(1e3 * v, 3)
                    for k, v in self._in_band.items()}
        env = self._per_rank_env()
        if env is not None:
            interval = (env - self._prev_env if self._prev_env is not None
                        else env.copy())
            self._prev_env = env
            lo, hi = int(interval.min()), int(interval.max())
            block["env_steps"] = {
                "per_rank": [int(v) for v in env],
                "interval": [int(v) for v in interval],
                # max/min per-rank ingested env-steps this interval; a
                # rank at zero reads against a floor of 1 (the
                # fleet_desync rule's metric); None before any ingestion
                "divergence": (round(hi / max(lo, 1), 3) if hi > 0
                               else None),
            }
        if self.rank == 0:
            self._merge_host_rows(block, now, local_stage_counts)
        self._reset_interval()
        return block

    def _merge_host_rows(self, block: dict, now: float,
                         local_stage_counts: Optional[dict]) -> None:
        ages: List[Optional[float]] = [0.0]      # rank 0 is, well, here
        absent: List[int] = []
        counts = [local_stage_counts] if local_stage_counts else []
        if self.save_dir is not None:
            for r in range(1, self.nprocs):
                row = read_last_jsonl_row(host_row_path(self.save_dir, r))
                if row is None:
                    # never wrote a row yet: bring-up grace, not staleness
                    # (a rank that dies before its first row is caught by
                    # jax.distributed's heartbeat, not this signal)
                    ages.append(None)
                    absent.append(r)
                    continue
                wall = row.get("wall")
                ages.append(round(now - wall, 3) if wall else None)
                if row.get("stage_counts"):
                    counts.append(row["stage_counts"])
        known = [a for a in ages if a is not None]
        block["host_rows"] = {
            "ages_s": ages,
            "absent_ranks": absent,
            # the missing_rank rule's metric: the stalest row age seen
            "max_age_s": round(max(known), 3) if known else None,
        }
        if counts:
            block["stages"] = summarize_stage_counts(
                merge_stage_counts(counts))
