"""Low-overhead span tracer: thread-local ring buffers, drained off-thread.

Every pipeline stage worth seeing on a timeline records a
``(name, t_start, t_end, tags)`` event. The hot path takes NO locks: each
thread appends to its own bounded ``deque`` (the GIL makes ``append``
atomic; ``maxlen`` gives ring semantics — the oldest events fall off when
a drain falls behind, counted in ``dropped``). The Telemetry drain thread
(core.py) swaps events out periodically and appends them to a JSONL file
that ``tools/inspect.py`` turns into Chrome-trace JSON viewable in
Perfetto alongside an xprof capture.

Span cadence is block-level (emits, drains, dispatches — a few to a few
hundred per second), NOT per-env-step: per-step timing goes to the
histograms (histogram.py), which cost one integer increment each.
"""

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional


class SpanTracer:
    def __init__(self, ring_size: int = 4096, enabled: bool = True):
        from collections import deque
        self._deque = deque
        self.ring_size = ring_size
        self.enabled = enabled
        self._local = threading.local()
        self._rings: List = []          # (thread_name, deque)
        self._register_lock = threading.Lock()   # registration only
        self.dropped = 0                # approximate (racy increment is fine)

    def _ring(self):
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = self._deque(maxlen=self.ring_size)
            self._local.ring = ring
            with self._register_lock:
                self._rings.append((threading.current_thread(), ring))
        return ring

    def record(self, name: str, t_start: float, t_end: float,
               tags: Optional[Dict] = None) -> None:
        """Record one completed span (wall-clock unix seconds)."""
        if not self.enabled:
            return
        ring = self._ring()
        if len(ring) >= self.ring_size:
            self.dropped += 1
        ring.append((name, t_start, t_end, tags))

    @contextmanager
    def span(self, name: str, **tags):
        """Time a block as one span; no-op (and no clock reads) when
        disabled."""
        if not self.enabled:
            yield
            return
        t0 = time.time()
        try:
            yield
        finally:
            self.record(name, t0, time.time(), tags or None)

    def drain(self) -> List[dict]:
        """Pop every buffered event from every thread's ring (off-thread:
        the drain loop owns this). Writers keep appending concurrently;
        ``popleft`` and ``append`` never touch the same end."""
        out = []
        with self._register_lock:
            rings = list(self._rings)
        dead = []
        for thread, ring in rings:
            for _ in range(len(ring)):
                try:
                    name, t0, t1, tags = ring.popleft()
                except IndexError:
                    break
                ev = {"name": name, "ts": t0, "dur": t1 - t0,
                      "tid": thread.name}
                if tags:
                    ev["tags"] = tags
                out.append(ev)
            if not thread.is_alive() and not ring:
                # respawned workers register fresh rings; drained rings of
                # dead threads must not accumulate over a crash-looping
                # soak
                dead.append((thread, ring))
        if dead:
            with self._register_lock:
                for entry in dead:
                    try:
                        self._rings.remove(entry)
                    except ValueError:
                        pass
        out.sort(key=lambda e: e["ts"])
        return out


def chrome_trace_events(events: List[dict], pid: str,
                        pid_index: int = 0) -> List[dict]:
    """Convert drained span events (JSONL schema above) to Chrome-trace
    'X' events plus the process/thread name metadata Perfetto uses for
    track labels. Timestamps convert to microseconds."""
    tids: Dict[str, int] = {}
    out = [{"ph": "M", "name": "process_name", "pid": pid_index,
            "args": {"name": pid}}]
    for ev in events:
        tid = tids.setdefault(ev.get("tid", "main"), len(tids))
        out.append({"ph": "X", "name": ev["name"], "pid": pid_index,
                    "tid": tid, "ts": round(ev["ts"] * 1e6, 1),
                    "dur": round(ev["dur"] * 1e6, 1),
                    "args": ev.get("tags") or {}})
    for name, tid in tids.items():
        out.append({"ph": "M", "name": "thread_name", "pid": pid_index,
                    "tid": tid, "args": {"name": name}})
    return out
