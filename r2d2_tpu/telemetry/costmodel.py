"""XLA cost-model extraction + the analytic per-component cost model
(ISSUE 9 tentpole).

Two complementary views of "where does a step's compute go", one
machine-readable table for both:

  * **XLA program costs** (``collect_cost_table``): every compiled step
    factory — the learner step (single / multi-step scan / dp-sharded
    shard_map / GSPMD-TP external-batch), ``replay_add_many``,
    ``replay_sample``, and the anakin acting program — lowered AOT from
    shape avals and read back through ``compiled.cost_analysis()`` /
    ``memory_analysis()``: flops, transcendentals, bytes accessed,
    output bytes, argument/output/temp buffer sizes. Works on the CPU
    backend (tier-1-testable) and on TPU identically.
  * **Analytic component model** (``analytic_component_costs``): the
    PERF.md roofline's hand math as code — per-component
    (torso / lstm / head / sum_tree / replay) FLOPs and bytes per train
    step from the config alone, plus the serial-chain model. The
    program totals calibrate it; the component split is what the
    roofline report (tools/roofline.py) and the periodic record's
    ``costs`` block are built from.

THE while-loop caveat (measured, jax 0.4.37 / XLA HloCostAnalysis): a
``while`` body is counted ONCE, not x trip-count — so any ``lax.scan``
program (the LSTM time scan, the multi-step dispatch scan, the anakin
acting scan) undercounts its loop body's flops by (T-1)/T. Two uses,
two treatments:

  * the **regression gate** (``make regress`` via tools/regress.py)
    compares tables compiled exactly like production (scan form) with
    exact-match tolerance: analytic counts are deterministic, and any
    real change to the loop body still shifts the counted body cost, so
    an injected 2x FLOP change fails the gate even though the absolute
    number under-represents executed work;
  * the **roofline** compiles an *unroll twin* (``unroll_scans=True``:
    ``network.scan_unroll = seq_len`` and the anakin scan's ``unroll =
    block_length``) so the counted flops reflect executed work — that
    twin is what parity against ``bench.model_flops_per_step`` is
    asserted on (within 5%; tests/test_costmodel.py).

CLI (the ``make costs`` face):

    python -m r2d2_tpu.telemetry.costmodel --out COSTS.json
"""

import dataclasses
import json
import sys
from typing import Any, Dict, Iterable, Optional, Tuple

# ---------------------------------------------------------------------------
# per-backend peak specs (roofline numerators): dense matmul peak by
# compute dtype + HBM bandwidth. TPU numbers are the published per-chip
# figures; the CPU row is a NOMINAL placeholder (flagged) so the report
# renders on the test backend without pretending to know the host.
# ---------------------------------------------------------------------------

PEAK_SPECS: Tuple[Tuple[str, Dict[str, float]], ...] = (
    ("v6", dict(flops_bf16=918e12, flops_f32=459e12, hbm_gbps=1640.0)),
    ("v5p", dict(flops_bf16=459e12, flops_f32=229.5e12, hbm_gbps=2765.0)),
    ("v5 lite", dict(flops_bf16=197e12, flops_f32=98.5e12, hbm_gbps=819.0)),
    ("v5e", dict(flops_bf16=197e12, flops_f32=98.5e12, hbm_gbps=819.0)),
    ("v4", dict(flops_bf16=275e12, flops_f32=137.5e12, hbm_gbps=1228.0)),
    ("v3", dict(flops_bf16=123e12, flops_f32=61.5e12, hbm_gbps=900.0)),
    ("v2", dict(flops_bf16=45e12, flops_f32=22.5e12, hbm_gbps=700.0)),
)

# nominal 2-core-container numbers, NOT a measurement — %-of-peak rows on
# the CPU backend are structural smoke, never quoted (nominal=True rides
# the report so a reader cannot mistake them)
CPU_FALLBACK = dict(flops_bf16=5e10, flops_f32=5e10, hbm_gbps=10.0,
                    nominal=True)


def peak_spec(device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Peak FLOP/s + HBM bandwidth for a device kind (default: device 0
    of the current backend). Unknown kinds get the flagged CPU/nominal
    fallback rather than a silent zero."""
    if device_kind is None:
        import jax
        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    for marker, spec in PEAK_SPECS:
        if marker in kind:
            return dict(spec, device_kind=device_kind, nominal=False)
    return dict(CPU_FALLBACK, device_kind=device_kind)


# ---------------------------------------------------------------------------
# analytic component model
# ---------------------------------------------------------------------------

COMPONENTS = ("torso", "lstm", "head", "sum_tree", "replay")


def _conv_pyramid(cfg, action_dim: int):
    """Per-layer conv MACs/token + activation element counts, plus the
    downstream FC/LSTM/head MACs — the one place the per-token shape
    math lives (bench.model_flops_per_step delegates here)."""
    net, env = cfg.network, cfg.env
    h, w, c = env.frame_height, env.frame_width, env.frame_stack
    conv_macs, conv_elems = [], []
    for features, kernel, stride in net.conv_layers:
        h = (h - kernel) // stride + 1
        w = (w - kernel) // stride + 1
        conv_macs.append(h * w * features * kernel * kernel * c)
        conv_elems.append(h * w * features)
        c = features
    fc_macs = h * w * c * net.cnn_out_dim
    lstm_in = net.cnn_out_dim + action_dim
    lstm_macs = 4 * net.hidden_dim * (lstm_in + net.hidden_dim)
    head_macs = net.hidden_dim * net.hidden_dim + net.hidden_dim * action_dim
    if net.use_dueling:
        head_macs += net.hidden_dim * net.hidden_dim + net.hidden_dim
    return conv_macs, conv_elems, fc_macs, lstm_macs, head_macs


def model_flops_per_step(cfg, action_dim: int, use_double: bool) -> float:
    """Analytic model FLOPs for one train step: fwd + bwd (~2x fwd) +
    the target fwd when double-DQN is on, counting conv/FC/LSTM/head
    matmul MACs over the full (batch x seq_window) unroll at 2 FLOPs per
    MAC. Elementwise/decode/Adam FLOPs are noise against these and are
    not counted.

    Reconciled against XLA's ``cost_analysis()`` (ISSUE 9 satellite;
    parity-tested within 5% in tests/test_costmodel.py): the FIRST
    conv's input gradient is never computed — the observation needs no
    grad, XLA DCEs that backward conv — so the first conv contributes
    one unroll fewer than every other matmul. The pre-PR9 count skipped
    that term and overcounted 5-7% at the reference shape (the
    PERF.md:383 slope-sanity drift)."""
    conv_macs, _, fc_macs, lstm_macs, head_macs = _conv_pyramid(
        cfg, action_dim)
    unrolls = 3.0 + (1.0 if use_double else 0.0)
    tokens = cfg.replay.batch_size * cfg.sequence.seq_len
    macs_all = sum(conv_macs) + fc_macs + lstm_macs + head_macs
    # first conv: fwd + weight-grad + (target fwd), NO input-grad (a
    # conv-less torso has no such term)
    first_conv = conv_macs[0] if conv_macs else 0.0
    return 2.0 * tokens * (macs_all * unrolls - first_conv)


def analytic_component_costs(cfg, action_dim: int,
                             use_double: Optional[bool] = None,
                             act_bytes: Optional[int] = None
                             ) -> Dict[str, Any]:
    """Per-component FLOPs and bytes for ONE train step, from the config
    alone — pure math, no compile, deterministic (the periodic record's
    ``costs`` block and the roofline's component split).

    Bytes are documented first-order estimates: activations read+written
    once per unroll in the compute dtype, parameters read once per
    unroll in f32, the uint8 obs gather + decode, and the sum-tree's
    node touches — accurate enough to classify compute- vs memory-bound
    per component, NOT a byte-exact transfer model (the XLA program
    totals are; see ``collect_cost_table``).

    ``act_bytes`` is the activation dtype size: callers holding the
    RESOLVED compute dtype (the roofline tool, the Learner's record
    block — NetworkApply resolves the bf16 tri-state) pass 2 or 4 so
    the byte counts match the peak row they'll be judged against;
    unresolved contexts default to the backend-independent f32 worst
    case ("auto" counted as 4 — the golden-file convention)."""
    net, env, seq = cfg.network, cfg.env, cfg.sequence
    if use_double is None:
        use_double = net.use_double
    conv_macs, conv_elems, fc_macs, lstm_macs, head_macs = _conv_pyramid(
        cfg, action_dim)
    B, T = cfg.replay.batch_size, seq.seq_len
    tokens = B * T
    unrolls = 3.0 + (1.0 if use_double else 0.0)
    if act_bytes is None:
        act_bytes = 2 if str(net.bf16).lower() in ("on", "true", "1") else 4
    H = net.hidden_dim

    obs_bytes = tokens * env.frame_height * env.frame_width * env.frame_stack
    conv_act_bytes = sum(conv_elems) * tokens * act_bytes
    # f32 parameter bytes per component (kernels + FC / gates / heads)
    c_in = env.frame_stack
    torso_params = 0.0
    for features, kernel, _ in net.conv_layers:
        torso_params += 4.0 * kernel * kernel * c_in * features
        c_in = features
    fc_in = conv_elems[-1] if conv_elems else 0
    torso_params += 4.0 * fc_in * net.cnn_out_dim
    lstm_params = 4.0 * 4 * H * ((net.cnn_out_dim + action_dim) + H)
    head_params = 4.0 * head_macs

    components = {
        "torso": {
            # first conv contributes one unroll fewer (no input grad)
            "flops": 2.0 * tokens * (
                (sum(conv_macs) + fc_macs) * unrolls
                - (conv_macs[0] if conv_macs else 0.0)),
            "bytes": (obs_bytes              # uint8 frame gather
                      + obs_bytes * act_bytes  # decoded stack write
                      + 2.0 * unrolls * conv_act_bytes
                      + unrolls * torso_params),
        },
        "lstm": {
            "flops": 2.0 * tokens * lstm_macs * unrolls,
            # hoisted input projection activations + the per-step h/c
            # chain; recurrent weights counted once (VMEM-resident
            # across the scan — the fused-kernel design assumption)
            "bytes": (2.0 * unrolls * tokens * 4 * H * act_bytes
                      + 2.0 * unrolls * tokens * 2 * H * act_bytes
                      + unrolls * lstm_params),
        },
        "head": {
            "flops": 2.0 * tokens * head_macs * unrolls,
            "bytes": (2.0 * unrolls * tokens * (H + action_dim) * act_bytes
                      + unrolls * head_params),
        },
    }
    # prioritized sum tree: stratified descent (sample) + leaf update +
    # bottom-up rebuild — a handful of f32 ops per (sample x layer)
    from r2d2_tpu.ops.sum_tree import tree_num_layers
    layers = tree_num_layers(cfg.num_sequences)
    sum_tree_touches = B * layers
    components["sum_tree"] = {
        "flops": 8.0 * sum_tree_touches,          # cmp/sub/add per level x2 passes
        "bytes": 4.0 * 4 * sum_tree_touches,      # 2 reads + write, f32, x2 passes
    }
    # replay-side data movement of one sample: the uint8 window gather out
    # of the ring + hidden/meta rows (flops-free, pure bytes)
    components["replay"] = {
        "flops": 0.0,
        "bytes": float(obs_bytes + B * 2 * H * 4
                       + B * seq.learning_steps * 4 * 4),
    }

    total_flops = sum(c["flops"] for c in components.values())
    # the serial recurrent chain (PERF.md round-5 model): fwd + bwd
    # always walk the chain; the target fwd adds a third walk under
    # double-DQN unless the fused dual unroll interleaves it with the
    # online chain in the same scan. Resolved EXACTLY like the real
    # program (train_step.make_loss_fn) — "auto" is backend-dependent,
    # and a hand-rolled string check would claim the wrong chain length
    from r2d2_tpu.ops.pallas_kernels import resolve_pallas_setting
    fused_dual = use_double and resolve_pallas_setting(
        cfg.optim.fused_double_unroll, "optim.fused_double_unroll")
    serial_walks = 2 + (1 if (use_double and not fused_dual) else 0)
    serial_iters = T * serial_walks
    serial_flops = 2.0 * 4 * H * H * B * serial_iters
    return {
        "components": components,
        "total_flops": total_flops,
        "model_flops_per_step": model_flops_per_step(cfg, action_dim,
                                                     use_double),
        "tokens_per_step": tokens,
        "unrolls": unrolls,
        "serial_chain": {
            "iterations": serial_iters,
            "per_iter_flops": 2.0 * 4 * H * H * B,
            "flops": serial_flops,
            "share_of_total": (serial_flops / total_flops
                               if total_flops else 0.0),
        },
    }


# ---------------------------------------------------------------------------
# XLA program-cost extraction
# ---------------------------------------------------------------------------


def _sds(tree):
    """ShapeDtypeStruct twin of a pytree, preserving shardings where the
    leaves carry them (committed arrays of a sharded replay/state —
    lowering a shard_map program from unsharded avals would let the
    compiler pick layouts the real arrays don't match)."""
    import jax

    def one(x):
        sharding = getattr(x, "sharding", None)
        try:
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
        except TypeError:
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree_util.tree_map(one, tree)


def program_cost(compiled) -> Dict[str, Any]:
    """Flatten one compiled executable's ``cost_analysis()`` +
    ``memory_analysis()`` into a plain dict. Tolerant of backend
    variance: either API may be absent/None on exotic backends — missing
    numbers are simply omitted, never fabricated."""
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:                               # pragma: no cover
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        for key, name in (("flops", "flops"),
                          ("transcendentals", "transcendentals"),
                          ("bytes accessed", "bytes_accessed"),
                          ("bytes accessedout{}", "output_bytes_accessed")):
            if key in ca:
                out[name] = float(ca[key])
    try:
        ma = compiled.memory_analysis()
    except Exception:                               # pragma: no cover
        ma = None
    if ma is not None:
        for attr, name in (
                ("argument_size_in_bytes", "argument_bytes"),
                ("output_size_in_bytes", "output_bytes"),
                ("temp_size_in_bytes", "temp_bytes"),
                ("generated_code_size_in_bytes", "generated_code_bytes")):
            v = getattr(ma, attr, None)
            if v is not None:
                out[name] = int(v)
    return out


def _cost_of(jitted, *args) -> Dict[str, Any]:
    return program_cost(jitted.lower(*args).compile())


GATE_VARIANTS = ("learner_step", "learner_step_multi", "learner_step_sharded",
                 "learner_step_tp", "replay_add_many", "replay_sample",
                 "anakin_act", "serve_forward", "quant_forward")


def collect_cost_table(cfg, variants: Iterable[str] = GATE_VARIANTS,
                       unroll_scans: bool = False) -> Dict[str, Any]:
    """Lower + compile each requested step factory at ``cfg``'s shapes
    and extract its program costs into one machine-readable table.

    ``unroll_scans`` builds the roofline's unroll twin (scan bodies
    fully unrolled so flops count executed work — see module caveat);
    the default scan form is what the regression gate snapshots. Every
    program is built with ``diag=None`` (the telemetry kill-switch
    baseline program).

    Variants needing a wider mesh than the backend offers raise — the
    gate must be deterministic, so "silently skipped" is not a state.
    """
    import jax

    from r2d2_tpu.envs.factory import create_jax_env
    from r2d2_tpu.learner.train_step import (create_train_state,
                                             make_external_batch_step,
                                             make_learner_step,
                                             make_multi_learner_step)
    from r2d2_tpu.models.network import NetworkApply
    from r2d2_tpu.replay.device_replay import (replay_add_many, replay_init,
                                               replay_sample)
    from r2d2_tpu.replay.structs import ReplaySpec
    from r2d2_tpu.replay.synthetic import make_synthetic_block

    variants = tuple(variants)
    if unroll_scans:
        cfg = cfg.replace(**{"network.scan_unroll": cfg.sequence.seq_len})
    env = create_jax_env(cfg.env)
    action_dim = env.action_dim
    spec = ReplaySpec.from_config(cfg)
    net = NetworkApply(action_dim, cfg.network, cfg.env.frame_stack,
                       cfg.env.frame_height, cfg.env.frame_width)
    ts_aval = _sds(jax.eval_shape(
        lambda k: create_train_state(k, net, cfg.optim),
        jax.random.PRNGKey(0)))
    rs_aval = _sds(jax.eval_shape(lambda: replay_init(spec)))
    key_aval = jax.ShapeDtypeStruct((2,), jax.numpy.uint32)

    programs: Dict[str, Dict[str, Any]] = {}

    if "learner_step" in variants:
        step = make_learner_step(net, spec, cfg.optim,
                                 cfg.network.use_double)
        programs["learner_step"] = _cost_of(step, ts_aval, rs_aval)
    if "learner_step_multi" in variants:
        k = max(cfg.runtime.steps_per_dispatch, 2)
        multi = make_multi_learner_step(net, spec, cfg.optim,
                                        cfg.network.use_double, k)
        programs["learner_step_multi"] = dict(
            _cost_of(multi, ts_aval, rs_aval), steps_per_dispatch=k)
    if "learner_step_sharded" in variants or "learner_step_tp" in variants:
        from r2d2_tpu.parallel import make_mesh
    if "learner_step_sharded" in variants:
        from r2d2_tpu.parallel import make_sharded_learner_step
        from r2d2_tpu.parallel.mesh import dp_sharding
        dp = max(cfg.mesh.dp, 2)
        if len(jax.devices()) < dp:
            raise RuntimeError(
                f"learner_step_sharded needs {dp} devices, backend has "
                f"{len(jax.devices())} — pin a virtual mesh first "
                "(utils.platform.pin_cpu_platform)")
        mesh = make_mesh(dataclasses.replace(cfg.mesh, dp=dp, mp=1))
        sharded = make_sharded_learner_step(
            net, spec, cfg.optim, cfg.network.use_double, mesh,
            steps_per_dispatch=1)
        # avals only — materializing the real sharded ring just to read
        # shardings would allocate the multi-GiB obs buffers at the
        # reference shape; sharded_replay_init's layout is uniform
        # (leading dp axis, every leaf dp_sharding-placed), so build it
        sharding = dp_sharding(mesh)
        srs_aval = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct((dp,) + a.shape, a.dtype,
                                           sharding=sharding),
            jax.eval_shape(lambda: replay_init(spec)))
        programs["learner_step_sharded"] = dict(
            _cost_of(sharded, ts_aval, srs_aval), dp=dp)
    if "learner_step_tp" in variants:
        from r2d2_tpu.parallel.tensor_parallel import (
            make_tp_external_batch_step, state_shardings)
        mp = max(cfg.mesh.mp, 2)
        if len(jax.devices()) < mp:
            raise RuntimeError(
                f"learner_step_tp needs {mp} devices, backend has "
                f"{len(jax.devices())}")
        from jax.sharding import NamedSharding, PartitionSpec as P
        tp_mesh = make_mesh(dataclasses.replace(cfg.mesh, dp=1, mp=mp))
        tp_step, _, _ = make_tp_external_batch_step(
            net, spec, cfg.optim, cfg.network.use_double, tp_mesh)
        shardings = state_shardings(
            jax.eval_shape(lambda k: create_train_state(k, net, cfg.optim),
                           jax.random.PRNGKey(0)), tp_mesh)
        ts_tp = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            ts_aval, shardings)
        batch_aval = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=NamedSharding(tp_mesh, P("dp"))),
            jax.eval_shape(lambda r, k: replay_sample(spec, r, k),
                           rs_aval, key_aval))
        programs["learner_step_tp"] = dict(
            _cost_of(tp_step, ts_tp, batch_aval), mp=mp)
    if "external_batch_step" in variants:
        ext = make_external_batch_step(net, spec, cfg.optim,
                                       cfg.network.use_double)
        batch_aval = _sds(jax.eval_shape(
            lambda r, k: replay_sample(spec, r, k), rs_aval, key_aval))
        programs["external_batch_step"] = _cost_of(ext, ts_aval, batch_aval)
    if "replay_add_many" in variants:
        import numpy as np
        k = min(8, spec.num_blocks)
        blk = make_synthetic_block(spec, np.random.default_rng(0))
        blocks_aval = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct((k,) + np.shape(x),
                                           np.asarray(x).dtype), blk)
        add = jax.jit(lambda s, b: replay_add_many(spec, s, b),
                      donate_argnums=0)
        programs["replay_add_many"] = dict(
            _cost_of(add, rs_aval, blocks_aval), blocks=k)
    if "replay_sample" in variants:
        samp = jax.jit(lambda s, k: replay_sample(spec, s, k))
        programs["replay_sample"] = _cost_of(samp, rs_aval, key_aval)
    if "serve_forward" in variants or "quant_forward" in variants:
        h_f, w_f = cfg.env.frame_height, cfg.env.frame_width
        s_f, hd_f = cfg.env.frame_stack, cfg.network.hidden_dim
        params_aval = _sds(jax.eval_shape(net.init, jax.random.PRNGKey(0)))

        def fwd_avals(b):
            return (jax.ShapeDtypeStruct((b, h_f, w_f, s_f),
                                         jax.numpy.float32),
                    jax.ShapeDtypeStruct((b,), jax.numpy.int32),
                    jax.ShapeDtypeStruct((b, 2, hd_f), jax.numpy.float32))
    if "serve_forward" in variants:
        # the serving plane's pow2 dispatch buckets (ISSUE 14 satellite:
        # PR 12 added the micro-batched program but never tabled it) —
        # one row per AOT-precompiled bucket of the PRODUCTION serve
        # forward at this config's inference dtype, so `make costs` /
        # tools/roofline.py cover the serving plane and the costs gate
        # catches a program change at any width
        from r2d2_tpu.actor.policy import make_forward_fn
        from r2d2_tpu.serve.server import serve_buckets
        fwd = make_forward_fn(
            net, probe_interval=(cfg.telemetry.quant_probe_interval
                                 if cfg.network.inference_dtype != "f32"
                                 else 0))
        quant_mode = cfg.network.inference_dtype != "f32"
        if quant_mode:
            from r2d2_tpu.models.network import make_inference_bundle
            serve_params = _sds(jax.eval_shape(
                lambda p: make_inference_bundle(net, p, 0), params_aval))
        else:
            serve_params = params_aval
        for b in serve_buckets(cfg.serve.max_batch):
            args = (serve_params,) + fwd_avals(b)
            if quant_mode:
                # + tick and live-row count (the quant signature)
                args = args + (jax.ShapeDtypeStruct((), jax.numpy.int32),
                               jax.ShapeDtypeStruct((), jax.numpy.int32))
            programs[f"serve_forward_b{b}"] = dict(_cost_of(fwd, *args),
                                                   batch=b)
    if "quant_forward" in variants:
        # the quantized-acting weight-streaming rows (ISSUE 14): the
        # probe-free forward over EXACTLY the weight tree the steady
        # state streams per dispatch — f32 params vs the bf16/int8
        # twins — plus the analytic weight_bytes each one reads. The
        # int8 row's weight_bytes / the f32 row's is the >= 3x cut the
        # TPU projection rests on; both are exact-match-gated.
        from r2d2_tpu.models.network import (param_tree_bytes,
                                             quantize_params,
                                             quantized_inference_apply)
        bq = cfg.serve.max_batch
        for mode in ("f32", "bf16", "int8"):
            if mode == "f32":
                from r2d2_tpu.actor.policy import make_forward_fn
                fn = make_forward_fn(net, "f32")
                tree_aval = params_aval
            else:
                net_m = NetworkApply(
                    action_dim,
                    dataclasses.replace(cfg.network, inference_dtype=mode),
                    cfg.env.frame_stack, cfg.env.frame_height,
                    cfg.env.frame_width)

                def step(qt, stacked, last_action, hidden, _net=net_m):
                    import jax.numpy as jnp
                    obs = stacked[:, None]
                    la = jax.nn.one_hot(last_action, _net.action_dim,
                                        dtype=jnp.float32)[:, None]
                    q, h2 = quantized_inference_apply(_net, qt, obs, la,
                                                      hidden)
                    return jnp.argmax(q[:, 0], axis=-1), q[:, 0], h2

                fn = jax.jit(step)
                tree_aval = _sds(jax.eval_shape(
                    lambda p, _m=mode: quantize_params(p, _m), params_aval))
            programs[f"acting_forward_{mode}"] = dict(
                _cost_of(fn, tree_aval, *fwd_avals(bq)), batch=bq,
                weight_bytes=param_tree_bytes(tree_aval))
    if "anakin_act" in variants:
        from r2d2_tpu.actor.anakin import init_act_carry, make_anakin_act
        from r2d2_tpu.config import apex_epsilon
        lanes = cfg.actor.anakin_lanes
        eps = [apex_epsilon(i, lanes, cfg.actor.base_eps,
                            cfg.actor.eps_alpha) for i in range(lanes)]
        act = make_anakin_act(
            env, net, spec, num_lanes=lanes, epsilons=eps,
            gamma=cfg.optim.gamma, priority=cfg.actor.anakin_priority,
            near_greedy_eps=cfg.actor.near_greedy_eps,
            priority_eta=cfg.optim.priority_eta,
            unroll=spec.block_length if unroll_scans else 1)
        carry_aval = _sds(jax.eval_shape(
            lambda k: init_act_carry(env, spec, lanes, k),
            jax.random.PRNGKey(0)))
        wv_aval = jax.ShapeDtypeStruct((), jax.numpy.int32)
        act_params_aval = ts_aval.params
        if cfg.network.inference_dtype != "f32":
            # the quantized acting scan takes the published inference
            # bundle, not raw params (actor/anakin.py)
            from r2d2_tpu.models.network import make_inference_bundle
            act_params_aval = _sds(jax.eval_shape(
                lambda p: make_inference_bundle(net, p, 0),
                ts_aval.params))
        programs["anakin_act"] = dict(
            _cost_of(act, act_params_aval, carry_aval, wv_aval),
            lanes=lanes)

    return {
        "schema": 1,
        "backend": jax.default_backend(),
        "unroll_scans": bool(unroll_scans),
        "action_dim": action_dim,
        "shape": {
            "batch_size": spec.batch_size,
            "seq_len": cfg.sequence.seq_len,
            "frame": [cfg.env.frame_height, cfg.env.frame_width,
                      cfg.env.frame_stack],
            "hidden_dim": cfg.network.hidden_dim,
            "block_length": spec.block_length,
            "use_double": bool(cfg.network.use_double),
        },
        "programs": programs,
    }


# ---------------------------------------------------------------------------
# the regression-gate fixture: ONE pinned tiny config (compiles in
# seconds on the CPU backend) whose table BASELINE.json snapshots under
# "costs" — tools/regress.py recomputes and exact-compares it, so a
# refactor that silently changes any step factory's flops/bytes fails
# `make regress` even on wall-clock-noisy hosts.
# ---------------------------------------------------------------------------

GATE_OVERRIDES = {
    "env.game_name": "Fake",
    "env.frame_height": 24, "env.frame_width": 24, "env.frame_stack": 2,
    "env.episode_len": 40,
    "network.conv_layers": ((8, 4, 2), (16, 3, 1)),
    "network.hidden_dim": 32, "network.cnn_out_dim": 64,
    "network.use_double": True,
    "sequence.burn_in_steps": 6, "sequence.learning_steps": 5,
    "sequence.forward_steps": 3,
    "replay.capacity": 800, "replay.block_length": 20,
    "replay.batch_size": 8, "replay.learning_starts": 100,
    "actor.anakin_lanes": 4,
    "runtime.steps_per_dispatch": 3,
}


def gate_config():
    from r2d2_tpu.config import Config
    return Config().replace(**GATE_OVERRIDES)


_gate_table_cache: Optional[Dict[str, Any]] = None


def gate_table() -> Dict[str, Any]:
    """The gated cost table: the pinned fixture config through every
    step-factory variant, in production (scan) form. Deterministic for a
    given jax/XLA build + backend; `make regress` runs it CPU-pinned.
    Memoized per process — the ~20-30 s of tiny-config compiles are a
    pure function of the checked-out code, and the regress-gate tests
    drive the CLI's main() several times in one process."""
    global _gate_table_cache
    if _gate_table_cache is None:
        _gate_table_cache = collect_cost_table(
            gate_config(), variants=GATE_VARIANTS, unroll_scans=False)
    return _gate_table_cache


def compare_cost_tables(baseline: Dict[str, Any], current: Dict[str, Any],
                        rtol: float = 1e-6) -> list:
    """One row per baselined program metric: ok / CHANGED / missing.
    Unlike the bench gate's lower-is-worse tolerance bands, ANY relative
    change beyond ``rtol`` fails in BOTH directions — the analytic
    counts are deterministic, and a silent 2x FLOP increase is exactly
    the regression this gate exists for. Programs new in ``current``
    are not rows (they join at the next --update)."""
    rows = []
    base_progs = (baseline or {}).get("programs") or {}
    cur_progs = (current or {}).get("programs") or {}
    for prog, metrics in sorted(base_progs.items()):
        cur = cur_progs.get(prog)
        for name, base in sorted(metrics.items()):
            if not isinstance(base, (int, float)) or isinstance(base, bool):
                continue
            row = {"program": prog, "metric": name, "baseline": float(base)}
            if cur is None or name not in cur:
                row.update({"current": None, "status": "missing"})
            else:
                value = float(cur[name])
                row["current"] = value
                denom = max(abs(float(base)), 1.0)
                if abs(value - float(base)) / denom > rtol:
                    row["status"] = "CHANGED"
                    row["delta_pct"] = round(
                        100.0 * (value - float(base)) / denom, 3)
                else:
                    row["status"] = "ok"
            rows.append(row)
    return rows


def main(argv=None) -> int:
    import argparse

    from r2d2_tpu.utils.platform import pin_cpu_platform
    p = argparse.ArgumentParser(
        description="extract the per-program XLA cost table (make costs)")
    p.add_argument("--out", default="COSTS.json")
    p.add_argument("--unroll-scans", action="store_true",
                   help="build the roofline's unroll twin instead of the "
                        "gate's scan-form table")
    p.add_argument("--variants", nargs="*", default=None,
                   help=f"subset of {GATE_VARIANTS}")
    p.add_argument("--reference-shape", action="store_true",
                   help="use the full reference config instead of the "
                        "pinned gate fixture (slow compiles)")
    args = p.parse_args(argv)

    # the sharded variant needs >= 2 devices; a virtual CPU mesh keeps
    # the table backend-independent and tier-1-testable
    pin_cpu_platform(2)
    from r2d2_tpu.config import Config
    cfg = Config() if args.reference_shape else gate_config()
    table = collect_cost_table(cfg, variants=args.variants or GATE_VARIANTS,
                               unroll_scans=args.unroll_scans)
    with open(args.out, "w") as f:
        json.dump(table, f, indent=2, sort_keys=True)
        f.write("\n")
    for prog, m in sorted(table["programs"].items()):
        print(f"{prog:>22}: flops={m.get('flops', 0):.6g} "
              f"bytes={m.get('bytes_accessed', 0):.6g} "
              f"temp={m.get('temp_bytes', 0):.4g}")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
