"""Policy-quality observability (ISSUE 20) — the pillar that watches the
one thing the fleet exists to produce.

Three signals, one ledger:

  * **Q-calibration** — R2D2's own diagnostic (Kapturowski et al.): the gap
    between the greedy max-Q the actor predicted at decision time and the
    realized discounted n-step return over the same window. The tap is
    ``LocalBuffer.finish`` (the only place predicted Q and realized rewards
    coexist on the host); the join math lives here (``calibration_join``)
    so it is testable against a per-row python reference. Blocks do NOT
    carry q-values, so the tap feeds raw per-step quantities straight into
    the aggregator — thread actors only, the same boundary as the quant
    accuracy probes (process children have no channel back to this record).
  * **Continuous eval** — a background ``QualityEvaluator`` re-runs
    ``cli/evaluate.py``'s rollout machinery (optionally through the serving
    plane, ``--serve`` style) against each new checkpoint, producing
    per-scenario return rows that share one schema with the CLI's
    ``evaluate_scenarios`` (ROADMAP item 5's scenario-coverage axis).
  * **Shadow scoring** — fed by ``fleet/promotion.ShadowScorer`` through
    ``on_shadow``: greedy-agreement and max-|ΔQ| divergence of a candidate
    server against live replies on mirrored traffic.

All of it aggregates in ``QualityStats`` (thread-safe, interval-consumed —
the QuantStats discipline) and emits as the periodic record's ``quality``
block plus a ``quality_player{p}.jsonl`` stream (``QualityLedger``) the
tower tails. Default-off: with ``telemetry.quality_enabled = false``
nothing here is constructed and records are byte-identical to the PR-19
schema. Ledger rows carry checkpoint lineage (step, publish stamp, parent
stamp) so self-play Elo bookkeeping (ROADMAP 5b) can attach later without
a schema break.
"""

import json
import os
import threading
import time
from typing import Callable, List, Optional

import numpy as np


def calibration_join(qvals: np.ndarray, rewards: np.ndarray, gamma: float,
                     n_steps: int):
    """Join predicted Q against realized n-step return for one block.

    ``qvals``: (T+1, A) — per-step Q at decision time plus the bootstrap
    row (zeros when the episode terminated, matching LocalBuffer's
    convention, so termination needs no separate flag). ``rewards``: (T,)
    raw per-step rewards. Returns ``(pred, realized)`` of shape (T,):

      pred[t]     = max_a Q[t, a]
      realized[t] = sum_{i<m} gamma^i r[t+i] + gamma^m max_a Q[t+m, a],
                    m = min(n_steps, T - t)

    — the same target convention as ops/returns.initial_priorities, built
    independently here so the test's per-row python reference actually
    cross-checks something."""
    qvals = np.asarray(qvals, np.float64)
    rewards = np.asarray(rewards, np.float64)
    T = rewards.shape[0]
    if qvals.shape[0] != T + 1:
        raise ValueError(f"qvals rows ({qvals.shape[0]}) must be "
                         f"len(rewards)+1 ({T + 1})")
    n = max(int(n_steps), 1)
    maxq = qvals.max(axis=1)                             # (T+1,)
    # windowed discounted reward sums: pad so tail windows shorten cleanly
    kernel = gamma ** np.arange(n)
    padded = np.concatenate([rewards, np.zeros(n - 1)])
    # np.convolve flips its kernel; flip back so window t dots r[t:t+n]
    rsum = np.convolve(padded, kernel[::-1], mode="valid")
    t = np.arange(T)
    boot = np.minimum(t + n, T)
    realized = rsum + gamma ** (boot - t) * maxq[boot]
    return maxq[:T], realized


def make_calibration_feed(stats: "QualityStats", *, gamma: float,
                          n_steps: int, sample_every: int = 1,
                          stamp_fn: Optional[Callable[[], int]] = None):
    """Build the LocalBuffer-side tap: a callable ``feed(qvals, rewards)``
    invoked once per finished block, sampling every Nth block
    (``telemetry.quality_calib_sample_every``). ``stamp_fn`` supplies the
    publish stamp the feeding actor is currently acting with (its fan-out
    endpoint's adopted version — the PR-5 lineage plumbing), joining the
    calibration signal to a checkpoint generation."""
    every = max(int(sample_every), 1)
    count = [0]

    def feed(qvals, rewards):
        count[0] += 1
        if count[0] % every:
            return
        pred, realized = calibration_join(qvals, rewards, gamma, n_steps)
        if pred.size == 0:
            return
        gaps = pred - realized
        stamp = int(stamp_fn()) if stamp_fn is not None else None
        stats.on_calibration(int(pred.size), float(gaps.sum()),
                             float(np.abs(gaps).max()), stamp=stamp)
    return feed


_IDLE_PROMOTION = {"state": "idle", "candidate_stamp": None,
                   "previous_stamp": None, "age_s": None,
                   "promotions": 0, "rollbacks": 0, "refusals": 0}


class QualityStats:
    """Thread-safe aggregator behind the record's ``quality`` block —
    calibration taps (actor threads), the evaluator, and the shadow
    scorer all feed it; ``interval_block()`` consumes the interval
    (the QuantStats discipline). Interval extrema are None when nothing
    fed them, which HOLDS the alert rules instead of feeding them
    zeros."""

    def __init__(self, promotion_block: Optional[Callable[[], dict]] = None):
        self._lock = threading.Lock()
        self._promotion_block = promotion_block
        # calibration (interval-consumed + cumulative)
        self._cal_samples = 0
        self._cal_gap_sum = 0.0
        self._cal_abs_max: Optional[float] = None
        self._cal_stamp: Optional[int] = None
        self.calibration_samples_total = 0
        # latest eval snapshot (persists across intervals so the drop
        # rule sees a value series, not a one-interval blip)
        self._eval: Optional[dict] = None
        self.evals_total = 0
        # shadow (interval-consumed + cumulative)
        self._sh_requests = 0
        self._sh_agreed = 0
        self._sh_dq_max: Optional[float] = None
        self._sh_dropped = 0
        self.shadow_mirrored_total = 0

    def set_promotion(self, provider: Callable[[], dict]) -> None:
        self._promotion_block = provider

    def on_calibration(self, samples: int, gap_sum: float, gap_abs_max: float,
                       stamp: Optional[int] = None) -> None:
        with self._lock:
            self._cal_samples += int(samples)
            self._cal_gap_sum += float(gap_sum)
            if (self._cal_abs_max is None
                    or gap_abs_max > self._cal_abs_max):
                self._cal_abs_max = float(gap_abs_max)
            if stamp is not None:
                self._cal_stamp = int(stamp)
            self.calibration_samples_total += int(samples)

    def on_eval(self, scenarios: List[dict], *, step: Optional[int] = None,
                publish_stamp: Optional[int] = None,
                parent_stamp: Optional[int] = None) -> None:
        """Record a completed per-checkpoint eval: per-scenario rows (the
        ``evaluate_scenarios`` schema) plus the checkpoint's lineage."""
        eps = sum(int(r.get("episodes", 0)) for r in scenarios)
        mean = None
        if eps > 0:
            mean = sum(float(r["mean_return"]) * int(r.get("episodes", 0))
                       for r in scenarios) / eps
        with self._lock:
            self._eval = {
                "checkpoint_step": step,
                "publish_stamp": publish_stamp,
                "parent_stamp": parent_stamp,
                "mean_return": mean,
                "scenarios": list(scenarios),
            }
            self.evals_total += 1

    def latest_eval(self) -> Optional[dict]:
        with self._lock:
            return dict(self._eval) if self._eval is not None else None

    def on_shadow(self, requests: int, agreed: int,
                  dq_max: Optional[float] = None, dropped: int = 0) -> None:
        with self._lock:
            self._sh_requests += int(requests)
            self._sh_agreed += int(agreed)
            if dq_max is not None and (self._sh_dq_max is None
                                       or dq_max > self._sh_dq_max):
                self._sh_dq_max = float(dq_max)
            self._sh_dropped += int(dropped)
            self.shadow_mirrored_total += int(requests)

    def interval_block(self) -> dict:
        with self._lock:
            cal = {
                "samples": self._cal_samples,
                "gap_mean": (self._cal_gap_sum / self._cal_samples
                             if self._cal_samples else None),
                "gap_abs_max": self._cal_abs_max,
                "stamp": self._cal_stamp,
                "samples_total": self.calibration_samples_total,
            }
            self._cal_samples = 0
            self._cal_gap_sum = 0.0
            self._cal_abs_max = None
            ev = self._eval or {}
            eval_blk = {
                "evals_total": self.evals_total,
                "checkpoint_step": ev.get("checkpoint_step"),
                "publish_stamp": ev.get("publish_stamp"),
                "parent_stamp": ev.get("parent_stamp"),
                "mean_return": ev.get("mean_return"),
                "scenarios": list(ev.get("scenarios", [])),
            }
            reqs = self._sh_requests
            shadow = {
                "requests": reqs,
                "agree_frac": (self._sh_agreed / reqs) if reqs else None,
                "divergence": (1.0 - self._sh_agreed / reqs) if reqs
                              else None,
                "dq_max": self._sh_dq_max,
                "dropped": self._sh_dropped,
                "mirrored_total": self.shadow_mirrored_total,
            }
            self._sh_requests = 0
            self._sh_agreed = 0
            self._sh_dq_max = None
            self._sh_dropped = 0
            promo = self._promotion_block
        promotion = dict(_IDLE_PROMOTION) if promo is None else promo()
        return {"calibration": cal, "eval": eval_blk, "shadow": shadow,
                "promotion": promotion}


class QualityLedger:
    """The ``quality_player{p}.jsonl`` stream: one row per metrics
    interval, shaped like every other plane stream the tower tails —
    a process-identity header + clock anchor (``proc``, the PR-19
    convention) and the ``quality`` block under its own key, so
    ``tools/sentinel.py --stream`` replays it through the unchanged rule
    paths. ``interval_block()`` is the TrainMetrics provider: it computes
    the block, appends the row (write failures are counted, never
    raised — telemetry must not kill the driver loop), and returns the
    block for the record."""

    def __init__(self, stats: QualityStats, save_dir: str, player_idx: int,
                 resume: bool = False):
        from r2d2_tpu.telemetry.tracing import proc_header
        self.stats = stats
        self.path = os.path.join(save_dir or ".",
                                 f"quality_player{player_idx}.jsonl")
        self._proc = proc_header("quality")
        self.write_errors = 0
        self._lock = threading.Lock()
        if not resume:
            try:
                open(self.path, "w").close()
            except OSError:
                self.write_errors += 1

    def interval_block(self) -> dict:
        block = self.stats.interval_block()
        row = {"t": time.time(), "proc": self._proc, "quality": block}
        ev = block.get("eval", {})
        # lineage rides at top level too (ROADMAP 5b's attach point)
        row["lineage"] = {"step": ev.get("checkpoint_step"),
                          "publish_stamp": ev.get("publish_stamp"),
                          "parent_stamp": ev.get("parent_stamp")}
        try:
            with self._lock, open(self.path, "a") as f:
                f.write(json.dumps(row) + "\n")
        except (OSError, TypeError, ValueError):
            self.write_errors += 1
        return block


class QualityEvaluator:
    """Continuous eval as a background client of the training run: polls
    ``runtime.save_dir`` for new checkpoints and re-runs the
    ``cli/evaluate.py`` rollout machinery against each (through the
    serving plane when ``serve=True`` — eval traffic exercises the same
    fleet it scores, the SEED evaluation-as-a-service shape). Results
    land in ``QualityStats.on_eval`` with lineage: the checkpoint step,
    the publish stamp at eval time (``stamp_fn``), and the PREVIOUS
    eval's stamp as parent. ``run_once()`` is the synchronous entry the
    tests and the drill drive directly."""

    def __init__(self, cfg, player_idx: int, stats: QualityStats, *,
                 interval_s: float = 60.0, rounds: int = 2, clients: int = 2,
                 serve: bool = True, testing: bool = False,
                 stamp_fn: Optional[Callable[[], int]] = None):
        self.cfg = cfg
        self.player_idx = player_idx
        self.stats = stats
        self.interval_s = float(interval_s)
        self.rounds = int(rounds)
        self.clients = int(clients)
        self.serve = bool(serve)
        self.testing = bool(testing)
        self.stamp_fn = stamp_fn
        self.eval_errors = 0
        self._last_index: Optional[int] = None
        self._last_stamp: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> Optional[List[dict]]:
        """Evaluate the newest checkpoint if it hasn't been scored yet;
        returns its per-scenario rows (None when nothing new)."""
        from r2d2_tpu.runtime.checkpoint import list_checkpoints
        ckpts = list_checkpoints(self.cfg.runtime.save_dir or ".",
                                 self.cfg.env.game_name, self.player_idx)
        if not ckpts:
            return None
        index, path = ckpts[-1]
        if self._last_index is not None and index <= self._last_index:
            return None
        from r2d2_tpu.cli.evaluate import evaluate_scenarios
        res = evaluate_scenarios(
            self.cfg, path, self.rounds, serve=self.serve,
            serve_clients=self.clients, testing=self.testing,
            seed=self.cfg.runtime.seed + 777)
        rows = res["scenarios"]
        stamp = int(self.stamp_fn()) if self.stamp_fn is not None else None
        self.stats.on_eval(rows, step=res.get("step"), publish_stamp=stamp,
                           parent_stamp=self._last_stamp)
        self._last_index = int(index)
        self._last_stamp = stamp
        return rows

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                # eval is best-effort observability: a transient failure
                # (checkpoint mid-write, serve hiccup) must not kill the
                # evaluator — count it and retry next interval
                self.eval_errors += 1

    def start(self) -> "QualityEvaluator":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"quality-eval-p{self.player_idx}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
