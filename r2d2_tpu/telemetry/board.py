"""Cross-process histogram aggregation: the shared-memory telemetry board.

Actor PROCESSES cannot feed the learner's in-process stage timers, and
shipping timing events through the experience queue would put telemetry
on the data path. Instead each actor slot owns one row of a
``multiprocessing.shared_memory`` table of CUMULATIVE histogram counts —
(n_slots, n_stages * NBUCKETS) int64 — and publishes by overwriting its
row on the telemetry flush cadence (core.py drain thread; publishing is
one vectorized row store, off the policy hot path). The learner side
reads the whole table per log interval and differences it against the
previous read, so each interval's aggregated percentiles cover exactly
that interval's fleet-wide observations. Same pickle/attach lifecycle as
the HeartbeatBoard (runtime/feeder.py): the handle crosses the spawn
boundary by name, the creator owns and unlinks the region.

Torn reads are tolerated by design: a row store is not atomic, so a read
racing a publish can see a row mid-write. Counts are cumulative and
monotonic per slot, so the torn buckets surface in the NEXT interval's
delta instead of being lost. A respawned actor restarts its row from
zero; the reader treats any count decrease as a slot reset and takes the
fresh cumulative row as that interval's delta.
"""

import time
from multiprocessing import shared_memory
from typing import Optional

import numpy as np

from r2d2_tpu.telemetry.histogram import NBUCKETS

# Per-slot resource gauge columns appended after the histogram table
# (ISSUE 7): [rss_bytes, cpu_ms_cumulative]. Same publish cadence and
# torn-read tolerance as the histograms; the ResourceMonitor reads them
# per sample and differences cpu_ms into a utilization percentage.
N_GAUGES = 2


class TelemetryBoard:
    def __init__(self, n_slots: int, n_stages: Optional[int] = None,
                 _attach_name: Optional[str] = None):
        if n_stages is None:
            from r2d2_tpu.telemetry.core import STAGES
            n_stages = len(STAGES)
        self.n_slots = n_slots
        self.n_stages = n_stages
        self._owner = _attach_name is None
        self._shm = None
        self._arr = None
        self._gauges = None
        self._final = None     # post-close snapshot for post-mortem reads
        self._prev = None      # owner-side last-read snapshot (take_deltas)
        if self._owner:
            self._shm = shared_memory.SharedMemory(
                create=True,
                size=n_slots * (n_stages * NBUCKETS + N_GAUGES) * 8)
            self._bind()
            self._arr[:] = 0
            self._gauges[:] = 0
        else:
            self._name = _attach_name

    def __getstate__(self):
        return {"n_slots": self.n_slots, "n_stages": self.n_stages,
                "name": self.name}

    def __setstate__(self, state):
        self.__init__(state["n_slots"], state["n_stages"],
                      _attach_name=state["name"])

    @property
    def name(self) -> str:
        return self._shm.name if self._shm is not None else self._name

    def _bind(self) -> None:
        self._arr = np.ndarray((self.n_slots, self.n_stages * NBUCKETS),
                               np.int64, self._shm.buf)
        self._gauges = np.ndarray(
            (self.n_slots, N_GAUGES), np.int64, self._shm.buf,
            offset=self.n_slots * self.n_stages * NBUCKETS * 8)

    def _ensure(self) -> np.ndarray:
        if self._shm is None:
            if self._final is not None:
                return self._final
            from r2d2_tpu.runtime.weights import untrack_attached_shm
            self._shm = shared_memory.SharedMemory(name=self._name)
            untrack_attached_shm(self._shm)
            self._bind()
        return self._arr

    def publish(self, slot: int, counts: np.ndarray) -> None:
        """Overwrite this slot's row with the worker's CUMULATIVE
        (n_stages, NBUCKETS) counts matrix — one vectorized store."""
        self._ensure()[slot] = counts.reshape(-1)

    def read(self) -> np.ndarray:
        """Snapshot of the whole table as (n_slots, n_stages, NBUCKETS)."""
        return (self._ensure().copy()
                .reshape(self.n_slots, self.n_stages, NBUCKETS))

    def publish_gauges(self, slot: int, rss_bytes: int, cpu_ms: int) -> None:
        """Worker-side resource gauges for this slot (ISSUE 7): current
        RSS and cumulative CPU milliseconds — published on the telemetry
        flush cadence alongside the histogram row."""
        self._ensure()
        self._gauges[slot, 0] = int(rss_bytes)
        self._gauges[slot, 1] = int(cpu_ms)

    def read_gauges(self) -> Optional[np.ndarray]:
        """Snapshot of the gauge table, (n_slots, N_GAUGES) int64; None
        once the board is closed (gauges are live-only — the histogram
        _final snapshot exists for post-mortem percentile reads, which
        gauges don't serve)."""
        if self._shm is None and self._final is not None:
            return None
        self._ensure()
        return self._gauges.copy()

    def reset_slot(self, slot: int) -> None:
        """Fresh incarnation (actor respawn): zero the row so the new
        worker's cumulative counts start clean. The reader's reset
        detection handles the discontinuity."""
        self._ensure()[slot] = 0
        self._gauges[slot] = 0

    def take_deltas(self) -> np.ndarray:
        """Owner-side interval read: per-stage counts observed fleet-wide
        since the previous call, summed over slots -> (n_stages, NBUCKETS).
        A slot whose counts DECREASED anywhere was reset (respawn); its
        fresh cumulative row counts as that interval's delta."""
        cur = self.read()
        if self._prev is None:
            delta = cur
        else:
            delta = cur - self._prev
            reset = (delta < 0).any(axis=(1, 2))
            delta[reset] = cur[reset]
        self._prev = cur
        return delta.sum(axis=0)

    def close(self) -> None:
        if self._shm is None:
            return
        self._final = self._arr.copy()
        self._arr = None
        self._gauges = None
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
        self._shm = None
