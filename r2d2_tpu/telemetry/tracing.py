"""Cross-plane distributed tracing (ISSUE 19 tentpole, part a).

Two causal paths get per-hop wall-clock stamps, both behind the
``telemetry.tracing_enabled`` kill switch (off => records, wire frames,
and block schemas byte-identical to the pre-tracing system):

  * **Serving requests** — every Nth exchange
    (``telemetry.trace_sample_every``) attaches a ``trace`` dict to its
    ``Request`` objects: ``{"id", "t_submit_wall", "t_send_wall",
    "t_recv_wall"}``. The dict rides the pickle rungs for free (plain
    dataclasses pickle their ``__dict__``, so an absent attribute keeps
    untraced frames byte-identical) and two gated i64/f64 fields on the
    shm request layout (serve/transport.py ``request_layout``). The
    server decomposes the round trip into transit / queue_wait /
    forward / reply hops (``ServeTrace``, folded into the ``serving``
    record block as a ``trace`` sub-block).

  * **Experience blocks** — every Nth emitted block carries
    ``Block.trace_ms``, a trailing None-default leaf (the PR-5
    ``weight_version`` / PR-10 ``lane`` treatment: absent => old blocks
    and untraced runs load unchanged; present => it rides ``addw``
    socket frames via the omit-None ``_block_fields`` contract). The
    replay service strips the leaf before any device commit (the AOT
    ``replay_add_many`` avals never see it) and mirrors it into the
    ring accountant's host-side slot arrays, through spill
    demote/promote and snapshot capture/restore. At sample time the
    learner looks the stamps back up by slot and feeds
    ``ExperienceTrace`` — the periodic record's ``trace`` block with
    the end-to-end **env-step -> gradient** latency histogram and its
    per-hop breakdown (emit->ingest, ingest->sample, sample->train).

Timestamps are wall-clock **milliseconds mod 2^31** stored as int32
(fits the Block's int32 stamp convention; -1 = untraced, matching the
lane / weight_version sentinel). Hop latencies difference mod 2^31, so
the ~24-day wrap cannot produce negative hops.
"""

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from r2d2_tpu.telemetry.histogram import NBUCKETS, bucket_index, summarize

# Untraced sentinel for int32 stamp fields (slot mirrors, shm fields,
# Block.trace_ms when a run traces only a sampled fraction).
UNTRACED = -1
_WRAP = 2 ** 31


def now_ms() -> int:
    """Wall-clock milliseconds mod 2^31 (int32-safe; see module doc)."""
    return int(time.time() * 1e3) % _WRAP


def hop_ms(start_ms: int, end_ms: int) -> Optional[float]:
    """Latency between two mod-2^31 stamps; None when either side is
    untraced. The mod-difference keeps a wrap mid-hop non-negative."""
    if start_ms < 0 or end_ms < 0:
        return None
    return float((end_ms - start_ms) % _WRAP)


def new_request_trace(req_id: int) -> dict:
    """The serving-side trace payload attached to a sampled Request."""
    return {"id": int(req_id), "t_submit_wall": time.time()}


class _Hist:
    """One hop's thread-safe 64-bucket log histogram (ms-domain values
    observed as seconds into the shared layout, so ``summarize`` reports
    the usual p50/p95/p99 in ms)."""

    __slots__ = ("_lock", "counts")

    def __init__(self):
        self._lock = threading.Lock()
        self.counts = np.zeros(NBUCKETS, np.int64)

    def observe_ms(self, ms: float) -> None:
        i = bucket_index(ms / 1e3)
        with self._lock:
            self.counts[i] += 1

    def take(self) -> np.ndarray:
        with self._lock:
            out = self.counts.copy()
            self.counts[:] = 0
        return out


# Experience-path hops, in pipeline order. ``e2e`` is emit->train — the
# acceptance criterion's env-step->gradient latency.
EXPERIENCE_HOPS = ("emit_to_ingest", "ingest_to_sample", "sample_to_train")
# Serving-path hops: client submit->send (client-side routing/queueing),
# send->server receive (wire transit), receive->dispatch (micro-batch
# fill wait), the jitted forward, and the reply scatter+send.
SERVE_HOPS = ("route", "transit", "queue_wait", "forward", "reply")


class ExperienceTrace:
    """Learner-side aggregator for the experience lineage path. Fed at
    sample time with the (emit_ms, ingest_ms) pairs the service looked
    up for the drawn batch, and at train-consumption time with the
    sample tokens; consumed once per record by ``interval_block``."""

    def __init__(self, sample_every: int = 1):
        self.sample_every = max(int(sample_every), 1)
        self._hops = {name: _Hist() for name in EXPERIENCE_HOPS}
        self._e2e = _Hist()
        self._lock = threading.Lock()
        self._sampled = 0

    def on_sample(self, pairs: Sequence[Tuple[int, int]]
                  ) -> Optional[List[int]]:
        """Record emit->ingest and ingest->sample for every traced row
        of one sampled batch; returns the emit stamps as the token the
        train-consumption hook closes out (None when nothing was
        traced, so untraced batches cost one truthiness check)."""
        if not pairs:
            return None
        sample_ms = now_ms()
        emits: List[int] = []
        for emit_ms, ingest_ms in pairs:
            d = hop_ms(emit_ms, ingest_ms)
            if d is not None:
                self._hops["emit_to_ingest"].observe_ms(d)
            d = hop_ms(ingest_ms, sample_ms)
            if d is not None:
                self._hops["ingest_to_sample"].observe_ms(d)
            if emit_ms >= 0:
                emits.append(int(emit_ms))
        with self._lock:
            self._sampled += len(pairs)
        return [sample_ms] + emits if emits else None

    def on_train(self, token: Optional[List[int]]) -> None:
        """Close out one batch's traced rows at train consumption:
        sample->train for the batch, emit->train (e2e) per row."""
        if not token:
            return
        train_ms = now_ms()
        sample_ms, emits = token[0], token[1:]
        d = hop_ms(sample_ms, train_ms)
        if d is not None:
            self._hops["sample_to_train"].observe_ms(d)
        for emit_ms in emits:
            d = hop_ms(emit_ms, train_ms)
            if d is not None:
                self._e2e.observe_ms(d)

    def interval_block(self) -> Optional[dict]:
        """The periodic record's ``trace`` block; consumes the interval
        (the TrainMetrics provider contract). None when the interval
        traced nothing — the key is then omitted."""
        e2e = summarize(self._e2e.take())
        hops = {}
        for name in EXPERIENCE_HOPS:
            s = summarize(self._hops[name].take())
            if s is not None:
                hops[name] = s
        with self._lock:
            sampled = self._sampled
            self._sampled = 0
        if e2e is None and not hops and sampled == 0:
            return None
        block: dict = {"sampled": sampled}
        if e2e is not None:
            block["e2e_experience_latency"] = e2e
        if hops:
            block["hops"] = hops
        return block


class ServeTrace:
    """Server-side aggregator for the serving request path. Attached to
    ``ServingStats`` (``stats.trace``) when tracing is on; the serving
    record block then carries a ``trace`` sub-block — absent it, the
    block is byte-identical to the untraced schema."""

    def __init__(self):
        self._hops = {name: _Hist() for name in SERVE_HOPS}
        self._lock = threading.Lock()
        self._requests = 0

    def on_request(self, trace: dict, queue_wait_s: float) -> None:
        """Per traced request at dispatch: client-side route hop
        (submit->send), wire transit (send->receive), and the
        micro-batch fill wait (receive->dispatch, measured on the
        server's monotonic clock — exact, no cross-process skew)."""
        t_submit = trace.get("t_submit_wall")
        t_send = trace.get("t_send_wall")
        t_recv = trace.get("t_recv_wall")
        if t_submit is not None and t_send is not None:
            self._hops["route"].observe_ms(max(t_send - t_submit, 0.0) * 1e3)
        start = t_send if t_send is not None else t_submit
        if start is not None and t_recv is not None:
            self._hops["transit"].observe_ms(max(t_recv - start, 0.0) * 1e3)
        self._hops["queue_wait"].observe_ms(max(queue_wait_s, 0.0) * 1e3)
        with self._lock:
            self._requests += 1

    def on_batch(self, forward_s: float, reply_s: float) -> None:
        """Per dispatched batch containing >= 1 traced request."""
        self._hops["forward"].observe_ms(max(forward_s, 0.0) * 1e3)
        self._hops["reply"].observe_ms(max(reply_s, 0.0) * 1e3)

    def interval_block(self) -> Optional[dict]:
        hops = {}
        for name in SERVE_HOPS:
            s = summarize(self._hops[name].take())
            if s is not None:
                hops[name] = s
        with self._lock:
            requests = self._requests
            self._requests = 0
        if not hops and requests == 0:
            return None
        return {"requests": requests, "hops": hops}


def proc_header(plane: str, lease: Optional[int] = None) -> dict:
    """Process-identity header + clock anchor for a per-process metrics
    row (ISSUE 19 satellite: cli/serve.py / fleet/service_main.py rows).
    The wall/mono pair is the PR-11 ``clock_anchor`` generalized to
    non-rank processes: the tower join and the Perfetto merge align
    streams on it without assuming a shared monotonic clock."""
    import os
    head = {"plane": plane, "pid": os.getpid(),
            "clock_anchor": {"wall": time.time(),
                             "mono": time.monotonic()}}
    if lease is not None:
        head["lease"] = int(lease)
    return head
