"""XLA compilation telemetry (ISSUE 7): compile counts/wall-time and
post-warm-up retrace detection.

Recompiles are this stack's quietest failure mode: a jitted function
handed a new abstract shape silently recompiles (~1.5 s each on the CPU
container, far more over a TPU tunnel), and the PR-2 ingestion saga
showed a single lazy mid-run ``replay_add_many`` compile backing the
feeder up enough to park the whole actor fleet. Nothing surfaced it —
the symptom was a throughput dip a human had to correlate by hand.

Two capture channels, both public-ish and cheap:

  * ``jax.monitoring`` duration events
    (``/jax/core/compile/backend_compile_duration``): every backend
    compile's wall time, no function identity — the aggregate
    count/time counters.
  * the ``jax._src.interpreters.pxla`` DEBUG log line
    ``"Compiling <fn> with global shapes and types [avals]"``: function
    NAME + ABSTRACT SHAPES per compile. The monitor attaches a logging
    handler at DEBUG and stops propagation (restored at uninstall) so
    capture costs no stderr spam; WARNING+ records are re-emitted to the
    parent so real jax warnings stay visible.

Retrace = a compile AFTER :meth:`CompileMonitor.mark_warm` of a function
name seen before with a DIFFERENT aval signature — exactly the
"same fn, new shapes" event that parks actors. Flagged with the
offending avals in the record's ``resources.compile`` block, and counted
per interval so the sentinel's ``retrace_storm`` rule can fire on a
burst. Late FIRST compiles (a new function after warm-up, e.g. an
odd-size stager bucket) count as ``late_compiles`` — noteworthy, but not
a retrace.

One monitor per process (module-level active slot): jax.monitoring has
no per-listener unregister, so ONE dispatching listener is registered on
first install and routes to whichever monitor is active.
"""

import logging
import re
import threading
import time
from typing import Any, Dict, List, Optional

_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"
_PXLA_LOGGER = "jax._src.interpreters.pxla"
# "Compiling <name> with global shapes and types [<avals>]. Argument ..."
_COMPILING_RE = re.compile(
    r"Compiling ([^\s]+) (?:with global shapes and types |for pjit )?"
    r"\[?(.*?)\]?\.? Argument", re.DOTALL)

_ACTIVE: Optional["CompileMonitor"] = None
_LISTENER_REGISTERED = False
# reentrant: install() displaces a previous owner by calling ITS
# uninstall() while already holding the lock
_INSTALL_LOCK = threading.RLock()


def _duration_listener(event: str, duration: float, **kwargs) -> None:
    mon = _ACTIVE
    if mon is not None and event == _COMPILE_DURATION_EVENT:
        mon._on_backend_compile(duration)


class _CompileLogHandler(logging.Handler):
    """Captures the pxla compile lines for the active monitor; WARNING+
    records pass through to the 'jax' parent handler so suppressing
    propagation (needed to keep DEBUG capture off stderr) loses
    nothing user-visible."""

    def emit(self, record: logging.LogRecord) -> None:
        mon = _ACTIVE
        if mon is not None:
            try:
                msg = record.getMessage()
            except Exception:
                return
            m = _COMPILING_RE.search(msg)
            if m is not None:
                mon._on_compile(m.group(1), m.group(2))
        if record.levelno >= logging.WARNING:
            logging.getLogger("jax").handle(record)


def active_monitor() -> Optional["CompileMonitor"]:
    """The process's currently-installed monitor, or None. Orchestrating
    loops check this before installing: compile events are process-global,
    so the FIRST stack in a multiplayer process owns the monitor and later
    stacks must not displace it (install() deactivates the previous
    owner)."""
    return _ACTIVE


class CompileMonitor:
    """Per-process compile/retrace tracker. ``install()`` activates the
    capture channels; ``uninstall()`` restores the logger exactly (tests
    install/uninstall repeatedly). Counters are cumulative; the record
    block reads per-interval deltas via :meth:`interval_summary`."""

    MAX_RETRACE_LOG = 32      # retained retrace events (newest kept)

    def __init__(self):
        self._lock = threading.Lock()
        self.compiles = 0              # backend compiles (monitoring event)
        self.compile_time_s = 0.0
        self.traced_compiles = 0       # named compiles (pxla log line)
        self.retraces = 0
        self.late_compiles = 0         # post-warm first compile of a new fn
        self.warm = False
        self._signatures: Dict[str, set] = {}
        self._retrace_log: List[dict] = []
        self._prev = (0, 0.0, 0, 0)    # interval take baseline
        self._handler: Optional[_CompileLogHandler] = None
        self._saved_logger_state: Optional[tuple] = None

    # -- capture-channel callbacks --

    def _on_backend_compile(self, duration: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compile_time_s += float(duration)

    def _on_compile(self, name: str, avals: str) -> None:
        with self._lock:
            self.traced_compiles += 1
            seen = self._signatures.setdefault(name, set())
            is_retrace = self.warm and bool(seen) and avals not in seen
            if self.warm and not seen:
                self.late_compiles += 1
            seen.add(avals)
            if is_retrace:
                self.retraces += 1
                self._retrace_log.append(
                    {"fn": name, "avals": avals[:400], "t": time.time()})
                del self._retrace_log[:-self.MAX_RETRACE_LOG]

    # -- lifecycle --

    def install(self) -> "CompileMonitor":
        global _ACTIVE, _LISTENER_REGISTERED
        with _INSTALL_LOCK:
            if _ACTIVE is self:
                return self
            if _ACTIVE is not None:
                _ACTIVE.uninstall()
            if not _LISTENER_REGISTERED:
                import jax.monitoring
                jax.monitoring.register_event_duration_secs_listener(
                    _duration_listener)
                _LISTENER_REGISTERED = True
            logger = logging.getLogger(_PXLA_LOGGER)
            self._saved_logger_state = (logger.level, logger.propagate)
            self._handler = _CompileLogHandler(level=logging.DEBUG)
            logger.addHandler(self._handler)
            logger.setLevel(logging.DEBUG)
            # propagation off: the 'jax' parent has a stderr handler that
            # would print every DEBUG compile line; the handler re-emits
            # WARNING+ records there itself
            logger.propagate = False
            _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        with _INSTALL_LOCK:
            if _ACTIVE is not self:
                return
            logger = logging.getLogger(_PXLA_LOGGER)
            if self._handler is not None:
                logger.removeHandler(self._handler)
                self._handler = None
            if self._saved_logger_state is not None:
                logger.setLevel(self._saved_logger_state[0])
                logger.propagate = self._saved_logger_state[1]
                self._saved_logger_state = None
            _ACTIVE = None

    def mark_warm(self) -> None:
        """Declare warm-up over: every fn compiled so far is baseline;
        further compiles of known fns with new avals are retraces.
        Idempotent — call it at the first log boundary where training has
        started (the train program has compiled by then)."""
        with self._lock:
            self.warm = True

    # -- reads --

    def totals(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "compiles_total": self.compiles,
                "compile_time_s_total": round(self.compile_time_s, 3),
                "retraces_total": self.retraces,
                "late_compiles": self.late_compiles,
                "warm": self.warm,
            }
            if self._retrace_log:
                out["last_retrace"] = dict(self._retrace_log[-1])
            return out

    def interval_summary(self) -> Dict[str, Any]:
        """totals() plus per-interval deltas (consumes the interval) —
        the record's ``resources.compile`` block; ``retraces_interval``
        is what the retrace_storm alert rule reads."""
        with self._lock:
            cur = (self.compiles, self.compile_time_s, self.retraces,
                   self.late_compiles)
            pc, pt, pr, pl = self._prev
            self._prev = cur
            out = {
                "compiles": cur[0] - pc,
                "compile_time_s": round(cur[1] - pt, 3),
                "retraces_interval": cur[2] - pr,
                "late_compiles_interval": cur[3] - pl,
                "compiles_total": cur[0],
                "compile_time_s_total": round(cur[1], 3),
                "retraces_total": cur[2],
                "late_compiles": cur[3],
                "warm": self.warm,
            }
            if self._retrace_log:
                out["last_retrace"] = dict(self._retrace_log[-1])
            return out

    def functions_seen(self) -> Dict[str, int]:
        """{fn name: distinct aval signatures} — the tracked universe."""
        with self._lock:
            return {k: len(v) for k, v in self._signatures.items()}


def aot_coverage(expected: List[int], compiled: List[int]) -> dict:
    """AOT-precompile coverage report (the stager's pow2 add_many
    buckets): which batch sizes have executables vs which would compile
    lazily mid-run — the exact hazard the PR-2 precompile exists to
    prevent; a non-empty ``missing`` list is the regression signal."""
    expected = sorted(set(int(x) for x in expected))
    compiled = sorted(set(int(x) for x in compiled))
    return {"expected": expected, "compiled": compiled,
            "missing": [s for s in expected if s not in compiled],
            "extra": [s for s in compiled if s not in expected]}
