"""Telemetry facade: stage timers + span tracer + publication, one object
per process (the learner process shares one across its threads; each
spawned actor process builds its own bound to a TelemetryBoard slot).

Kill-switch: ``telemetry.enabled=false`` turns every entry point into a
cheap no-op (one attribute check); the module-level NULL_TELEMETRY serves
call sites that received no telemetry at all, so instrumented code never
branches on None. Overhead with telemetry ON is budgeted < 2% env-steps/s
(tools/e2e_bench.py --telemetry-ab measures it; PERF.md records the A/B).
"""

import json
import os
import threading
import time
from typing import Dict, Optional

import numpy as np

from r2d2_tpu.telemetry.histogram import NBUCKETS, summarize
from r2d2_tpu.telemetry.spans import SpanTracer

# The canonical pipeline stages — ONE fixed, ordered list shared by local
# timers, the shm board layout, and the aggregated record, so counts merge
# elementwise everywhere. Actor-side stages are published through the
# board by process actors (thread actors observe straight into the
# learner's local timers); learner-side stages are always local.
STAGES = (
    "actor/env_step",             # venv/env .step per tick
    "actor/forward",              # jitted policy forward per tick
    "actor/block_emit",           # whole block sink call (incl. queue wait)
    "actor/queue_put",            # time inside put_patient (back-pressure)
    "actor/weight_sync",          # weight_poll + policy.update_params
    "actor/act_scan",             # fused on-device acting segment dispatch
    "ingest/ring_get",            # feeder drain: shm ring pop / queue get
    "ingest/stage",               # stager: stack + host->device + enqueue
    "ingest/commit",              # replay_add / add_many commit dispatch
    "learner/sample",             # host-placement prefetch sample
    "learner/train_dispatch",     # fused-step dispatch (host-side)
    "learner/device_sync",        # flush_metrics device readback
    "learner/priority_writeback", # host-placement async priority update
    "weights/publish",            # learner -> weight service publish
    "lockstep/dispatch",          # multihost: blocked in the psum collective
    "lockstep/step",              # multihost: one whole lockstep iteration
    "serve/enqueue",              # serving: request arrival -> dispatch
    "serve/batch_wait",           # serving: oldest request's fill wait
    "serve/forward",              # serving: jitted micro-batch forward
    "serve/reply",                # serving: state scatter + reply send
    "recovery/snapshot_capture",  # replay snapshot host cut (train path
                                  # cost; the write runs off-thread)
)
STAGE_INDEX: Dict[str, int] = {name: i for i, name in enumerate(STAGES)}


class StageTimers:
    """Per-process cumulative histogram matrix, (len(STAGES), NBUCKETS)
    int64. ``observe`` is the hot entry point: one bucket_index + one
    locked increment (stage cadence is per-tick at worst, so the lock is
    uncontended in practice; it exists because the stager, write-back,
    actor threads, and the main loop all observe into one matrix)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._m = np.zeros((len(STAGES), NBUCKETS), np.int64)
        self._prev = np.zeros_like(self._m)

    def observe(self, stage: str, seconds: float) -> None:
        from r2d2_tpu.telemetry.histogram import bucket_index
        row = STAGE_INDEX[stage]          # typo'd stage -> KeyError, loudly
        with self._lock:
            self._m[row, bucket_index(seconds)] += 1

    def cumulative(self) -> np.ndarray:
        with self._lock:
            return self._m.copy()

    def take(self) -> np.ndarray:
        """Counts observed since the previous take() -> (stages, buckets)."""
        with self._lock:
            cur = self._m.copy()
        delta = cur - self._prev
        self._prev = cur
        return delta


def summarize_matrix(matrix: np.ndarray) -> Dict[str, Dict[str, float]]:
    """{stage: {count, p50_ms, p95_ms, p99_ms}} for every stage with data."""
    out = {}
    for i, name in enumerate(STAGES):
        s = summarize(matrix[i])
        if s is not None:
            out[name] = s
    return out


class Telemetry:
    """One per process. ``board``/``slot``: publication target for worker
    processes (the owner side instead passes the board to
    ``interval_summary`` via ``attach_board``)."""

    def __init__(self, enabled: bool = True, ring_size: int = 4096,
                 flush_interval_s: float = 5.0, spans: bool = True,
                 name: str = "main", board=None, slot: Optional[int] = None,
                 resource_gauges: bool = False):
        self.enabled = enabled
        self.name = name
        self.flush_interval_s = flush_interval_s
        self.timers = StageTimers()
        self.spans = SpanTracer(ring_size, enabled=enabled and spans)
        self._board = board          # worker side: publish target
        self._slot = slot
        # worker side (ISSUE 7): publish this process's RSS / cumulative
        # CPU into the board's gauge columns on the same flush cadence
        self._resource_gauges = resource_gauges
        self._agg_board = None       # owner side: aggregation source
        self._spans_path: Optional[str] = None
        self._drain_stop: Optional[threading.Event] = None
        self._drain_thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, cfg, name: str = "main", board=None,
                    slot: Optional[int] = None) -> "Telemetry":
        """Build from a Config (duck-typed: anything carrying a
        ``telemetry`` section with the TelemetryConfig fields)."""
        t = cfg.telemetry
        return cls(enabled=t.enabled, ring_size=t.ring_size,
                   flush_interval_s=t.flush_interval_s, spans=t.spans,
                   name=name, board=board, slot=slot,
                   resource_gauges=getattr(t, "resources_enabled", False))

    # -- hot-path entry points --

    def observe(self, stage: str, seconds: float) -> None:
        if self.enabled:
            self.timers.observe(stage, seconds)

    def record_span(self, name: str, t_start: float, t_end: float,
                    tags: Optional[dict] = None) -> None:
        self.spans.record(name, t_start, t_end, tags)

    def span(self, name: str, **tags):
        return self.spans.span(name, **tags)

    # -- publication / aggregation --

    def attach_board(self, board) -> None:
        """Owner side: fold this board's per-interval deltas into
        interval_summary() (the learner aggregating its actor fleet)."""
        self._agg_board = board

    def flush(self) -> None:
        """Publish cumulative counts to the board (worker side) and append
        drained spans to the spans file, if configured."""
        if not self.enabled:
            return
        if self._board is not None and self._slot is not None:
            self._board.publish(self._slot, self.timers.cumulative())
            if self._resource_gauges and hasattr(self._board,
                                                 "publish_gauges"):
                from r2d2_tpu.telemetry.resources import host_usage
                u = host_usage()
                self._board.publish_gauges(
                    self._slot, u["rss_bytes"] or 0,
                    int(u["cpu_s"] * 1e3))
        if self._spans_path:
            events = self.spans.drain()
            if events:
                with open(self._spans_path, "a") as f:
                    for ev in events:
                        ev["pid"] = self.name
                        f.write(json.dumps(ev) + "\n")

    def interval_summary(self) -> Dict[str, Dict[str, float]]:
        """The aggregated per-interval record: local observations since
        the last call, merged with the attached board's fleet-wide deltas.
        Consumes the interval — call once per log boundary."""
        if not self.enabled:
            return {}
        matrix = self.timers.take()
        if self._agg_board is not None:
            matrix = matrix + self._agg_board.take_deltas()
        return summarize_matrix(matrix)

    # -- background drain --

    def start_drain(self, spans_path: Optional[str] = None,
                    append: bool = False) -> None:
        """Start the off-thread drain loop: every flush_interval_s,
        publish board counts and append spans to ``spans_path`` (JSONL).
        ``append=False`` truncates at start (a fresh run's file);
        ``append=True`` keeps what's there — respawned actor processes
        and resumed runs must not wipe the history a post-mortem needs."""
        if not self.enabled or self._drain_thread is not None:
            return
        if spans_path and self.spans.enabled:
            os.makedirs(os.path.dirname(spans_path) or ".", exist_ok=True)
            if not append:
                open(spans_path, "w").close()
            self._spans_path = spans_path
        self._drain_stop = threading.Event()

        def loop():
            while not self._drain_stop.wait(self.flush_interval_s):
                try:
                    self.flush()
                except (OSError, ValueError):
                    # a torn-down board/file at shutdown must not kill the
                    # drain thread loudly; the final flush in close() is
                    # best-effort too
                    pass

        self._drain_thread = threading.Thread(
            target=loop, daemon=True, name=f"telemetry-drain-{self.name}")
        self._drain_thread.start()

    def close(self) -> None:
        if self._drain_stop is not None:
            self._drain_stop.set()
            self._drain_thread.join(timeout=2.0)
            self._drain_thread = None
            self._drain_stop = None
        try:
            self.flush()
        except (OSError, ValueError):
            pass


NULL_TELEMETRY = Telemetry(enabled=False, spans=False, name="null")
