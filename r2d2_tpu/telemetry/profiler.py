"""One owner for ``jax.profiler`` trace lifecycles.

The orchestrator used to inline start_trace/stop_trace with two separate
stop sites; an exception raised between the start and the first stop
skipped the in-loop stop but could still reach the second, stopping a
dead trace (and conversely a propagating exception could leave the trace
running). ProfilerCapture makes start/stop idempotent and gives the run
loop a single ``poll(now)`` to end a bounded capture — shared by the
first-interval capture, the mid-run ``runtime.profile_at_step`` /
SIGUSR2 triggers (runtime/orchestrator.py), and the step profiler
(tools/profile_step.py via ``trace``).
"""

import logging
import time
from contextlib import contextmanager
from typing import Optional


class ProfilerCapture:
    def __init__(self):
        self.active = False
        self.captures = 0
        self._until: Optional[float] = None
        self.out_dir: Optional[str] = None

    def start(self, out_dir: str, duration_s: Optional[float] = None) -> bool:
        """Begin a capture; returns False (and changes nothing) when one
        is already running. ``duration_s`` arms poll()-driven stop."""
        if self.active:
            return False
        import jax
        try:
            jax.profiler.start_trace(out_dir)
        except RuntimeError as e:
            # another trace is live in this process (e.g. an outer tool's
            # capture) — skip rather than corrupt it
            logging.getLogger(__name__).warning(
                "profiler capture skipped: %s", e)
            return False
        self.active = True
        self.out_dir = out_dir
        self._until = (time.time() + duration_s
                       if duration_s is not None else None)
        return True

    def poll(self, now: Optional[float] = None) -> bool:
        """Stop a bounded capture whose window elapsed; returns True if a
        capture was stopped."""
        if not self.active or self._until is None:
            return False
        if (time.time() if now is None else now) < self._until:
            return False
        self.stop()
        return True

    def stop(self) -> None:
        """Idempotent: stopping with no active capture is a no-op."""
        if not self.active:
            return
        import jax
        self.active = False        # cleared first: stop_trace may raise
        self._until = None
        self.captures += 1
        try:
            jax.profiler.stop_trace()
        except RuntimeError as e:
            logging.getLogger(__name__).warning(
                "profiler stop_trace failed: %s", e)


@contextmanager
def trace(out_dir: str):
    """Context-managed capture for tools: the trace always stops exactly
    once, raise or return."""
    cap = ProfilerCapture()
    cap.start(out_dir)
    try:
        yield cap
    finally:
        cap.stop()
