"""One owner for ``jax.profiler`` trace lifecycles.

The orchestrator used to inline start_trace/stop_trace with two separate
stop sites; an exception raised between the start and the first stop
skipped the in-loop stop but could still reach the second, stopping a
dead trace (and conversely a propagating exception could leave the trace
running). ProfilerCapture makes start/stop idempotent and gives the run
loop a single ``poll(now)`` to end a bounded capture — shared by the
first-interval capture, the mid-run ``runtime.profile_at_step`` /
SIGUSR2 triggers (runtime/orchestrator.py), and the step profiler
(tools/profile_step.py via ``trace``).
"""

import logging
import os
import signal
import threading
import time
from contextlib import contextmanager
from typing import Optional

_UNSET = object()   # a previous signal handler can legitimately BE None


class ProfilerCapture:
    def __init__(self):
        self.active = False
        self.captures = 0
        self._until: Optional[float] = None
        self.out_dir: Optional[str] = None

    def start(self, out_dir: str, duration_s: Optional[float] = None) -> bool:
        """Begin a capture; returns False (and changes nothing) when one
        is already running. ``duration_s`` arms poll()-driven stop."""
        if self.active:
            return False
        import jax
        try:
            jax.profiler.start_trace(out_dir)
        except RuntimeError as e:
            # another trace is live in this process (e.g. an outer tool's
            # capture) — skip rather than corrupt it
            logging.getLogger(__name__).warning(
                "profiler capture skipped: %s", e)
            return False
        self.active = True
        self.out_dir = out_dir
        self._until = (time.time() + duration_s
                       if duration_s is not None else None)
        return True

    def poll(self, now: Optional[float] = None) -> bool:
        """Stop a bounded capture whose window elapsed; returns True if a
        capture was stopped."""
        if not self.active or self._until is None:
            return False
        if (time.time() if now is None else now) < self._until:
            return False
        self.stop()
        return True

    def stop(self) -> None:
        """Idempotent: stopping with no active capture is a no-op."""
        if not self.active:
            return
        import jax
        self.active = False        # cleared first: stop_trace may raise
        self._until = None
        self.captures += 1
        try:
            jax.profiler.stop_trace()
        except RuntimeError as e:
            logging.getLogger(__name__).warning(
                "profiler stop_trace failed: %s", e)


class CaptureTriggers:
    """The three standard mid-run capture triggers around ONE
    ProfilerCapture — shared by the host orchestrator loop and the fused
    anakin loop (ISSUE 9) so the subtle rules exist exactly once:

      * first-interval capture when ``runtime.profile_dir`` is set;
      * one-shot ``runtime.profile_at_step``: disarms only on a REAL
        start — ``ProfilerCapture.start`` refuses while another capture
        is live, and the knob's capture must then fire once it ends,
        not be silently lost;
      * SIGUSR2 on demand: the handler only flags (jax.profiler is not
        async-signal-safe; the loop starts the capture at its next
        ``poll``), and a request stays pending across a live window for
        the same reason; the previous handler is restored exactly at
        ``uninstall`` (including a ``None``/not-from-Python one).

    Captures land in ``runtime.profile_dir`` or ``{save_dir}/xprof`` —
    where telemetry/traceparse.py expects them.
    """

    def __init__(self, runtime_cfg):
        self.prof = ProfilerCapture()
        self.out_dir = runtime_cfg.profile_dir or os.path.join(
            runtime_cfg.save_dir or ".", "xprof")
        self.window = min(runtime_cfg.log_interval, 30.0)
        self._first_interval_dir = runtime_cfg.profile_dir
        self._at_step = runtime_cfg.profile_at_step
        self._armed = self._at_step > 0
        self._request = threading.Event()
        self._prev_usr2 = _UNSET

    def install(self) -> "CaptureTriggers":
        """Install the SIGUSR2 flag handler (main thread only — signal
        rules); safe no-op anywhere else. Returns self."""
        if threading.current_thread() is threading.main_thread():
            def _on_usr2(signum, frame):
                self._request.set()
            try:
                self._prev_usr2 = signal.signal(signal.SIGUSR2, _on_usr2)
            except (ValueError, OSError, AttributeError):
                self._prev_usr2 = _UNSET
        return self

    def start_first_interval(self) -> None:
        """The legacy profile_dir-armed capture of the first training
        interval; no-op when the knob is unset."""
        if self._first_interval_dir:
            self.prof.start(self._first_interval_dir, self.window)

    def poll(self, now: float, training_steps: int) -> None:
        """One per-loop tick: end an elapsed window, fire the one-shot
        step trigger, service a pending SIGUSR2 request."""
        self.prof.poll(now)
        if self._armed and training_steps >= self._at_step:
            if self.prof.start(self.out_dir, self.window):
                self._armed = False
        if self._request.is_set():
            if self.prof.start(self.out_dir, self.window):
                self._request.clear()

    def uninstall(self) -> None:
        """Stop any live capture (idempotent) and restore the previous
        SIGUSR2 handler exactly."""
        self.prof.stop()
        if self._prev_usr2 is not _UNSET:
            try:
                signal.signal(signal.SIGUSR2,
                              self._prev_usr2 or signal.SIG_DFL)
            except (ValueError, OSError, TypeError):
                pass
            self._prev_usr2 = _UNSET


@contextmanager
def trace(out_dir: str):
    """Context-managed capture for tools: the trace always stops exactly
    once, raise or return."""
    cap = ProfilerCapture()
    cap.start(out_dir)
    try:
        yield cap
    finally:
        cap.stop()
