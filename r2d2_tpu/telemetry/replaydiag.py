"""Replay & data-pathology observability (ISSUE 10) — the fifth
telemetry pillar: what the prioritized recurrent replay actually FEEDS
the learner, fused into the jitted sample/update path.

After four pillars the stack can see how fast data moves (PR 4), how
stale it is (PR 5), and what it costs (PR 9) — but not what the sum-tree
prioritizes, which sequences get learned from versus evicted unseen, or
which ε-ladder lanes produce the learning signal. Three instruments,
behind ``telemetry.replay_diag_enabled`` (off ⇒ records byte-identical
to the PR9 schema — the established kill-switch contract):

  * **sum-tree / priority health** — a device-side histogram of the live
    leaf priorities on the shared 64-bucket log layout
    (telemetry/histogram.py — the SAME bucketize-scatter the learning
    diagnostics use), plus collapse indicators derived from one
    5-element moment vector [active, Σp, Σp², max, count-at-max]:
    effective sample size of the sampling distribution
    (ESS = (Σp)²/Σp²), max/mean leaf ratio, and the
    fraction-at-max-priority. Computed under ``lax.cond`` every
    ``telemetry.replay_diag_interval`` learner steps inside the existing
    step factories; ``replay/host_replay.py`` is the numpy twin for host
    placement (parity-tested).
  * **per-slot sample-lifetime accounting** — ReplayState carries an
    in-graph (N,) sample-count ring incremented at the sample gather
    (``note_sampled``) and read at overwrite in ``replay_add_many``, so
    each eviction accumulates the retired slot's lifetime (times sampled
    before overwrite, age at eviction in ring adds, final priority) and
    the learner reports the **never-sampled-before-eviction fraction** —
    the single best "is replay sized and prioritized right" number.
  * **lane provenance** — blocks carry their ε-ladder lane index
    end-to-end (the PR5 staleness-stamp pattern: LocalBuffer loops stamp
    the relative lane, ``instrument_block_sink`` offsets to the global
    ladder, the anakin paths stamp in-graph), and every sampled batch's
    per-lane composition lands in a (lanes+1,)-bincount — Ape-X's
    exploration ladder measured at the point of LEARNING, not just at
    acting.

Under the dp-sharded step the per-shard views are ``all_gather``-ed
(``rd/shard_*`` keys, leading dp axis) and the host aggregator derives
both per-shard rows and the merged view from them; the single-chip path
emits the unprefixed keys directly. :class:`ReplayDiagAggregator` builds
the periodic record's ``replay_diag`` block; 4 stock alert rules
(priority_collapse, priority_saturation, never_sampled_growth,
lane_starvation) watch it in telemetry/alerts.py.
"""

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from r2d2_tpu.telemetry.histogram import NBUCKETS, value_counts, value_summary

# near-max tolerance for the count-at-max indicator: f32 tree priorities
# that round to the max still count as "at max"
_AT_MAX_RTOL = 1e-6


@dataclass(frozen=True)
class ReplayDiag:
    """Static (hashable) replay-diagnostics spec closed over by the jitted
    step factories — the LearningDiag pattern. ``None`` in the factories
    means the pillar is OFF and (together with ``ReplaySpec.replay_diag``
    False) the compiled step is byte-identical to the pre-diagnostics
    program."""

    interval: int = 50        # learner steps between sum-tree snapshots
    lanes: int = 0            # global ε-ladder width (lane bincount size)

    @classmethod
    def from_config(cls, cfg) -> Optional["ReplayDiag"]:
        """The ONE gating rule: replay diagnostics require BOTH the master
        telemetry switch and the pillar kill switch — the same resolution
        ReplaySpec.from_config applies to the ring-state allocation."""
        t = cfg.telemetry
        if not (t.enabled and t.replay_diag_enabled):
            return None
        if cfg.actor.on_device:
            lanes = cfg.actor.anakin_lanes
        else:
            # the GLOBAL ladder width: multihost fleets stamp global lane
            # indices spanning every process's workers (the same
            # process_count * num_actors layout vector_lane_epsilons
            # spreads ε over), so the bincount must cover all of them —
            # a rank-local width would route every remote rank's stamps
            # to the unknown bucket
            procs = (max(cfg.mesh.num_processes, 1)
                     if cfg.mesh.multihost else 1)
            lanes = procs * cfg.actor.num_actors * cfg.actor.envs_per_actor
        return cls(interval=t.replay_diag_interval, lanes=lanes)


# ---------------------------------------------------------------------------
# Device-side pieces (jnp; traced into the fused step)


def tree_health_moments(tree, num_layers: int):
    """(moments, hist) of the tree's LIVE leaves: moments is the (5,) f32
    vector [active, Σp, Σp², max, count-at-max] every derived collapse
    indicator comes from (host side, :func:`derive_tree_stats`), hist the
    (64,) leaf-priority histogram on the shared log layout. Zero-priority
    leaves (empty/padding slots — unsamplable by construction) are
    excluded everywhere."""
    import jax.numpy as jnp
    leaves = tree[2 ** (num_layers - 1) - 1:]
    mask = leaves > 0
    maskf = mask.astype(jnp.float32)
    active = jnp.sum(maskf)
    mx = jnp.max(leaves)
    at_max = jnp.sum(maskf * (leaves >= mx * (1.0 - _AT_MAX_RTOL)))
    moments = jnp.stack([
        active, jnp.sum(leaves), jnp.sum(leaves ** 2), mx, at_max])
    return moments.astype(jnp.float32), value_counts(
        leaves, mask=mask.astype(jnp.int32))


def lane_counts(lane, num_lanes: int):
    """(num_lanes + 1,) int32 bincount of a batch's producing lanes —
    the last bucket collects unknown (-1 / out-of-range) stamps."""
    import jax.numpy as jnp
    lane = lane.astype(jnp.int32).reshape(-1)
    idx = jnp.where((lane >= 0) & (lane < num_lanes), lane, num_lanes)
    return jnp.zeros((num_lanes + 1,), jnp.int32).at[idx].add(1)


def fused_replay_diag(spec, rdiag: ReplayDiag, new_step, replay_state,
                      batch):
    """The device-side replay-diagnostics block, traced into the fused
    step: returns ``(replay_state, rd_metrics)``.

    Every step: the (N,) sample-count ring is incremented at the sampled
    blocks (one scatter-add) and the batch's lane composition bincounted.
    Every ``rdiag.interval`` steps, under ``lax.cond`` so the
    steady-state step pays nothing: the sum-tree health snapshot
    (moments + leaf histogram) and a READ-AND-RESET of the eviction
    accumulators ``replay_add_many`` maintains — the emitted eviction
    values are since-last-snapshot DELTAS, which stay far below f32's
    2^24 exact-integer ceiling no matter how long the run is; the host
    aggregator integrates the cumulative totals in float64. Off-interval
    steps return NaN moments / zero histograms, which the aggregator
    skips."""
    import jax
    import jax.numpy as jnp

    rs = replay_state
    out: Dict[str, Any] = {}
    if rs.sample_count is not None:
        with jax.named_scope("replay_diag_count"):
            block_idx = batch.idxes // spec.seqs_per_block
            rs = rs.replace(
                sample_count=rs.sample_count.at[block_idx].add(1))
    if batch.lane is not None and rdiag.lanes > 0:
        out["rd/lane_counts"] = lane_counts(batch.lane, rdiag.lanes)

    has_evict = rs.evict_stats is not None

    def on(_):
        moments, hist = tree_health_moments(rs.tree, spec.tree_layers)
        if has_evict:
            ev, lh = rs.evict_stats, rs.evict_life_hist
            ev_new = jnp.zeros_like(rs.evict_stats)
            lh_new = jnp.zeros_like(rs.evict_life_hist)
        else:
            ev = jnp.full((5,), jnp.nan, jnp.float32)
            lh = jnp.zeros((NBUCKETS,), jnp.int32)
            ev_new = lh_new = None
        return (moments, hist, ev, lh) + \
            ((ev_new, lh_new) if has_evict else ())

    def off(_):
        base = (jnp.full((5,), jnp.nan, jnp.float32),
                jnp.zeros((NBUCKETS,), jnp.int32),
                jnp.full((5,), jnp.nan, jnp.float32),
                jnp.zeros((NBUCKETS,), jnp.int32))
        return base + ((rs.evict_stats, rs.evict_life_hist)
                       if has_evict else ())

    vals = jax.lax.cond(
        (new_step % rdiag.interval) == 0, on, off, operand=None)
    moments, hist, ev, lh = vals[:4]
    if has_evict:
        rs = rs.replace(evict_stats=vals[4], evict_life_hist=vals[5])
    out["rd/tree_moments"] = moments
    out["rd/leaf_hist"] = hist
    out["rd/evict_stats"] = ev
    out["rd/evict_life_hist"] = lh
    return rs, out


def shard_replay_diag(rd: Dict[str, Any], axis_name: str) -> Dict[str, Any]:
    """Reshape a per-shard ``fused_replay_diag`` output for the manual
    shard_map step's replicated (P()) metric specs: snapshot keys gather
    to ``rd/shard_*`` arrays with a leading dp axis (the per-shard views
    the aggregator reports AND merges), lane counts psum to one global
    composition."""
    import jax
    out: Dict[str, Any] = {}
    if "rd/lane_counts" in rd:
        out["rd/lane_counts"] = jax.lax.psum(rd["rd/lane_counts"],
                                             axis_name)
    for key in ("rd/tree_moments", "rd/leaf_hist", "rd/evict_stats",
                "rd/evict_life_hist"):
        out[key.replace("rd/", "rd/shard_")] = jax.lax.all_gather(
            rd[key], axis_name)
    return out


# ---------------------------------------------------------------------------
# Host-side derivation + aggregation


def derive_tree_stats(moments, hist=None) -> Optional[dict]:
    """The record's ``tree`` sub-block from one (5,) moment vector
    [active, Σp, Σp², max, at_max] (+ its leaf histogram): effective
    sample size of the sampling distribution, ESS as a fraction of the
    live leaves, max/mean ratio, fraction-at-max. None when the snapshot
    is empty/off-interval (NaN or zero active)."""
    m = np.asarray(moments, np.float64).reshape(-1)
    if m.size < 5 or not np.isfinite(m[0]) or m[0] <= 0:
        return None
    active, s1, s2, mx, at_max = m[:5]
    ess = (s1 * s1 / s2) if s2 > 0 else 0.0
    mean = s1 / active
    out = {
        "active_leaves": int(active),
        "ess": round(ess, 2),
        "ess_frac": round(ess / active, 4),
        "max_mean_ratio": round(mx / mean, 3) if mean > 0 else None,
        "frac_at_max": round(at_max / active, 4),
    }
    if hist is not None:
        counts = np.asarray(hist, np.int64).reshape(-1)
        out["priorities"] = value_summary(counts)
        out["leaf_hist_counts"] = [int(c) for c in counts]
    return out


def merge_shard_moments(shard_moments) -> np.ndarray:
    """One merged (5,) moment vector from (dp, 5) per-shard moments:
    sums for active/Σp/Σp², max of maxes, and at-max counted against the
    GLOBAL max (shards whose local max falls below it contribute 0)."""
    sm = np.asarray(shard_moments, np.float64).reshape(-1, 5)
    gmx = sm[:, 3].max() if sm.size else 0.0
    at_max = float(np.sum(np.where(
        sm[:, 3] >= gmx * (1.0 - _AT_MAX_RTOL), sm[:, 4], 0.0)))
    return np.asarray([sm[:, 0].sum(), sm[:, 1].sum(), sm[:, 2].sum(),
                       gmx, at_max], np.float64)


def derive_evictions(stats, life_hist=None,
                     interval=None) -> Optional[dict]:
    """The record's ``evictions`` sub-block from the CUMULATIVE (5,)
    accumulator [evicted, never_sampled, lifetime_sum, age_sum,
    final_priority_sum] (float64, integrated host-side from the device
    path's per-snapshot deltas): the never-sampled-before-eviction
    fraction plus mean lifetime / age-at-eviction (ring adds) / final
    priority, the lifetime histogram summary, and — from ``interval``,
    this flush's delta vector — the interval sub-block whose
    ``never_sampled_frac`` the never_sampled_growth rule watches (the
    cumulative fraction's per-window change decays as 1/t, so a
    pathology starting late in a long run would never move it past the
    growth bound)."""
    s = np.asarray(stats, np.float64).reshape(-1)
    if s.size < 5 or not np.isfinite(s[0]):
        return None
    evicted, never, life, age, prio = s[:5]
    out: Dict[str, Any] = {"evicted": int(evicted),
                           "never_sampled": int(never)}
    if evicted > 0:
        out.update({
            "never_sampled_frac": round(never / evicted, 4),
            "mean_lifetime": round(life / evicted, 3),
            "mean_age_blocks": round(age / evicted, 2),
            "mean_final_priority": round(prio / evicted, 6),
        })
    if life_hist is not None:
        out["lifetime"] = value_summary(
            np.asarray(life_hist, np.int64).reshape(-1))
    if interval is not None:
        d = np.asarray(interval, np.float64).reshape(-1)
        out["interval"] = {"evicted": int(d[0]),
                           "never_sampled": int(d[1])}
        if d[0] > 0:
            out["interval"]["never_sampled_frac"] = round(d[1] / d[0], 4)
    return out


def derive_lanes(counts, num_lanes: int) -> Optional[dict]:
    """The record's ``lanes`` sub-block from the interval's summed
    (lanes+1,) bincount: how the ε ladder actually composed the sampled
    batches — active/starved lane fractions, the dominant lane's share,
    unknown-stamp fraction, and (for ladders that fit) the raw counts."""
    c = np.asarray(counts, np.int64).reshape(-1)
    total = int(c.sum())
    if total == 0 or num_lanes <= 0:
        return None
    known = c[:-1]
    active = int(np.sum(known > 0))
    out = {
        "total_lanes": num_lanes,
        "sampled_sequences": total,
        "unknown_frac": round(float(c[-1]) / total, 4),
        "active_lanes": active,
        "starved_frac": round(1.0 - active / num_lanes, 4),
        "max_share": round(float(known.max()) / max(int(known.sum()), 1),
                           4),
    }
    if num_lanes <= 64:
        out["counts"] = [int(x) for x in known]
    return out


class ReplayDiagAggregator:
    """Host-side accumulator for the fused step's ``rd/`` outputs: holds
    device values between metric flushes (no sync on the step path), then
    produces the periodic record's ``replay_diag`` block in the same
    device_get the learning aggregator batches. Snapshot keys (tree
    moments / histograms / eviction accumulators) take the NEWEST
    interval firing — they are state snapshots, not flows — while lane
    counts SUM across the interval's dispatches. ``host_stats``
    (HostReplay.diag_raw) substitutes for the device snapshot under host
    placement."""

    def __init__(self, lanes: int):
        self.lanes = lanes
        self._pending: List[Dict[str, Any]] = []
        # cumulative eviction totals, integrated in float64 from the
        # device/host paths' per-snapshot deltas (the device
        # accumulators read-and-reset each snapshot precisely so no f32
        # counter ever has to hold a run-length total)
        self._cum_evict = np.zeros(5, np.float64)
        self._cum_life = np.zeros(NBUCKETS, np.int64)
        self._evict_seen = False

    def on_dispatch(self, metrics: Dict[str, Any]) -> None:
        rd = {k: v for k, v in metrics.items() if k.startswith("rd/")}
        if rd:
            self._pending.append(rd)

    @staticmethod
    def _last_snapshot(host, mkey, extras=()):
        """Newest row (by dispatch + scan order) whose moment vector is a
        live interval firing (finite leading element), paired with the
        same row of each extra key. Handles the multi-step scan's (K, 5)
        stacking — a (5,) single-step value is one row."""
        for d in reversed(host):
            if mkey not in d:
                continue
            rows = np.asarray(d[mkey], np.float64).reshape(-1, 5)
            ex = [np.asarray(d[k]).reshape(rows.shape[0], -1)
                  for k in extras]
            for i in range(rows.shape[0] - 1, -1, -1):
                if np.isfinite(rows[i, 0]):
                    return rows[i], [e[i] for e in ex]
        return None, []

    @staticmethod
    def _sum_evict_deltas(host, key, hist_key):
        """Sum EVERY finite eviction-delta row this flush (each row is a
        disjoint since-last-snapshot window; off-interval rows are NaN),
        plus the matching lifetime-histogram rows. Handles the single
        path's (…, 5), the multi-step scan's (K, 5), and the sharded
        paths' (…, dp, 5) layouts alike by flattening to rows. Returns
        (delta5, hist, found)."""
        delta = np.zeros(5, np.float64)
        hist = np.zeros(NBUCKETS, np.int64)
        found = False
        for d in host:
            if key not in d:
                continue
            rows = np.asarray(d[key], np.float64).reshape(-1, 5)
            hrows = np.asarray(d[hist_key], np.int64).reshape(
                rows.shape[0], -1)
            finite = np.isfinite(rows[:, 0])
            if finite.any():
                found = True
                delta += rows[finite].sum(axis=0)
                hist += hrows[finite].sum(axis=0)
        return delta, hist, found

    @staticmethod
    def _last_shard_snapshot(host, mkey, extras=()):
        """Per-shard twin of ``_last_snapshot``: newest (dp, 5) moment
        slab with any finite shard, plus matching (dp, -1) extras."""
        for d in reversed(host):
            if mkey not in d:
                continue
            m = np.asarray(d[mkey], np.float64)
            dp = m.shape[-2]
            slabs = m.reshape(-1, dp, 5)
            ex = [np.asarray(d[k]) for k in extras]
            ex = [e.reshape(slabs.shape[0], dp, -1) for e in ex]
            for i in range(slabs.shape[0] - 1, -1, -1):
                if np.isfinite(slabs[i, :, 0]).any():
                    return slabs[i], [e[i] for e in ex]
        return None, []

    def flush(self, host_stats: Optional[dict] = None) -> Optional[dict]:
        """Aggregate the interval and return the ``replay_diag`` record
        block (None when no training dispatches ran)."""
        import jax
        if not self._pending:
            return None
        pending, self._pending = self._pending, []
        host = jax.device_get(pending)

        block: Dict[str, Any] = {}

        # -- sum-tree health: merged view + per-shard rows --
        moments = hist = None
        sh_m, sh_ex = self._last_shard_snapshot(
            host, "rd/shard_tree_moments", ("rd/shard_leaf_hist",))
        if sh_m is not None:
            block["shards"] = [derive_tree_stats(sh_m[i])
                               for i in range(sh_m.shape[0])]
            moments = merge_shard_moments(sh_m)
            hist = sh_ex[0].reshape(sh_m.shape[0], -1).sum(axis=0)
            delta, dhist, found = self._sum_evict_deltas(
                host, "rd/shard_evict_stats", "rd/shard_evict_life_hist")
        else:
            m, ex = self._last_snapshot(
                host, "rd/tree_moments", ("rd/leaf_hist",))
            if m is not None:
                moments, hist = m, ex[0]
            delta, dhist, found = self._sum_evict_deltas(
                host, "rd/evict_stats", "rd/evict_life_hist")

        if host_stats:
            # host placement: the numpy twin supplies the snapshot the
            # external-batch step cannot form (no device-resident ring);
            # its eviction readings are read-and-reset deltas like the
            # device path's
            moments = host_stats["tree_moments"]
            hist = host_stats["leaf_hist"]
            delta = np.asarray(host_stats["evict_stats"], np.float64)
            dhist = np.asarray(host_stats["evict_life_hist"], np.int64)
            found = True

        tree = derive_tree_stats(moments, hist) if moments is not None \
            else None
        if tree is not None:
            block["tree"] = tree
        if found:
            self._evict_seen = True
            self._cum_evict += delta
            self._cum_life += dhist.reshape(-1)
        if self._evict_seen:
            evictions = derive_evictions(
                self._cum_evict, self._cum_life,
                interval=(delta if found else np.zeros(5)))
            if evictions is not None:
                block["evictions"] = evictions

        # -- lane composition: SUM over the interval's dispatches --
        lc = [np.asarray(d["rd/lane_counts"], np.int64)
              for d in host if "rd/lane_counts" in d]
        if lc:
            counts = np.concatenate(
                [c.reshape(-1, self.lanes + 1) for c in lc]).sum(axis=0)
            lanes = derive_lanes(counts, self.lanes)
            if lanes is not None:
                block["lanes"] = lanes

        return block or None
