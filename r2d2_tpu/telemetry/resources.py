"""Resource observability (ISSUE 7): the MACHINE-side telemetry pillar.

PR 4/5 made the pipeline and the learning dynamics visible; the hardware
stayed a black box — HBM was read ad hoc in exactly two places (the
device-replay capacity guard and the soak's ``_mem_stats``), host memory
nowhere, and "how much of the ring's 5.7 GiB is actually the ring"
answerable only by grepping PERF.md. This module centralizes all of it:

  * :func:`device_memory_stats` — the ONE ``memory_stats()`` wrapper
    (backend-optional: TPU reports byte counters, CPU returns nothing —
    callers get ``{}`` instead of an exception either way). The
    device-replay HBM guard and tools/soak.py both call through here.
  * :class:`BufferRegistry` — subsystems REGISTER their device-buffer
    footprints (replay ring, params+opt state, the stager's staging
    window, the anakin lane carry) so a memory report attributes
    bytes-in-use to owners instead of printing one opaque total. The
    architectural-implications study (arXiv 2012.04210) makes exactly
    this point: distributed-RL throughput tuning starts from knowing
    which component owns the resource.
  * :class:`ResourceMonitor` — periodic sampler behind
    ``telemetry.resources_enabled``: per-device memory stats with
    host-side peak/headroom tracking, learner-process RSS/CPU, per-actor-
    slot RSS/CPU read from the :class:`TelemetryBoard` gauge columns
    (actor processes publish them on the telemetry flush cadence), and
    the buffer-attribution table. Produces the periodic record's
    ``resources`` block, and owns the one-shot OOM/headroom forensics
    dump (``resource_dump_player{p}.json``) mirroring the PR-5
    ``nan_dump`` pattern: the first sample that sees device headroom
    below ``telemetry.resources_headroom_warn_frac`` writes the full
    attribution picture to disk — the post-mortem an OOM kill would
    otherwise destroy.

Sampling cost is a handful of dict reads and one ``/proc`` line per
``telemetry.resources_interval_s`` — benched within noise on the
interleaved A/B (tools/e2e_bench.py --resources-ab, PERF.md).
"""

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

# The byte counters worth carrying in summaries (full memory_stats also
# includes allocator internals nobody alerts on).
SUMMARY_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")


def device_memory_stats(device=None, keys=None) -> Dict[str, int]:
    """``device.memory_stats()`` with the backend-optional contract made
    explicit: a dict of int-valued counters, ``{}`` when the backend
    reports nothing (CPU), the device is unavailable, or the call raises.
    ``keys`` filters to a subset (e.g. :data:`SUMMARY_KEYS`)."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        stats = device.memory_stats() or {}
    except Exception:       # memory_stats is backend-optional by contract
        return {}
    out = {}
    for k, v in stats.items():
        if keys is not None and k not in keys:
            continue
        try:
            out[k] = int(v)
        except (TypeError, ValueError):
            continue
    return out


def pytree_nbytes(tree) -> int:
    """Total byte footprint of every array leaf in a pytree — the number a
    subsystem registers for its buffers."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def host_usage() -> Dict[str, Any]:
    """This process's host footprint: RSS bytes (``/proc/self/statm``;
    peak-RSS fallback from getrusage where /proc is absent), cumulative
    CPU seconds (user+system, children excluded), and live threads."""
    rss = None
    try:
        with open("/proc/self/statm") as f:
            rss = int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        try:
            import resource
            import sys
            # ru_maxrss is a PEAK, not current — still better than
            # nothing on /proc-less platforms; KiB on Linux/BSD but
            # BYTES on macOS, the main platform that takes this branch
            scale = 1 if sys.platform == "darwin" else 1024
            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale
        except Exception:
            rss = None
    t = os.times()
    return {"rss_bytes": rss, "cpu_s": t.user + t.system,
            "threads": threading.active_count()}


class BufferRegistry:
    """Named device-buffer footprints, registered by their owners.
    Re-registering a name overwrites (a Learner rebuilt in the same
    process replaces its own entries); names are conventionally
    ``p{player}/component`` so multiplayer stacks coexist."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, int] = {}

    def register(self, name: str, nbytes: int) -> None:
        with self._lock:
            self._entries[name] = int(nbytes)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._entries.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def clear_prefix(self, prefix: str) -> None:
        with self._lock:
            for k in [k for k in self._entries if k.startswith(prefix)]:
                del self._entries[k]

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._entries)

    def total(self) -> int:
        with self._lock:
            return sum(self._entries.values())


# Process-wide default registry: owners (Learner, stager, anakin loop)
# register at construction without threading a handle through every
# signature; the ResourceMonitor reads it unless given its own.
BUFFERS = BufferRegistry()


def register_buffer(name: str, nbytes: int) -> None:
    BUFFERS.register(name, nbytes)


def clear_player_buffers(player_idx: int) -> None:
    """Drop every ``p{player}/`` registration before a rebuilt stack
    re-registers its own. Same-name overwrite covers components that
    exist in both incarnations; this covers the ones that DON'T — e.g.
    an e2e A/B whose host arm registered an ingest staging window and
    whose anakin arm has no stager would otherwise carry the stale entry
    in every resources block of the second arm."""
    BUFFERS.clear_prefix(f"p{player_idx}/")


class ResourceMonitor:
    """Periodic resource sampler + the record's ``resources`` block.

    ``maybe_sample`` is called on the supervision cadence (cheap time
    check); ``block()`` once per log interval builds the record entry
    from the newest sample. ``stats_fn`` injects a device-stats source
    for tests (the CPU backend reports nothing real)."""

    def __init__(self, player_idx: int = 0, save_dir: str = ".",
                 interval_s: float = 10.0,
                 headroom_warn_frac: float = 0.05,
                 registry: Optional[BufferRegistry] = None,
                 board=None,
                 compile_monitor=None,
                 aot_coverage_fn: Optional[Callable[[], Optional[dict]]] = None,
                 stats_fn: Optional[Callable[[Any], Dict[str, int]]] = None):
        self.player_idx = player_idx
        self.save_dir = save_dir or "."
        self.interval_s = interval_s
        self.headroom_warn_frac = headroom_warn_frac
        self.registry = registry if registry is not None else BUFFERS
        self._board = board
        self.compile_monitor = compile_monitor
        self._aot_fn = aot_coverage_fn
        self._stats_fn = stats_fn or device_memory_stats
        self.dumped = False                  # one-shot forensics latch
        self._last_sample_t: Optional[float] = None
        self._devices: List[dict] = []
        self._peak_seen: Dict[int, int] = {}   # host-side running peak
        self._host: Dict[str, Any] = {}
        self._prev_host_cpu: Optional[tuple] = None   # (t, cpu_s)
        self._host_cpu_pct: Optional[float] = None
        self._actor_prev: Optional[np.ndarray] = None  # (slots, 2) gauges
        self._actor_prev_t: Optional[float] = None
        self._actors: Optional[dict] = None

    # -- sampling --

    def maybe_sample(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        if (self._last_sample_t is not None
                and now - self._last_sample_t < self.interval_s):
            return False
        self.sample(now)
        return True

    def sample(self, now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        self._last_sample_t = now
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            devices = []
        devs = []
        for d in devices:
            stats = self._stats_fn(d)
            entry: Dict[str, Any] = {"id": int(getattr(d, "id", 0)),
                                     "platform": getattr(d, "platform", "?")}
            for k in SUMMARY_KEYS:
                if k in stats:
                    entry[k] = stats[k]
            in_use, limit = entry.get("bytes_in_use"), entry.get("bytes_limit")
            if in_use is not None:
                # host-side running peak: survives backends whose
                # peak_bytes_in_use resets across allocator epochs
                prev = self._peak_seen.get(entry["id"], 0)
                self._peak_seen[entry["id"]] = max(prev, in_use)
                entry["peak_seen"] = self._peak_seen[entry["id"]]
            if in_use is not None and limit:
                entry["headroom_frac"] = round(1.0 - in_use / limit, 4)
            devs.append(entry)
        self._devices = devs
        host = host_usage()
        if self._prev_host_cpu is not None:
            pt, pc = self._prev_host_cpu
            dt = now - pt
            if dt > 0:
                self._host_cpu_pct = round(
                    100.0 * (host["cpu_s"] - pc) / dt, 1)
        self._prev_host_cpu = (now, host["cpu_s"])
        self._host = host
        self._sample_actors(now)
        self._check_headroom()

    def _sample_actors(self, now: float) -> None:
        board = self._board
        if board is None or not hasattr(board, "read_gauges"):
            return
        g = board.read_gauges()
        if g is None:
            return
        rss = [int(x) for x in g[:, 0]]
        cpu_ms = g[:, 1].astype(np.float64)
        cpu_pct: List[Optional[float]] = [None] * len(rss)
        if self._actor_prev is not None and self._actor_prev_t is not None:
            dt = now - self._actor_prev_t
            if dt > 0:
                delta = (cpu_ms - self._actor_prev[:, 1]) / 1e3
                # a respawned slot restarts its cumulative counter; a
                # negative delta reads as the fresh value (same rule as
                # the board's histogram reset detection)
                delta = np.where(delta < 0, cpu_ms / 1e3, delta)
                cpu_pct = [round(100.0 * float(d) / dt, 1) for d in delta]
        self._actor_prev = g.astype(np.float64)
        self._actor_prev_t = now
        self._actors = {"rss_bytes": rss, "cpu_pct": cpu_pct}

    def _check_headroom(self) -> None:
        """The OOM-forensics trigger: first sample under the headroom
        floor writes ONE dump with the full attribution picture (the
        nan_dump pattern — the data an actual OOM kill would destroy)."""
        if self.dumped or self.headroom_warn_frac <= 0:
            return
        low = [d for d in self._devices
               if d.get("headroom_frac") is not None
               and d["headroom_frac"] < self.headroom_warn_frac]
        if low:
            self.dump(reason=f"device headroom below "
                             f"{self.headroom_warn_frac:.0%}: "
                             + ", ".join(f"dev{d['id']}="
                                         f"{d['headroom_frac']:.1%}"
                                         for d in low))

    @property
    def dump_path(self) -> str:
        return os.path.join(self.save_dir,
                            f"resource_dump_player{self.player_idx}.json")

    def dump(self, reason: str = "requested") -> Optional[str]:
        """One-shot forensics dump (idempotent, like the NaN dump)."""
        if self.dumped:
            return None
        self.dumped = True
        record = {"time": time.time(), "reason": reason,
                  **self.block(consume_compile=False)}
        try:
            os.makedirs(self.save_dir, exist_ok=True)
            with open(self.dump_path, "w") as f:
                json.dump(record, f, indent=2)
        except OSError:
            logging.getLogger(__name__).exception(
                "failed writing resource forensics dump")
            return None
        logging.getLogger(__name__).warning(
            "player %d: resource forensics dumped to %s (%s)",
            self.player_idx, self.dump_path, reason)
        return self.dump_path

    # -- the record block --

    def block(self, consume_compile: bool = True) -> dict:
        """The periodic record's ``resources`` entry, from the newest
        sample (sampling first if none was ever taken). The compile
        sub-block consumes the CompileMonitor's interval counters, so
        call once per log boundary."""
        if self._last_sample_t is None:
            self.sample()
        headrooms = [d["headroom_frac"] for d in self._devices
                     if d.get("headroom_frac") is not None]
        out: Dict[str, Any] = {
            "devices": self._devices,
            "hbm_headroom_frac_min": min(headrooms) if headrooms else None,
            "host": {"rss_bytes": self._host.get("rss_bytes"),
                     "cpu_pct": self._host_cpu_pct,
                     "threads": self._host.get("threads")},
            "buffers": self.registry.snapshot(),
            "buffers_total": self.registry.total(),
        }
        if self._actors is not None:
            out["actor_slots"] = self._actors
        if self.compile_monitor is not None:
            comp = (self.compile_monitor.interval_summary()
                    if consume_compile
                    else self.compile_monitor.totals())
            aot = self._aot_fn() if self._aot_fn is not None else None
            if aot is not None:
                comp["aot"] = aot
            out["compile"] = comp
        return out
