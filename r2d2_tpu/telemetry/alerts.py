"""Declarative alerting over the periodic metrics records (ISSUE 7).

Everything the stack measures — throughput, heartbeat ages, sample
staleness, HBM headroom, retraces, NaNs — already lands in the periodic
``metrics_player{p}.jsonl`` record; until now NOTHING watched it, so a
throughput collapse or retrace storm was only noticed when a human read
a JSONL. This module is the watcher: a small rule engine evaluated once
per record, at the log boundary, inside :meth:`TrainMetrics.log` — so
every record carries an ``alerts`` block and every firing appends one
line to ``alerts_player{p}.jsonl`` (the machine-readable side
tools/sentinel.py and the inspector read).

Rules are DATA (:class:`AlertRule`): a kind, a key path into the record,
and a bound — no subclassing per alert. Four kinds cover the failure
modes the ISSUE names:

  * ``threshold`` — value crosses a bound (heartbeat age, HBM headroom
    with ``below=True``, per-interval retrace count, non-finite steps);
  * ``drop``      — value falls below ``bound x`` the rolling median of
    the previous ``window`` records (throughput collapse; warm-up zeros
    never enter the median, so the rule arms only once the metric has
    actually been healthy for a full window);
  * ``growth``    — value exceeds ``bound x`` the rolling median
    (sample-age/staleness creep);
  * ``counter``   — a CUMULATIVE counter increased since the last record
    (watchdog hang detections, restarts). Pure edge semantics: one
    increment fires exactly once; the baseline starts at zero, so events
    that precede the first log boundary (a warm-up hang) alert on the
    first record that carries them.

Level-triggered kinds (threshold/drop/growth) fire on the
inactive→active EDGE and stay silently active until the condition
clears — a persistent condition produces one alert line, not one per
interval; recovery re-arms the rule.
"""

import json
import os
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

_KINDS = ("threshold", "drop", "growth", "counter")


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule. ``path`` walks nested dicts of the periodic
    record (``("learning", "sample_age", "p50")``); missing keys / None
    values leave the rule inactive (never a false fire on a record that
    simply lacks the block)."""

    name: str
    kind: str                    # threshold | drop | growth | counter
    path: Tuple[str, ...]
    bound: float
    severity: str = "warn"       # warn | crit
    below: bool = False          # threshold: fire when value <= bound
    window: int = 8              # drop/growth rolling-median window

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"alert rule {self.name!r}: unknown kind {self.kind!r} "
                f"(expected one of {_KINDS})")
        if self.kind in ("drop", "growth") and self.window < 2:
            raise ValueError(
                f"alert rule {self.name!r}: window must be >= 2")


def record_value(record: dict, path: Sequence[str]) -> Optional[float]:
    """Walk a key path into the record; None for missing/None/non-numeric
    leaves (absent blocks must read as 'no data', not as zero)."""
    node: Any = record
    for key in path:
        if not isinstance(node, dict) or key not in node:
            return None
        node = node[key]
    if node is None or isinstance(node, (dict, list, str)):
        return None
    try:
        return float(node)
    except (TypeError, ValueError):
        return None


def default_rules(tcfg) -> Tuple[AlertRule, ...]:
    """The stock rule set, parameterized by the TelemetryConfig
    ``alerts_*`` knobs — what the orchestrator/anakin/multihost loops
    install. tools/sentinel.py builds the same set for offline runs."""
    w = tcfg.alerts_window
    return (
        # throughput collapse vs the run's own recent history: the 2012.04210
        # signal — a parked fleet or wedged stager shows here first
        AlertRule("env_throughput_drop", "drop", ("buffer_speed",),
                  tcfg.alerts_throughput_drop_frac, "crit", window=w),
        AlertRule("learner_throughput_drop", "drop", ("training_speed",),
                  tcfg.alerts_throughput_drop_frac, "crit", window=w),
        # an actor the watchdog had to declare hung (cumulative counter:
        # one hang -> exactly one alert)
        AlertRule("actor_stall", "counter", ("actor_hangs_detected",),
                  1.0, "crit"),
        AlertRule("actor_restart", "counter", ("actor_restarts",), 1.0,
                  "warn"),
        AlertRule("heartbeat_stale", "threshold", ("heartbeat_age_max_s",),
                  tcfg.alerts_heartbeat_age_s, "warn"),
        # replay staleness creep: sample ages growing past a multiple of
        # their own recent median (weight publication or ingestion lagging)
        AlertRule("staleness_growth", "growth",
                  ("learning", "sample_age", "p50"),
                  tcfg.alerts_staleness_growth_factor, "warn", window=w),
        # machine-side rules (the resources block, ISSUE 7 tentpole)
        AlertRule("hbm_headroom", "threshold",
                  ("resources", "hbm_headroom_frac_min"),
                  tcfg.alerts_hbm_headroom_frac, "crit", below=True),
        AlertRule("retrace_storm", "threshold",
                  ("resources", "compile", "retraces_interval"),
                  float(tcfg.alerts_retrace_storm), "crit"),
        AlertRule("nan", "threshold", ("learning", "nonfinite_steps"),
                  1.0, "crit"),
        # sharded-anakin balance (ISSUE 8): max/min per-shard env-steps
        # over the interval, measured from the blocks each shard's ring
        # actually received. Today's lockstep program emits full blocks
        # on every shard every segment, so this reads exactly 1.0 and
        # the rule stays silent BY CONSTRUCTION — it is the standing
        # guard for the compositions that can skew it (ragged/partial
        # per-shard emission, elastic meshes with parked shards), where
        # the lockstep program would run at the slowest shard's pace.
        # Inactive on non-anakin runs (no block).
        AlertRule("shard_imbalance", "threshold",
                  ("anakin", "shard_imbalance"),
                  tcfg.alerts_shard_imbalance, "warn"),
        # replay & data-pathology rules (ISSUE 10; the replay_diag block,
        # telemetry/replaydiag.py — inactive on records without it):
        # priority collapse = the sampling distribution's effective
        # sample size shrank to a sliver of the live leaves (training is
        # grinding a handful of sequences)
        AlertRule("priority_collapse", "threshold",
                  ("replay_diag", "tree", "ess_frac"),
                  tcfg.alerts_replay_ess_frac, "warn", below=True),
        # a mass of leaves tied at the tree max: prioritization has
        # stopped discriminating (constant-stamp seeding never resampled,
        # or TD errors saturating)
        AlertRule("priority_saturation", "threshold",
                  ("replay_diag", "tree", "frac_at_max"),
                  tcfg.alerts_priority_saturation, "warn"),
        # replay sized/prioritized wrong: the share of experience evicted
        # without EVER being sampled is growing past its own history.
        # Watches the PER-INTERVAL fraction — the cumulative one's
        # per-window change decays as 1/t and would mask late-onset
        # pathology behind a long healthy prefix.
        AlertRule("never_sampled_growth", "growth",
                  ("replay_diag", "evictions", "interval",
                   "never_sampled_frac"),
                  tcfg.alerts_never_sampled_growth, "warn", window=w),
        # ε-ladder lanes contributing nothing to the learning signal —
        # Ape-X exploration measured at the point of learning
        AlertRule("lane_starvation", "threshold",
                  ("replay_diag", "lanes", "starved_frac"),
                  tcfg.alerts_lane_starved_frac, "warn"),
        # fleet rules (ISSUE 12; the fleet block, telemetry/fleet.py —
        # inactive on records without it, i.e. every non-multihost run):
        # one rank's mean step time running a multiple of the fastest
        # rank's — under lockstep the WHOLE pod runs at its pace
        AlertRule("rank_straggler", "threshold",
                  ("fleet", "step_time", "skew"),
                  tcfg.alerts_rank_straggler, "warn"),
        # this rank's loop time is mostly spent blocked in the per-
        # iteration psum — the DCN barrier (or a peer) owns the step
        AlertRule("lockstep_wait_frac", "threshold",
                  ("fleet", "lockstep", "wait_frac"),
                  tcfg.alerts_lockstep_wait_frac, "warn"),
        # per-rank ingested env-steps diverging: one host's actors are
        # starving its replay shards relative to the fleet
        AlertRule("fleet_desync", "threshold",
                  ("fleet", "env_steps", "divergence"),
                  tcfg.alerts_fleet_desync, "warn"),
        # a rank stopped writing its host row (rank-0 view): wedged or
        # dead past the heartbeat horizon
        AlertRule("missing_rank", "threshold",
                  ("fleet", "host_rows", "max_age_s"),
                  tcfg.alerts_missing_rank_age_s, "crit"),
        # serving-plane rules (ISSUE 13; the serving block,
        # serve/server.py ServingStats — inactive on records without it,
        # i.e. every run with actor.inference="local" and no server):
        # client-visible request latency P99 over the SLO ceiling —
        # includes queueing, retries, and timed-out attempts, so a dead
        # or wedged server fires this DURING the outage, and recovery
        # re-arms it (the chaos drill's acceptance)
        AlertRule("serve_latency_slo", "threshold",
                  ("serving", "latency", "p99_ms"),
                  tcfg.alerts_serve_p99_ms, "crit"),
        # the micro-batcher dispatching singletons despite >1 connected
        # clients: batching is not coalescing under load (deadline too
        # tight for the arrival cadence, or clients serialized)
        AlertRule("serve_batch_starvation", "threshold",
                  ("serving", "batch", "starved_frac"),
                  tcfg.alerts_serve_starved_frac, "warn"),
        # a burst of client disconnects within one interval (cumulative
        # counter: one burst, one alert) — flapping clients or a
        # lease-thrashing cache
        AlertRule("serve_client_churn", "counter",
                  ("serving", "clients", "disconnects"),
                  tcfg.alerts_serve_churn, "warn"),
        # brownout (ISSUE 17; the serving block's admission sub-block —
        # present only when admission control or the serving fleet is
        # ON): the interval's shed fraction crossed the ceiling — the
        # fleet is rejecting a sustained share of offered load at the
        # queue-depth bound, i.e. under-provisioned, not just bursty
        AlertRule("serve_brownout", "threshold",
                  ("serving", "admission", "shed_frac"),
                  tcfg.alerts_serve_shed_frac, "warn"),
        # quantized-inference rule (ISSUE 14; the quant block,
        # telemetry/quant.py — inactive on records without it, i.e.
        # every inference_dtype="f32" run): the interval's lane-weighted
        # greedy-action agreement between the quantized forward and its
        # f32 twin fell to/below the floor — the quantized policy has
        # stopped acting like the policy the learner is training. A
        # probe-free interval carries agree_frac=None, which HOLDS the
        # rule (no data ≠ recovery).
        AlertRule("quant_divergence", "threshold",
                  ("quant", "agree_frac"),
                  tcfg.alerts_quant_agreement, "warn", below=True),
        # elastic-fleet rules (ISSUE 15; the replay_service block,
        # r2d2_tpu/fleet/ — inactive on records without it, i.e. every
        # run with no fleet plane configured):
        # spill thrash — the interval's demoted pages are falling off
        # the LRU end before re-promotion (eviction/demotion ratio): the
        # device ring turns over faster than the spill tier can cycle
        # experience back, so the tier is pure write-through loss
        AlertRule("spill_thrash", "threshold",
                  ("replay_service", "spill", "thrash_frac"),
                  tcfg.alerts_spill_thrash_frac, "warn"),
        # a weight-tree relay stopped propagating: its subtree's actors
        # act publications behind the learner (max root-to-relay lag)
        AlertRule("fanout_lag", "threshold",
                  ("replay_service", "fanout", "max_lag"),
                  tcfg.alerts_fanout_lag, "warn"),
        # a leased slot went silent without being parked or re-adopted —
        # a leaked lease the membership plane cannot fill (crit: the
        # fleet is silently narrower than the lease table claims)
        AlertRule("orphaned_slot", "threshold",
                  ("replay_service", "membership", "orphaned"),
                  tcfg.alerts_orphaned_slots, "crit"),
        # batched service ingest (ISSUE 16; the replay_service.ingest
        # sub-block — present only with fleet.ingest_batch_blocks > 1):
        # blocks left queued behind the service's grouped drain —
        # producers burst faster than the dispatch plane commits, so
        # experience ages in the feeder queue before ever becoming
        # samplable
        AlertRule("ingest_backlog", "threshold",
                  ("replay_service", "ingest", "backlog"),
                  tcfg.alerts_ingest_backlog, "warn"),
        # per-tier replay telemetry (ISSUE 19 satellite, ROADMAP 4d; the
        # spill.promotion_latency sub-block — present only with
        # telemetry.replay_tiers_enabled): pages promoted this interval
        # sat demoted longer than the ceiling before coming back — the
        # spill tier is a parking lot, not a cache (experience ages out
        # of relevance before it becomes samplable again)
        AlertRule("spill_promotion_latency", "threshold",
                  ("replay_service", "spill", "promotion_latency",
                   "p95_ms"),
                  tcfg.alerts_spill_promotion_ms, "warn"),
        # cross-plane tracing (ISSUE 19; the trace block — inactive on
        # records without it, i.e. every run with tracing_enabled off):
        # the end-to-end env-step -> gradient latency grew past a
        # multiple of its own recent median — experience is aging
        # somewhere between emission and consumption (ingest backlog,
        # spill churn, or a starved sampler; the per-hop breakdown in
        # the same block says which)
        AlertRule("e2e_latency_growth", "growth",
                  ("trace", "e2e_experience_latency", "p95_ms"),
                  tcfg.alerts_e2e_latency_growth, "warn", window=w),
        # crash-recovery rules (ISSUE 18; the recovery block — inactive
        # on records without it, i.e. every run with
        # runtime.snapshot_interval == 0):
        # the newest durable replay snapshot is older than the ceiling —
        # a crash now would lose more experience than the plane promises
        # (the writer thread wedged, or the interval is mis-sized)
        AlertRule("snapshot_stale", "threshold",
                  ("recovery", "snapshot", "age_s"),
                  tcfg.alerts_snapshot_stale_s, "warn"),
        # the supervisor has relaunched the learner repeatedly — a
        # crash LOOP, not a one-off preemption; the breaker is about to
        # (or did) give up, and every lap replays the snapshot window
        AlertRule("recovery_loop", "threshold",
                  ("recovery", "supervisor", "restarts"),
                  tcfg.alerts_recovery_loop, "crit"),
        # policy-quality rules (ISSUE 20; the quality block,
        # telemetry/quality.py — inactive on records without it, i.e.
        # every run with quality_enabled off):
        # the continuous-eval mean return fell below a fraction of its
        # own recent median — the policy the fleet is serving got WORSE
        # (regression past the publish boundary, not just a noisy
        # episode; eval snapshots persist across intervals so the
        # median is over real evals)
        AlertRule("quality_regression", "drop",
                  ("quality", "eval", "mean_return"),
                  tcfg.alerts_quality_regression, "warn", window=w),
        # shadow-scored candidate disagreeing with the live policy past
        # the bound — the canary under evaluation does not act like the
        # policy it would replace (crit: promotion must not proceed). A
        # shadow-free interval carries divergence=None, which HOLDS the
        # rule (no data ≠ recovery).
        AlertRule("canary_divergence", "threshold",
                  ("quality", "shadow", "divergence"),
                  tcfg.alerts_canary_divergence, "crit"),
        # a canary has been staged longer than the ceiling without a
        # promote/refuse/rollback decision — the deployment plane is
        # wedged mid-promotion and part of the fleet is serving an
        # unvetted candidate (age_s is None outside the canary state,
        # so the rule is inactive the rest of the time)
        AlertRule("promotion_stall", "threshold",
                  ("quality", "promotion", "age_s"),
                  tcfg.alerts_promotion_stall_s, "warn"),
    )


@dataclass
class _RuleState:
    active: bool = False
    history: deque = field(default_factory=deque)
    last_counter: Optional[float] = None


class AlertEngine:
    """Evaluates the rule set against each periodic record; returns the
    record's ``alerts`` block and appends fired alerts to the JSONL
    stream. One engine per metrics stream (player), attached via
    :meth:`TrainMetrics.set_sentinel`."""

    def __init__(self, rules: Sequence[AlertRule],
                 jsonl_path: Optional[str] = None, resume: bool = False):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names in {names}")
        self.rules = tuple(rules)
        self._state = {r.name: _RuleState(
            history=deque(maxlen=r.window)) for r in self.rules}
        self.fired_total = 0
        self._jsonl_path = jsonl_path
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            if not resume:
                # fresh run truncates, resume appends — the TrainMetrics
                # JSONL contract
                open(jsonl_path, "w").close()

    @property
    def active(self) -> List[str]:
        return sorted(n for n, s in self._state.items() if s.active)

    def evaluate(self, record: dict) -> dict:
        """One pass over all rules → the record's ``alerts`` block:
        ``{"active": [names], "fired": [alert dicts]}``. Consumes the
        record in order (counter baselines, history windows advance)."""
        fired: List[dict] = []
        for rule in self.rules:
            value = record_value(record, rule.path)
            st = self._state[rule.name]
            was_active = st.active
            active, detail = self._eval(rule, st, value)
            st.active = active
            if active and not was_active:
                alert = {"rule": rule.name, "severity": rule.severity,
                         "value": value, "bound": rule.bound, **detail}
                fired.append(alert)
        if fired:
            self.fired_total += len(fired)
            self._append(record, fired)
        return {"active": self.active, "fired": fired}

    def _eval(self, rule: AlertRule, st: _RuleState,
              value: Optional[float]) -> Tuple[bool, dict]:
        if rule.kind == "counter":
            # cumulative counter: edge per increase of >= bound. The
            # baseline starts at ZERO, not at the first observation —
            # health counters are process-local and start at 0 in fresh
            # and resumed runs alike, and a hang detected during warm-up
            # (before the first log boundary) must still alert when the
            # first record arrives already carrying the count.
            if value is None:
                return False, {}
            prev, st.last_counter = st.last_counter, value
            prev = 0.0 if prev is None else prev
            if value - prev >= rule.bound:
                return True, {"delta": value - prev}
            return False, {}
        if value is None:
            # no data: level rules hold their state (a training pause must
            # not read as recovery + refire); history simply doesn't grow
            return st.active, {}
        if rule.kind == "threshold":
            hit = value <= rule.bound if rule.below else value >= rule.bound
            return hit, {}
        # drop / growth: compare against the rolling median of PREVIOUS
        # healthy observations, then admit the value to the window
        baseline = None
        if len(st.history) == st.history.maxlen:
            baseline = float(np.median(st.history))
        active = st.active
        detail: dict = {}
        if baseline is not None and baseline > 0:
            if rule.kind == "drop":
                active = value < rule.bound * baseline
            else:
                active = value > rule.bound * baseline
            detail = {"baseline": round(baseline, 3)}
        # zeros never enter the median: a warm-up/paused interval would
        # otherwise poison the 'healthy' baseline both kinds compare to
        if value > 0 and not active:
            st.history.append(value)
        return active, detail if active else {}

    def _append(self, record: dict, fired: List[dict]) -> None:
        if not self._jsonl_path:
            return
        with open(self._jsonl_path, "a") as f:
            for alert in fired:
                row = {"t": record.get("t"),
                       "training_steps": record.get("training_steps"),
                       "env_steps": record.get("env_steps"), **alert}
                f.write(json.dumps(row) + "\n")
