"""xprof/Chrome-trace → component device-time attribution (ISSUE 9).

The profiler captures (``runtime.profile_at_step`` / SIGUSR2 /
``profile_dir`` — telemetry/profiler.ProfilerCapture) leave Chrome-trace
JSON under ``plugins/profile/<ts>/*.trace.json.gz``; the spans exporter
(tools/inspect.py --export-trace) writes the same format. PR 4 could
only render those as raw per-op rows (tools/profile_step.summarize_trace)
— every optimization round still mapped ops back to model components BY
HAND. This module closes the loop: the ``jax.named_scope`` component
annotations threaded through models/network.py, learner/train_step.py,
ops/sum_tree.py and actor/anakin.py ride each HLO op's ``op_name``
metadata into the trace event args, so every complete ('X') device event
maps to a component — torso / lstm / head / sum_tree / replay /
obs_decode / loss / optimizer / emit_blocks / env_step / act_forward —
and whatever matches nothing is reported as ``unattributed``, never
dropped (the acceptance bar: >= 80% of a learner-step capture's device
time attributed, the rest visible).

    python -m r2d2_tpu.telemetry.traceparse --trace models/xprof
    python -m r2d2_tpu.telemetry.traceparse --trace t.trace.json.gz --out a.json
"""

import glob
import gzip
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Tuple

# (token, component), matched IN ORDER against the event's name + args
# text — most specific first: the network scopes nest inside act_forward
# and loss, and must win over their enclosing scope.
COMPONENT_TOKENS: Tuple[Tuple[str, str], ...] = (
    ("torso", "torso"),
    ("lstm", "lstm"),
    ("head", "head"),
    ("sum_tree", "sum_tree"),
    ("emit_blocks", "emit_blocks"),
    ("env_step", "env_step"),
    ("env_reset", "env_step"),
    ("obs_decode", "obs_decode"),
    ("stack_frames", "obs_decode"),
    ("replay_sample", "replay"),
    ("replay_add", "replay"),
    ("optimizer", "optimizer"),
    ("loss", "loss"),
    ("act_forward", "act_forward"),
)

UNATTRIBUTED = "unattributed"


def component_of(text: str) -> Optional[str]:
    """First component whose token appears in ``text`` (ordered — the
    nested network scopes beat their enclosing acting/loss scopes)."""
    for token, comp in COMPONENT_TOKENS:
        if token in text:
            return comp
    return None


def load_trace_events(path: str) -> List[dict]:
    """Trace events from a Chrome-trace ``.json``/``.json.gz`` file, or
    the NEWEST ``*.trace.json.gz`` under a capture directory (the
    ProfilerCapture ``out_dir`` layout: ``plugins/profile/<ts>/...``)."""
    if os.path.isdir(path):
        candidates = sorted(
            glob.glob(os.path.join(path, "**", "*.trace.json.gz"),
                      recursive=True)
            + glob.glob(os.path.join(path, "**", "*.trace.json"),
                        recursive=True),
            key=os.path.getmtime)
        if not candidates:
            raise FileNotFoundError(
                f"no *.trace.json(.gz) under {path!r} — did the capture "
                "run? (runtime.profile_at_step / SIGUSR2 write here)")
        path = candidates[-1]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    return doc["traceEvents"] if isinstance(doc, dict) else doc


def _event_text(e: dict) -> str:
    """Everything attributable about one event: its name plus every
    string arg value (xprof puts the HLO op_name metadata — where the
    named_scope path lives — in args like ``long_name``/``tf_op``)."""
    parts = [str(e.get("name", ""))]
    for v in (e.get("args") or {}).values():
        if isinstance(v, str):
            parts.append(v)
    return " ".join(parts)


def device_pids(events: Iterable[dict]) -> Dict[int, str]:
    """pid → process name for the accelerator planes ("/device:..." and
    not a host-CPU plane). Empty when the capture has no device plane
    (CPU backend) — callers then fall back to all pids."""
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[e.get("pid")] = str((e.get("args") or {}).get("name", ""))
    return {pid: n for pid, n in names.items()
            if "/device:" in n and "CPU" not in n.upper()}


# thread (tid) lines on a device plane that MIRROR or ENCLOSE the
# per-op "XLA Ops" events rather than adding new time: xprof derives
# "XLA Modules" (one span per module execution), "Steps", and the
# framework view lines ("TensorFlow Name Scope" / "TensorFlow Ops" /
# "Framework Name Scope" / "Framework Ops", one nested span per scope
# level, plus "Source code") from the same op stream — summing any of
# them double- or triple-counts every op's time and sinks the enclosing
# spans into 'unattributed'. Matched by substring on the thread name.
# "steps" is matched EXACTLY (below), not as a substring — a user
# thread named e.g. "env steps" must not be silently excluded
_AGGREGATE_THREAD_TOKENS = ("xla modules", "name scope",
                            "tensorflow ops", "framework ops",
                            "source code")


def _op_tids(events: Iterable[dict]) -> Dict[tuple, bool]:
    """(pid, tid) → include? from thread_name metadata: derived/
    aggregate lines excluded; unnamed threads included (the spans
    exporter and the test fixtures carry no thread names)."""
    include: Dict[tuple, bool] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            name = str((e.get("args") or {}).get("name", "")).strip().lower()
            include[(e.get("pid"), e.get("tid"))] = not (
                name == "steps"
                or any(tok in name for tok in _AGGREGATE_THREAD_TOKENS))
    return include


def attribute_trace(events_or_path, all_tracks: bool = False,
                    top_ops: int = 8) -> Dict[str, Any]:
    """Map a capture's complete ('X') device events to components.

    Returns a machine-readable summary: per-component total device time,
    share, and the top ops inside it; ``unattributed`` is a component
    row like any other (never dropped — its share is the attribution
    gap the >= 80% acceptance bar watches). ``host_fallback`` flags a
    capture with no device plane (CPU backend / spans export), where
    ALL tracks were used instead."""
    events = (load_trace_events(events_or_path)
              if isinstance(events_or_path, str) else list(events_or_path))
    dev = device_pids(events)
    host_fallback = not dev and not all_tracks
    use_all = all_tracks or host_fallback
    op_tids = _op_tids(events)

    comp_us: Dict[str, float] = defaultdict(float)
    comp_ops: Dict[str, Dict[str, List[float]]] = defaultdict(
        lambda: defaultdict(lambda: [0.0, 0]))
    total = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        if not use_all and e.get("pid") not in dev:
            continue
        if not op_tids.get((e.get("pid"), e.get("tid")), True):
            continue      # enclosing-span line (XLA Modules / Steps)
        dur = float(e.get("dur", 0.0))
        if dur <= 0:
            continue
        comp = component_of(_event_text(e)) or UNATTRIBUTED
        total += dur
        comp_us[comp] += dur
        row = comp_ops[comp][str(e.get("name", "?"))]
        row[0] += dur
        row[1] += 1

    components = {}
    for comp, us in sorted(comp_us.items(), key=lambda kv: -kv[1]):
        ops = sorted(((n, d, int(c)) for n, (d, c) in comp_ops[comp].items()),
                     key=lambda r: -r[1])[:top_ops]
        components[comp] = {
            "time_us": round(us, 3),
            "share": round(us / total, 6) if total else 0.0,
            "ops": [{"name": n, "time_us": round(d, 3), "count": c}
                    for n, d, c in ops],
        }
    unattributed = comp_us.get(UNATTRIBUTED, 0.0)
    return {
        "schema": 1,
        "total_us": round(total, 3),
        "attributed_frac": (round((total - unattributed) / total, 6)
                            if total else 0.0),
        "unattributed_us": round(unattributed, 3),
        "host_fallback": bool(host_fallback),
        "device_planes": sorted(dev.values()),
        "components": components,
    }


def format_attribution(summary: Dict[str, Any]) -> str:
    lines = [f"{'component':<14}{'time ms':>12}{'share':>9}"]
    for comp, row in summary["components"].items():
        lines.append(f"{comp:<14}{row['time_us'] / 1e3:>12.3f}"
                     f"{100 * row['share']:>8.1f}%")
    lines.append(f"attributed: {100 * summary['attributed_frac']:.1f}% of "
                 f"{summary['total_us'] / 1e3:.3f} ms device time"
                 + ("  [no device plane — all tracks]"
                    if summary["host_fallback"] else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--trace", required=True,
                   help="capture dir (runtime save_dir/xprof) or a "
                        "*.trace.json(.gz) file")
    p.add_argument("--out", default="",
                   help="write the attribution summary JSON here")
    p.add_argument("--all-tracks", action="store_true",
                   help="attribute every pid, not just device planes")
    p.add_argument("--top", type=int, default=8,
                   help="ops kept per component")
    args = p.parse_args(argv)

    summary = attribute_trace(args.trace, all_tracks=args.all_tracks,
                              top_ops=args.top)
    print(format_attribution(summary))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
            f.write("\n")
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
