"""Fleet control tower (ISSUE 19 tentpole, part b).

Every plane of the disaggregated stack already narrates itself into a
per-process JSONL stream: the learner's ``metrics_player{p}.jsonl``, the
serving fleet's ``serve_metrics.jsonl``, a standalone ReplayService's
``service_metrics_p{p}.jsonl``, the multihost ranks'
``telemetry_host{r}.jsonl``, the quality ledger's
``quality_player{p}.jsonl`` (ISSUE 20), and the per-stream alert
logs. Until now
NOTHING read them together — a brownout on the serving plane and an
ingest backlog on the replay plane looked like two unrelated warnings in
two files, when together they are one story (compute contention). The
tower is the reader: it tails every stream, joins the newest rows into
ONE fleet-wide record, derives the cross-plane signals no single stream
can see, and runs its own alert pass over the joined record (the same
declarative :class:`~r2d2_tpu.telemetry.alerts.AlertEngine` the per-run
sentinel uses — tower rules are data too).

Joined-record shape::

    {"t_wall": ..., "planes": {
         "learner":        [newest record per player],
         "serve":          newest fleet row or None,
         "replay_service": [newest row per standalone service host],
         "hosts":          [newest row per multihost rank],
         "quality":        [newest quality-ledger row per player]},
     "events": [newest alert firings across every alerts stream],
     "clock":  {"anchors": {plane: {...}}, "offsets": {plane: s}},
     "derived": {...}, "alerts": {"active": [...], "fired": [...]}}

Clock alignment generalizes the PR-11/12 ``clock_anchor``: serve and
replay-service processes stamp a wall/mono anchor pair at lease
announcement (``proc_header``); a standalone ReplayService additionally
exchanges anchors with the lease board at ``announce_replay`` (the board
echoes its wall clock, giving ``offset_est`` good to half the
announcement RTT), so the tower — and the Perfetto merge in
``tools/inspect.py --export-trace`` — can place every plane's events on
the learner's clock without assuming a shared monotonic clock.

Gated by ``telemetry.tower_enabled``; the tower is PULL-based (a reader
process beside the run — ``tools/tower.py``), so the switch gates the
reader, and the producing planes are byte-identical either way.
"""

import glob
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from r2d2_tpu.telemetry.alerts import AlertEngine, AlertRule, record_value

# Streams the tower joins, as (plane, glob) pairs. Multi-match globs
# (players, service hosts, ranks) contribute one row per file.
STREAM_GLOBS = (
    ("learner", "metrics_player*.jsonl"),
    ("serve", "serve_metrics.jsonl"),
    ("replay_service", "service_metrics_p*.jsonl"),
    ("hosts", "telemetry_host*.jsonl"),
    ("quality", "quality_player*.jsonl"),
)
ALERT_GLOBS = ("alerts_player*.jsonl", "serve_alerts.jsonl",
               "alerts_host*.jsonl")


def tower_rules(cfg) -> Tuple[AlertRule, ...]:
    """The tower's cross-plane rule set — evaluated against the JOINED
    record, so the paths walk ``derived``, where the cross-plane
    signals live. Parameterized by the same ``telemetry.alerts_*``
    knobs as the per-run sentinel (one knob vocabulary, two scopes)."""
    t = cfg.telemetry
    return (
        # the acceptance signal: end-to-end env-step -> gradient p95
        # growing past a multiple of its own recent median (the rolling
        # window lives in the engine, so offline replay and live tailing
        # share warm-up semantics with every other growth rule)
        AlertRule("tower_e2e_latency_growth", "growth",
                  ("derived", "e2e_p95_ms"),
                  t.alerts_e2e_latency_growth, "warn",
                  window=t.alerts_window),
        # the canonical cross-plane correlation: the serving fleet shed
        # requests in an interval where the replay plane's ingest ran a
        # backlog — two planes contending for the same resource budget;
        # either alone is a plane-local warning, together they are a
        # provisioning signal (1.0 = both observed this join)
        AlertRule("tower_shed_while_backlog", "threshold",
                  ("derived", "shed_while_backlog"), 1.0, "crit"),
        # per-tier replay health surfaced fleet-wide (ROADMAP 4d): the
        # same bound as the in-run spill_promotion_latency rule, read
        # from whichever plane hosts the service (learner-internal or
        # standalone)
        AlertRule("tower_spill_promotion_latency", "threshold",
                  ("derived", "spill_promotion_p95_ms"),
                  t.alerts_spill_promotion_ms, "warn"),
        # a plane stopped reporting: its newest row aged past the
        # ceiling while other planes kept writing (file-mtime based, so
        # a crashed serve fleet is visible even though its stream simply
        # ends) — live mode only; offline replay carries no ages
        AlertRule("tower_plane_silent", "threshold",
                  ("derived", "stalest_plane_age_s"),
                  t.alerts_missing_rank_age_s, "crit"),
        # policy-quality twins (ISSUE 20): the same three signals the
        # in-run sentinel watches, read from the quality-ledger stream
        # so a tower beside a quality-enabled run catches a regressing
        # checkpoint / diverging canary even when the run's own engine
        # is kill-switched
        AlertRule("tower_quality_regression", "drop",
                  ("derived", "quality_eval_return"),
                  t.alerts_quality_regression, "warn",
                  window=t.alerts_window),
        AlertRule("tower_canary_divergence", "threshold",
                  ("derived", "canary_divergence"),
                  t.alerts_canary_divergence, "crit"),
        AlertRule("tower_promotion_stall", "threshold",
                  ("derived", "promotion_age_s"),
                  t.alerts_promotion_stall_s, "warn"),
    )


def _read_last_row(path: str) -> Optional[dict]:
    from r2d2_tpu.telemetry.fleet import read_last_jsonl_row
    return read_last_jsonl_row(path)


class TowerCollector:
    """One tower instance per run directory. ``snapshot()`` joins the
    newest row of every stream (live mode); ``replay()`` walks the full
    histories index-aligned (every plane logs on the same
    ``runtime.log_interval`` cadence, so row *i* of each stream covers
    the same interval up to one period of skew — the offline join the
    post-mortem CLI uses). Both feed ``evaluate()``."""

    def __init__(self, run_dir: str, cfg=None,
                 jsonl_path: Optional[str] = None):
        if cfg is None:
            from r2d2_tpu.config import Config
            cfg = Config()
        self.run_dir = run_dir
        self.cfg = cfg
        self.engine = AlertEngine(tower_rules(cfg), jsonl_path=jsonl_path)
        self._events_seen: Dict[str, int] = {}

    # -- stream discovery / reading --

    def _paths(self, pattern: str) -> List[str]:
        return sorted(glob.glob(os.path.join(self.run_dir, pattern)))

    def _plane_rows(self) -> Tuple[Dict[str, object], Dict[str, float]]:
        """Newest row per stream, plus per-plane staleness (seconds
        since the newest contributing file was written)."""
        planes: Dict[str, object] = {}
        ages: Dict[str, float] = {}
        now = time.time()
        for plane, pattern in STREAM_GLOBS:
            rows, age = [], None
            for path in self._paths(pattern):
                row = _read_last_row(path)
                if row is None:
                    continue
                rows.append(row)
                try:
                    a = now - os.path.getmtime(path)
                except OSError:
                    continue
                age = a if age is None else min(age, a)
            if plane == "serve":
                planes[plane] = rows[0] if rows else None
            else:
                planes[plane] = rows
            if age is not None:
                ages[plane] = round(age, 1)
        return planes, ages

    def _new_events(self, limit: int = 32) -> List[dict]:
        """Alert firings appended to ANY alerts stream since the last
        call — the joined record's supervisor/recovery/brownout event
        feed (each row tagged with its source stream)."""
        from r2d2_tpu.tools.logparse import parse_jsonl
        events: List[dict] = []
        for pattern in ALERT_GLOBS:
            for path in self._paths(pattern):
                try:
                    rows = parse_jsonl(path)
                except FileNotFoundError:
                    continue
                seen = self._events_seen.get(path, 0)
                if len(rows) < seen:      # truncation: fresh run
                    seen = 0
                for row in rows[seen:]:
                    events.append({"stream": os.path.basename(path), **row})
                self._events_seen[path] = len(rows)
        return events[-limit:]

    # -- the join --

    @staticmethod
    def derive(planes: Dict[str, object],
               ages: Optional[Dict[str, float]] = None) -> dict:
        """The cross-plane signals — everything here reads >= 1 plane
        and exists nowhere else. Static so offline replay (which joins
        historical rows, not files) shares the exact derivation."""
        derived: dict = {}
        learners = planes.get("learner") or []
        lead = learners[0] if learners else {}

        # end-to-end experience latency (the tracing tentpole's record
        # block) — surfaced fleet-wide for the growth rule
        e2e = record_value(lead, ("trace", "e2e_experience_latency",
                                  "p95_ms"))
        if e2e is not None:
            derived["e2e_p95_ms"] = e2e

        # the replay plane's view: prefer the standalone service hosts'
        # rows, fall back to the learner-internal service block
        svc_rows = list(planes.get("replay_service") or [])
        if not svc_rows and lead.get("replay_service") is not None:
            svc_rows = [lead]
        backlog = max((record_value(r, ("replay_service", "ingest",
                                        "backlog")) or 0.0
                       for r in svc_rows), default=0.0)
        promo = [v for r in svc_rows
                 if (v := record_value(r, ("replay_service", "spill",
                                           "promotion_latency",
                                           "p95_ms"))) is not None]
        if promo:
            derived["spill_promotion_p95_ms"] = max(promo)

        # the serving plane's view: the standalone fleet row, else the
        # learner-internal serving block
        serve_row = planes.get("serve") or lead
        shed = record_value(serve_row, ("serving", "admission", "shed"))

        # shed-while-backlog: BOTH planes degraded in the joined
        # interval (the correlation no single stream carries)
        if shed is not None or backlog:
            derived["ingest_backlog"] = backlog
            derived["serve_shed"] = shed or 0.0
            derived["shed_while_backlog"] = float(
                bool(shed) and bool(backlog))

        # the policy-quality plane's view (ISSUE 20): worst-case across
        # players — the tower flags the WORST checkpoint's regression
        # and the most-diverged canary, not the average
        q_rows = list(planes.get("quality") or [])
        evals = [v for r in q_rows
                 if (v := record_value(r, ("quality", "eval",
                                           "mean_return"))) is not None]
        if evals:
            derived["quality_eval_return"] = min(evals)
        divs = [v for r in q_rows
                if (v := record_value(r, ("quality", "shadow",
                                          "divergence"))) is not None]
        if divs:
            derived["canary_divergence"] = max(divs)
        p_ages = [v for r in q_rows
                  if (v := record_value(r, ("quality", "promotion",
                                            "age_s"))) is not None]
        if p_ages:
            derived["promotion_age_s"] = max(p_ages)

        if ages:
            derived["plane_ages_s"] = dict(ages)
            derived["stalest_plane_age_s"] = max(ages.values())
        return derived

    @staticmethod
    def clock(planes: Dict[str, object]) -> dict:
        """Per-plane clock anchors (+ the announce-time offset estimate
        where a plane exchanged one) pulled from the proc headers."""
        anchors: Dict[str, dict] = {}
        offsets: Dict[str, float] = {}
        serve_row = planes.get("serve")
        rows = [("serve", serve_row)] if serve_row else []
        rows += [(f"replay_service/{i}", r)
                 for i, r in enumerate(planes.get("replay_service") or [])]
        rows += [(f"quality/{i}", r)
                 for i, r in enumerate(planes.get("quality") or [])]
        for name, row in rows:
            proc = (row or {}).get("proc") or {}
            anchor = proc.get("clock_anchor")
            if anchor:
                anchors[name] = anchor
                if anchor.get("offset_est") is not None:
                    offsets[name] = anchor["offset_est"]
        for row in planes.get("hosts") or []:
            a = row.get("clock_anchor")
            if a and row.get("rank") is not None:
                anchors[f"host{row['rank']}"] = a
        return {"anchors": anchors, "offsets": offsets}

    def join(self, planes: Dict[str, object],
             ages: Optional[Dict[str, float]] = None,
             events: Optional[List[dict]] = None) -> dict:
        record = {"t_wall": round(time.time(), 3), "planes": planes,
                  "derived": self.derive(planes, ages),
                  "clock": self.clock(planes)}
        if events:
            record["events"] = events
        return record

    # -- entry points --

    def snapshot(self, evaluate: bool = True) -> dict:
        """Live mode: join the newest rows + fresh events, evaluate the
        tower rules, return the joined record (``alerts`` included)."""
        planes, ages = self._plane_rows()
        record = self.join(planes, ages, self._new_events())
        if evaluate:
            record["alerts"] = self.engine.evaluate(record)
        return record

    def replay(self) -> List[dict]:
        """Offline mode: walk the full stream histories index-aligned
        and evaluate every joined record in order — the post-mortem the
        sentinel CLI performs per-stream, performed fleet-wide. Returns
        the joined records (each carrying its ``alerts`` block)."""
        from r2d2_tpu.tools.logparse import parse_jsonl
        histories: Dict[str, List[List[dict]]] = {}
        for plane, pattern in STREAM_GLOBS:
            streams = []
            for path in self._paths(pattern):
                try:
                    streams.append(parse_jsonl(path))
                except FileNotFoundError:
                    continue
            histories[plane] = streams
        depth = max((len(s) for streams in histories.values()
                     for s in streams), default=0)
        out = []
        for i in range(depth):
            planes: Dict[str, object] = {}
            for plane, streams in histories.items():
                # index-aligned join; a shorter stream holds its last
                # row (the plane stopped logging — its final state)
                rows = [s[min(i, len(s) - 1)] for s in streams if s]
                planes[plane] = ((rows[0] if rows else None)
                                 if plane == "serve" else rows)
            record = self.join(planes)
            record["alerts"] = self.engine.evaluate(record)
            out.append(record)
        return out


def render_tower(record: dict) -> str:
    """One dashboard frame over the joined record — every plane one
    line, then the derived signals and the tower's own alert state."""
    lines = []
    planes = record.get("planes") or {}
    learners = planes.get("learner") or []
    for i, row in enumerate(learners):
        bits = [f"learner[{i}]: t={row.get('t', 0):.0f}s "
                f"env_steps={row.get('env_steps', 0)} "
                f"train={row.get('training_steps', 0)}"]
        if row.get("buffer_speed") is not None:
            bits.append(f"{row['buffer_speed']:.0f} steps/s")
        tr = row.get("trace") or {}
        e2e = (tr.get("e2e_experience_latency") or {})
        if e2e.get("p95_ms") is not None:
            bits.append(f"e2e p95={e2e['p95_ms']:.0f}ms")
        rec = row.get("recovery") or {}
        if (rec.get("supervisor") or {}).get("restarts"):
            bits.append(f"restarts={rec['supervisor']['restarts']}")
        lines.append(" ".join(bits))
    serve = planes.get("serve")
    if serve:
        sv = serve.get("serving") or {}
        adm = sv.get("admission") or {}
        bits = [f"serve: t={serve.get('t', 0):.0f}s "
                f"batches={serve.get('batches', 0)} "
                f"req={sv.get('requests', 0)}"]
        if (sv.get("latency") or {}).get("p99_ms") is not None:
            bits.append(f"p99={sv['latency']['p99_ms']:.1f}ms")
        if adm.get("shed"):
            bits.append(f"SHED={adm['shed']}")
        tr = sv.get("trace") or {}
        if tr.get("requests"):
            bits.append(f"traced={tr['requests']}")
        lines.append(" ".join(bits))
    for i, row in enumerate(planes.get("replay_service") or []):
        rs = row.get("replay_service") or {}
        sh = rs.get("shards") or {}
        sp = rs.get("spill") or {}
        bits = [f"replay[{i}]: t={row.get('t', 0):.0f}s "
                f"shards={sh.get('n', '?')} "
                f"fill={sh.get('fill_min', 0):.2f}"
                f"-{sh.get('fill_max', 0):.2f}"]
        if (rs.get("ingest") or {}).get("backlog"):
            bits.append(f"BACKLOG={rs['ingest']['backlog']}")
        if sp.get("occupancy"):
            bits.append(f"spill={sp['occupancy']}/{sp.get('capacity')}")
        pl = sp.get("promotion_latency") or {}
        if pl.get("p95_ms") is not None:
            bits.append(f"promo p95={pl['p95_ms']:.0f}ms")
        lines.append(" ".join(bits))
    for i, row in enumerate(planes.get("quality") or []):
        q = row.get("quality") or {}
        ev, cal = q.get("eval") or {}, q.get("calibration") or {}
        sh, pr = q.get("shadow") or {}, q.get("promotion") or {}
        lineage = row.get("lineage") or {}
        bits = [f"quality[{i}]: t={row.get('t', 0):.0f}s"]
        if ev.get("mean_return") is not None:
            bits.append(f"eval={ev['mean_return']:.2f}"
                        + (f"@step{ev['checkpoint_step']}"
                           if ev.get("checkpoint_step") is not None
                           else ""))
        if cal.get("gap_mean") is not None:
            bits.append(f"calib gap={cal['gap_mean']:+.3f}")
        if sh.get("divergence") is not None:
            bits.append(f"shadow div={sh['divergence']:.3f}"
                        f"/{sh.get('requests', 0)}")
        if pr.get("state") and pr["state"] != "idle":
            bits.append(f"promotion={pr['state']}"
                        + (f" age={pr['age_s']:.0f}s"
                           if pr.get("age_s") is not None else ""))
        if lineage.get("publish_stamp") is not None:
            bits.append(f"stamp={lineage['publish_stamp']}")
        lines.append(" ".join(bits))
    hosts = planes.get("hosts") or []
    if hosts:
        lines.append(f"hosts: {len(hosts)} rank row(s)")
    if not lines:
        lines.append("(no plane streams found)")
    derived = record.get("derived") or {}
    bits = []
    for key in ("e2e_p95_ms", "ingest_backlog", "serve_shed",
                "spill_promotion_p95_ms", "quality_eval_return",
                "canary_divergence", "promotion_age_s",
                "stalest_plane_age_s"):
        if derived.get(key) is not None:
            bits.append(f"{key}={derived[key]:.4g}")
    if derived.get("shed_while_backlog"):
        bits.append("SHED-WHILE-BACKLOG")
    if bits:
        lines.append("derived: " + " ".join(bits))
    offsets = (record.get("clock") or {}).get("offsets") or {}
    if offsets:
        lines.append("clock offsets: " + " ".join(
            f"{k}={v * 1e3:+.1f}ms" for k, v in sorted(offsets.items())))
    ab = record.get("alerts")
    if ab is not None:
        active = ab.get("active") or []
        lines.append("tower alerts: "
                     + (" ".join(active) if active else "none active"))
        for a in ab.get("fired") or []:
            lines.append(f"  -> FIRED {a.get('severity', '?').upper()} "
                         f"{a.get('rule')}"
                         + (f" value={a['value']:.4g}"
                            if a.get("value") is not None else ""))
    for ev in (record.get("events") or [])[-4:]:
        lines.append(f"  event[{ev.get('stream')}] "
                     f"{ev.get('severity', '?')} {ev.get('rule')}")
    return "\n".join(lines)
