"""Learning-dynamics diagnostics (ISSUE 5): what the TRAINING is doing,
fused into the jitted step — the learner-side counterpart of the PR-4
systems telemetry.

Device side (``fused_diagnostics``, called from the train-step factories
when a :class:`LearningDiag` is passed):

  * fixed-bucket histograms of |TD error|, written-back priorities, and
    |Q(s,a)| — the SAME 64-bucket log layout as telemetry/histogram.py
    (edges reused verbatim; values read as raw magnitudes, not seconds),
    computed as a bucketize + scatter-add inside the jitted program: one
    log10 + one scatter per batch, no host round-trip (Podracer-style
    fused diagnostics, arXiv 2104.06272);
  * global + per-layer-group gradient norms (torso / lstm / head);
  * a non-finite guard on loss/grad-norm (the NaN forensics trigger);
  * sample staleness: the per-sequence weight-version stamps carried from
    the actors through replay (learner publish count − generation count);
  * every ``telemetry.learning_interval`` steps, under ``lax.cond`` so the
    steady-state step is untouched: target-network parameter distance and
    the paper's stored-state quality diagnostic ΔQ (Kapturowski et al.,
    ICLR 2019 §3/Fig. 4 — the R2D2 reproduction's first direct check that
    stored-state + burn-in actually works).

ΔQ definitions (the reproduction's proxy for the paper's ĥ): replay cannot
reconstruct the true episode-start state, so the REFERENCE Q is the
longest reconstruction it affords — a zero-state unroll over the sequence's
ENTIRE stored block row (up to burn_in + block_length steps of real
history vs the window's burn_in). Against that reference, at the learning
steps:

  * ``delta_q_stored``   — Q from the stored hidden + burn-in (training's
    own path) vs the reference, normalized by the reference's max |Q|;
    small ⇒ the stored-state strategy works;
  * ``delta_q_zero``     — Q from a zero hidden + burn-in vs the same
    reference; the stored/zero gap is the paper's Fig. 4 evidence;
  * ``delta_q_recomputed`` — the same stored-vs-reference discrepancy
    normalized by the TRAINING path's max |Q| instead; the
    (stored, recomputed) pair brackets the normalization choice.

Host side (:class:`LearningAggregator`): accumulates each dispatch's
device outputs without syncing, and at the metrics flush produces the ONE
``learning`` block of the periodic TrainMetrics record — plus the NaN
forensics: on the first non-finite loss/grad-norm it writes a one-shot
``nan_dump_player{p}.json`` (step, histograms, last batch idxes/ages, lr)
and applies ``telemetry.nan_policy`` (warn | halt).
"""

import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

# bucketize_values / value_counts are re-exported here for back-compat:
# they moved to telemetry/histogram.py (the ONE home of the bucket
# layout, host and device sides — ISSUE 10 satellite) so this pillar and
# replaydiag.py share a single scatter implementation.
from r2d2_tpu.telemetry.histogram import (  # noqa: F401
    NBUCKETS, bucketize_values, value_counts, value_summary)

_EPS = 1e-3          # ΔQ normalization floor (a near-zero max-Q state must
                     # not blow the ratio up)


@dataclass(frozen=True)
class LearningDiag:
    """Static (hashable) diagnostic spec closed over by the jitted train
    step — a distinct spec compiles a distinct program, exactly like
    ReplaySpec. ``None`` in the factories means diagnostics OFF and the
    compiled step is byte-identical to the pre-diagnostics program."""

    interval: int = 200       # learner steps between ΔQ / target-distance
    dq_batch: int = 16        # sequences per ΔQ evaluation

    @classmethod
    def from_config(cls, cfg) -> Optional["LearningDiag"]:
        """The ONE gating rule: learning diagnostics require BOTH the
        master telemetry switch and the learning kill switch."""
        t = cfg.telemetry
        if not (t.enabled and t.learning_enabled):
            return None
        return cls(interval=t.learning_interval, dq_batch=t.learning_dq_batch)


# ---------------------------------------------------------------------------
# Device-side pieces (jnp; traced into the fused step)


def group_grad_norms(grads) -> Dict[str, Any]:
    """Global-norm per top-level parameter group (torso / lstm / head for
    the R2D2 network; generic over whatever groups the param tree has)."""
    import optax
    groups = grads.get("params", grads) if isinstance(grads, dict) else grads
    return {str(k): optax.global_norm(v) for k, v in sorted(groups.items())}


def param_distance(params, target_params):
    """Global L2 distance between the online and target parameter trees.
    With use_double off the target is frozen at init, so this reads as
    total parameter drift since initialization instead."""
    import jax
    import optax
    diff = jax.tree_util.tree_map(lambda p, t: p - t, params, target_params)
    return optax.global_norm(diff)


def _window_q(net, spec, params, batch, hidden):
    """Full-window unroll of the sampled batch from an explicit hidden
    state — the diagnostic's own decode (always the jnp decode path: the
    cadence is too low for the pallas kernel to matter)."""
    import jax
    import jax.numpy as jnp
    from r2d2_tpu.ops.pallas_kernels import stack_frames
    stacked = stack_frames(batch.obs, spec.seq_window, spec.frame_stack,
                           use_pallas=False,
                           out_dtype=net.module.compute_dtype,
                           out_height=spec.frame_height,
                           out_width=spec.frame_width)
    la = jax.nn.one_hot(batch.last_action, net.action_dim, dtype=jnp.float32)
    q, _ = net.module.apply(params, stacked, la, hidden)
    return q                                              # (m, T, A) f32


def delta_q_diag(net, spec, params, batch, replay_state, dq_batch: int):
    """The stored-state quality diagnostic (module docstring): returns
    (delta_q_stored, delta_q_zero, delta_q_recomputed) f32 scalars.
    ``replay_state`` supplies the full block rows the reference unroll
    needs — device placement only (host placement reports NaN)."""
    import jax
    import jax.numpy as jnp
    from r2d2_tpu.ops.indexing import learning_step_mask, online_q_positions
    from r2d2_tpu.ops.pallas_kernels import stack_frames

    m = min(dq_batch, spec.batch_size)
    sub = jax.tree_util.tree_map(
        lambda x: x[:m] if x is not None else None, batch)

    q_stored = _window_q(net, spec, params, sub, sub.hidden)
    q_zero = _window_q(net, spec, params, sub, jnp.zeros_like(sub.hidden))

    # reference: zero-state unroll over the sequence's WHOLE stored row —
    # the longest context replay affords (timeline 0 .. seq_start covers
    # up to burn_in + block_length real steps of history)
    idx = sub.idxes
    b = idx // spec.seqs_per_block
    s = idx % spec.seqs_per_block
    seq_start = replay_state.seq_start[b, s]              # (m,)
    obs_full = replay_state.obs[b]                        # (m, row, Hs, Ws)
    la_full = replay_state.last_action[b]                 # (m, la_row_len)
    stacked = stack_frames(obs_full, spec.la_row_len, spec.frame_stack,
                           use_pallas=False,
                           out_dtype=net.module.compute_dtype,
                           out_height=spec.frame_height,
                           out_width=spec.frame_width)
    la_oh = jax.nn.one_hot(la_full, net.action_dim, dtype=jnp.float32)
    zeros = jnp.zeros((m, 2, spec.hidden_dim), jnp.float32)
    q_full, _ = net.module.apply(params, stacked, la_oh, zeros)  # (m, T', A)

    L = spec.learning
    lpos = seq_start[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
    q_rec = jnp.take_along_axis(q_full, lpos[:, :, None], axis=1)
    opos = online_q_positions(sub.burn_in_steps, L)
    q_s = jnp.take_along_axis(q_stored, opos[:, :, None], axis=1)
    q_z = jnp.take_along_axis(q_zero, opos[:, :, None], axis=1)
    mask = learning_step_mask(sub.learning_steps, L)      # (m, L)
    denom = jnp.maximum(mask.sum(), 1.0)

    def dq(q, ref):
        # the paper's per-state discrepancy ||q - q_ref||2 / |max_a q_ref|,
        # averaged over the valid learning steps of the sub-batch
        d = jnp.sqrt(jnp.sum((q - ref) ** 2, axis=-1))
        scale = jnp.max(jnp.abs(ref), axis=-1) + _EPS
        return jnp.sum(d / scale * mask) / denom

    return dq(q_s, q_rec), dq(q_z, q_rec), dq(q_rec, q_s)


def version_stats(weight_version) -> Dict[str, Any]:
    """Reduced staleness stats over a (B,) version-stamp vector, for paths
    that cannot return the raw vector (the manual dp-sharded step reduces
    these with pmin/pmax/pmean). -1 stamps mean 'unknown' (pre-stamp
    blocks) and are masked out; min/max saturate at 0/-1 when all are."""
    import jax.numpy as jnp
    v = weight_version.astype(jnp.float32)
    known = (v >= 0).astype(jnp.float32)
    n_known = jnp.maximum(known.sum(), 1.0)
    big = jnp.float32(2 ** 30)
    return {
        "ld/version_min": jnp.min(jnp.where(known > 0, v, big)),
        "ld/version_max": jnp.max(jnp.where(known > 0, v, -1.0)),
        "ld/version_mean": jnp.sum(v * known) / n_known,
        "ld/unknown_frac": 1.0 - known.sum() / v.shape[0],
    }


def fused_diagnostics(net, spec, diag: LearningDiag, new_step, params,
                      target_params, batch, aux, grads, loss, grad_norm,
                      replay_state=None, raw_arrays: bool = True
                      ) -> Dict[str, Any]:
    """The device-side diagnostic block, traced into the fused step.
    Returns a dict of ``ld/``-prefixed device values for the metrics
    pytree. ``raw_arrays=False`` (manual dp-sharded path) omits the
    per-sample vectors whose values differ across shards — the caller
    psums the histograms and pmeans the scalars instead."""
    import jax
    import jax.numpy as jnp

    out: Dict[str, Any] = {
        "ld/td_hist": value_counts(aux["abs_td"], aux["mask"]),
        "ld/prio_hist": value_counts(aux["priorities"]),
        "ld/q_hist": value_counts(aux["q_chosen"], aux["mask"]),
        "ld/grad_norm": grad_norm,
        "ld/nonfinite": jnp.logical_not(
            jnp.isfinite(loss) & jnp.isfinite(grad_norm)).astype(jnp.int32),
    }
    for name, g in group_grad_norms(grads).items():
        out[f"ld/grad_norm_{name}"] = g
    out.update(version_stats(batch.weight_version))
    if raw_arrays:
        out["ld/weight_versions"] = batch.weight_version
        out["ld/batch_idxes"] = batch.idxes

    # interval-gated heavies: lax.cond executes ONE branch at runtime, so
    # the reference unroll's cost lands only on diagnostic steps
    def on(_):
        tdist = param_distance(params, target_params)
        if replay_state is not None:
            dq_s, dq_z, dq_r = delta_q_diag(net, spec, params, batch,
                                            replay_state, diag.dq_batch)
        else:
            # host placement: the full block rows live off-device; the
            # windowed strategies alone cannot form the reference
            dq_s = dq_z = dq_r = jnp.float32(jnp.nan)
        return tdist, dq_s, dq_z, dq_r

    def off(_):
        nan = jnp.float32(jnp.nan)
        return nan, nan, nan, nan

    tdist, dq_s, dq_z, dq_r = jax.lax.cond(
        (new_step % diag.interval) == 0, on, off, operand=None)
    out["ld/target_dist"] = tdist
    out["ld/delta_q_stored"] = dq_s
    out["ld/delta_q_zero"] = dq_z
    out["ld/delta_q_recomputed"] = dq_r
    return out


# ---------------------------------------------------------------------------
# Host-side aggregation + NaN forensics


def _flatten_rows(values: List[np.ndarray], width: int) -> np.ndarray:
    """Stack per-dispatch histogram outputs — (width,) per step or
    (K, width) per multi-step dispatch — into one (n, width) matrix."""
    return np.concatenate(
        [np.asarray(v).reshape(-1, width) for v in values], axis=0)


def _last_finite(values: List[np.ndarray]) -> Optional[float]:
    if not values:
        return None
    flat = np.concatenate([np.atleast_1d(np.asarray(v, np.float64))
                           for v in values])
    finite = flat[np.isfinite(flat)]
    return float(finite[-1]) if finite.size else None


class LearningAggregator:
    """Host-side accumulator for the fused step's ``ld/`` outputs: holds
    device values between metric flushes (no sync on the step path), then
    produces the periodic record's ``learning`` block in ONE device_get —
    and owns the NaN forensics (one-shot dump + nan_policy)."""

    def __init__(self, player_idx: int, save_dir: str, nan_policy: str,
                 lr: float):
        self.player_idx = player_idx
        self.save_dir = save_dir or "."
        self.nan_policy = nan_policy
        self.lr = lr
        self.nan_dumped = False
        self._pending: List[Dict[str, Any]] = []

    def on_dispatch(self, metrics: Dict[str, Any]) -> None:
        ld = {k: v for k, v in metrics.items() if k.startswith("ld/")}
        if ld:
            self._pending.append(ld)

    @property
    def dump_path(self) -> str:
        return os.path.join(self.save_dir,
                            f"nan_dump_player{self.player_idx}.json")

    def flush(self, host_step: int, publish_count: Optional[int] = None,
              occupancy_versions: Optional[List[int]] = None
              ) -> Optional[dict]:
        """Aggregate the interval and return the ``learning`` record block
        (None when no training steps ran). ``publish_count`` is the weight
        service's CURRENT publication counter (ages are measured against
        it — the flush-time value, a one-interval skew at most);
        ``occupancy_versions`` the per-ring-slot generation stamps for the
        replay-occupancy age percentiles."""
        import jax
        if not self._pending:
            return None
        pending, self._pending = self._pending, []
        host = jax.device_get(pending)

        def col(key):
            return [d[key] for d in host if key in d]

        block: Dict[str, Any] = {}
        for name, key in (("td_abs", "ld/td_hist"),
                          ("priority", "ld/prio_hist"),
                          ("q_abs", "ld/q_hist")):
            rows = col(key)
            if rows:
                counts = _flatten_rows(rows, NBUCKETS).sum(axis=0)
                block[name] = value_summary(counts)
                block[name + "_counts"] = [int(c) for c in counts]

        gn: Dict[str, Optional[float]] = {}
        for key in sorted({k for d in host for k in d
                           if k.startswith("ld/grad_norm")}):
            flat = np.concatenate([np.atleast_1d(np.asarray(v, np.float64))
                                   for v in col(key)])
            name = key[len("ld/grad_norm"):].lstrip("_") or "global"
            gn[name] = (round(float(np.max(flat)), 6),
                        round(float(np.mean(flat)), 6))
        block["grad_norm"] = {k: {"max": mx, "mean": mean}
                              for k, (mx, mean) in gn.items()}

        block["target_param_dist"] = _last_finite(col("ld/target_dist"))
        dq = {name: _last_finite(col(f"ld/delta_q_{name}"))
              for name in ("stored", "zero", "recomputed")}
        block["delta_q"] = dq if any(v is not None for v in dq.values()) \
            else None

        block["sample_age"] = self._sample_ages(host, col, publish_count)
        block["replay_age"] = self._occupancy_ages(publish_count,
                                                   occupancy_versions)
        nonfinite = int(sum(int(np.asarray(v).sum())
                            for v in col("ld/nonfinite")))
        block["nonfinite_steps"] = nonfinite
        if nonfinite:
            self._on_nonfinite(host_step, block, host)
        return block

    def _sample_ages(self, host, col, publish_count) -> Optional[dict]:
        """Sample-age distribution: learner publish count − generation
        stamp, over every sequence trained this interval. Raw stamps when
        the step returned them; the sharded paths' reduced stats
        otherwise. -1 stamps (pre-PR5 blocks) report as unknown."""
        raw = col("ld/weight_versions")
        if raw and publish_count is not None:
            v = np.concatenate([np.asarray(x).reshape(-1) for x in raw])
            known = v[v >= 0]
            out = {"unknown_frac": round(1.0 - known.size / max(v.size, 1),
                                         4)}
            if known.size:
                ages = np.maximum(publish_count - known.astype(np.int64), 0)
                out.update({
                    "p50": float(np.percentile(ages, 50)),
                    "p95": float(np.percentile(ages, 95)),
                    "max": int(ages.max()),
                    "mean": round(float(ages.mean()), 3),
                })
            return out
        vmax = col("ld/version_max")
        if vmax and publish_count is not None:
            mx = np.concatenate([np.atleast_1d(np.asarray(v, np.float64))
                                 for v in vmax])
            mn = np.concatenate([np.atleast_1d(np.asarray(v, np.float64))
                                 for v in col("ld/version_min")])
            uf = np.concatenate([np.atleast_1d(np.asarray(v, np.float64))
                                 for v in col("ld/unknown_frac")])
            known_mx = mx[mx >= 0]
            if known_mx.size == 0:
                return {"unknown_frac": 1.0}
            return {
                # min version = max age and vice versa
                "max": int(max(publish_count - float(np.min(
                    mn[mn < 2 ** 29])), 0)) if np.any(mn < 2 ** 29) else 0,
                "min": int(max(publish_count - float(np.max(known_mx)), 0)),
                "unknown_frac": round(float(np.mean(uf)), 4),
            }
        return None

    def _occupancy_ages(self, publish_count,
                        occupancy_versions) -> Optional[dict]:
        if publish_count is None or not occupancy_versions:
            return None
        v = np.asarray([x for x in occupancy_versions if x >= 0], np.int64)
        if v.size == 0:
            return {"unknown_slots": len(occupancy_versions)}
        ages = np.maximum(publish_count - v, 0)
        return {
            "p50": float(np.percentile(ages, 50)),
            "p95": float(np.percentile(ages, 95)),
            "max": int(ages.max()),
            "slots": int(v.size),
            "unknown_slots": len(occupancy_versions) - int(v.size),
        }

    def _on_nonfinite(self, host_step: int, block: dict, host) -> None:
        """The forensic path: first non-finite loss/grad-norm of the run
        writes ONE dump record, then nan_policy decides warn vs halt."""
        log = logging.getLogger(__name__)
        if not self.nan_dumped:
            self.nan_dumped = True
            last = host[-1]
            dump = {
                "step": int(host_step),
                "time": time.time(),
                "lr": self.lr,
                "nan_policy": self.nan_policy,
                "learning": {k: v for k, v in block.items()
                             if not k.endswith("_counts")},
                "histograms": {k: block[k] for k in
                               ("td_abs_counts", "priority_counts",
                                "q_abs_counts") if k in block},
                "last_batch_idxes": [
                    int(x) for x in np.asarray(
                        last.get("ld/batch_idxes", [])).reshape(-1)],
                "last_batch_weight_versions": [
                    int(x) for x in np.asarray(
                        last.get("ld/weight_versions", [])).reshape(-1)],
            }
            try:
                os.makedirs(self.save_dir, exist_ok=True)
                with open(self.dump_path, "w") as f:
                    json.dump(dump, f, indent=2)
            except OSError:
                log.exception("failed writing NaN forensics dump")
            log.warning(
                "player %d: NON-FINITE loss/grad-norm at step ~%d — "
                "forensics dumped to %s (telemetry.nan_policy=%s)",
                self.player_idx, host_step, self.dump_path, self.nan_policy)
        if self.nan_policy == "halt":
            raise RuntimeError(
                f"non-finite loss/grad-norm at step ~{host_step} "
                f"(telemetry.nan_policy=halt); forensics at "
                f"{self.dump_path}")
