"""Unified runtime telemetry (ISSUE 4).

Three layers, all behind the ``telemetry.enabled`` kill-switch:

  * **Percentile stage timers** (histogram.py, core.StageTimers):
    fixed-bucket log-scale histograms — one integer increment per
    observation on the hot path — giving P50/P95/P99 per pipeline stage,
    mergeable across threads and processes by elementwise addition.
  * **Span tracer** (spans.py): thread-local ring buffers of
    (name, t_start, t_end, tags) events at block cadence, drained
    off-thread to JSONL; ``tools/inspect.py`` exports Chrome-trace JSON
    for Perfetto, viewable alongside an xprof capture.
  * **Cross-process aggregation** (board.py): actor processes publish
    cumulative histogram counts into a shared-memory board on the flush
    cadence; the learner differences it per log interval so
    ``TrainMetrics.log`` emits ONE fleet-wide aggregated record.

``profiler.ProfilerCapture`` owns jax.profiler trace lifecycles (the
first-interval capture, mid-run ``runtime.profile_at_step`` / SIGUSR2
triggers, and tools/profile_step.py all share it).

``learning.py`` (ISSUE 5) is the LEARNING-side layer: diagnostics fused
into the jitted train step (|TD|/priority/Q histograms on the shared
bucket layout, grad norms, the stored-state ΔQ check, staleness, NaN
forensics) aggregated into the periodic record's ``learning`` block.

``resources.py`` / ``compile.py`` / ``alerts.py`` (ISSUE 7) are the
SYSTEM-HEALTH pillar: per-device memory + buffer attribution + host
RSS/CPU in the record's ``resources`` block, XLA compile/retrace
telemetry nested under it, and the declarative alert engine producing
the ``alerts`` block + ``alerts_player{p}.jsonl`` (tools/sentinel.py is
the offline/CLI face).

``replaydiag.py`` (ISSUE 10) is the REPLAY pillar: sum-tree / priority
health (leaf histograms on the shared bucket layout, effective sample
size, collapse indicators), per-slot sample-lifetime accounting (the
never-sampled-before-eviction fraction), and ε-lane provenance of
sampled batches — fused into the jitted sample/update path and
aggregated into the record's ``replay_diag`` block, with 4 stock alert
rules riding alerts.py.

``fleet.py`` (ISSUE 12) is the FLEET plane: per-rank lockstep/collective
timing gauges widened into the multihost psum row (sum/max/min step
time + one-hot straggler argmax + all-gathered per-row tables), the
rank-0 ``FleetAggregator`` merging host rows (stage histograms by
elementwise add, resource blocks, row ages) into the record's ``fleet``
block, per-rank AlertEngines on ranks > 0, clock-anchored host rows the
cross-host trace merge aligns on, and size-capped host-row rotation —
with 4 stock rules (rank_straggler, lockstep_wait_frac, fleet_desync,
missing_rank) riding alerts.py.

``quant.py`` (ISSUE 14) is the QUANTIZED-INFERENCE guard: the in-graph
f32-twin probe results (max |Q_f32 − Q_quant|, greedy-action agreement)
from local actors / the policy server / the anakin segment probe
aggregated into the record's ``quant`` block, with the
``quant_divergence`` rule riding alerts.py.

``costmodel.py`` / ``traceparse.py`` (ISSUE 9) are the COMPUTE pillar:
XLA ``cost_analysis()``/``memory_analysis()`` per-program cost tables
across every step factory (the ``make regress`` exact-match costs gate
+ the tools/roofline.py report), the analytic per-component flops/bytes
model behind the record's one-shot ``costs`` block, and the
trace→component device-time attribution over the named_scope
annotations threaded through the model/step/acting code.
"""

from r2d2_tpu.telemetry.alerts import (AlertEngine, AlertRule,
                                       default_rules, record_value)
from r2d2_tpu.telemetry.board import TelemetryBoard
from r2d2_tpu.telemetry.compile import (CompileMonitor, active_monitor,
                                        aot_coverage)
from r2d2_tpu.telemetry.costmodel import (analytic_component_costs,
                                          collect_cost_table,
                                          compare_cost_tables, peak_spec,
                                          program_cost)
from r2d2_tpu.telemetry.core import (NULL_TELEMETRY, STAGE_INDEX, STAGES,
                                     StageTimers, Telemetry,
                                     summarize_matrix)
from r2d2_tpu.telemetry.fleet import (FLEET_INFO_KEYS, FleetAggregator,
                                      RotatingJsonlWriter,
                                      cumulative_stage_matrix,
                                      merge_stage_counts, mesh_row_ranks,
                                      read_last_jsonl_row, stage_counts_dict,
                                      summarize_stage_counts)
from r2d2_tpu.telemetry.histogram import (NBUCKETS, LogHistogram,
                                          bucket_bounds, bucket_index,
                                          bucket_mid, percentile, summarize,
                                          value_summary)
from r2d2_tpu.telemetry.learning import LearningAggregator, LearningDiag
from r2d2_tpu.telemetry.profiler import ProfilerCapture, trace
from r2d2_tpu.telemetry.quality import (QualityEvaluator, QualityLedger,
                                        QualityStats, calibration_join,
                                        make_calibration_feed)
from r2d2_tpu.telemetry.quant import QuantStats
from r2d2_tpu.telemetry.replaydiag import ReplayDiag, ReplayDiagAggregator
from r2d2_tpu.telemetry.resources import (BufferRegistry, ResourceMonitor,
                                          device_memory_stats, host_usage,
                                          pytree_nbytes, register_buffer)
from r2d2_tpu.telemetry.spans import SpanTracer, chrome_trace_events
from r2d2_tpu.telemetry.traceparse import attribute_trace, component_of

__all__ = [
    "FLEET_INFO_KEYS", "NBUCKETS", "NULL_TELEMETRY", "STAGES",
    "STAGE_INDEX",
    "AlertEngine", "AlertRule", "BufferRegistry", "CompileMonitor",
    "FleetAggregator", "LearningAggregator", "LearningDiag",
    "LogHistogram",
    "ProfilerCapture", "QualityEvaluator", "QualityLedger", "QualityStats",
    "QuantStats", "ReplayDiag", "ReplayDiagAggregator",
    "ResourceMonitor", "RotatingJsonlWriter", "SpanTracer", "StageTimers",
    "Telemetry", "TelemetryBoard", "active_monitor",
    "analytic_component_costs", "aot_coverage", "attribute_trace",
    "bucket_bounds",
    "bucket_index", "bucket_mid", "chrome_trace_events",
    "collect_cost_table", "compare_cost_tables", "component_of",
    "cumulative_stage_matrix",
    "default_rules", "device_memory_stats", "host_usage",
    "merge_stage_counts", "mesh_row_ranks", "peak_spec",
    "calibration_join", "make_calibration_feed",
    "percentile", "program_cost",
    "pytree_nbytes", "read_last_jsonl_row", "record_value",
    "register_buffer", "stage_counts_dict", "summarize",
    "summarize_matrix", "summarize_stage_counts", "trace",
    "value_summary",
]
