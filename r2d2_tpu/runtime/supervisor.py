"""Learner supervision + auto-resume (ISSUE 18 tentpole, rung c).

The actor fleet has had a supervisor since PR 3 (WorkerHealth: hang
watchdog, backoff ladder, crash-loop breaker) — but the LEARNER process
itself was the last single point of failure: an OOM, a preempted VM, or
a plain bug killed the whole run and a human had to relaunch with
``--runtime.resume=<path>`` by hand. This module closes that loop:

  * the training run becomes a CHILD process of a thin supervisor
    (``supervise_train``; ``cli/train.py`` routes here under
    ``runtime.auto_resume``);
  * a child that dies is relaunched from its newest checkpoint
    (``latest_checkpoint``) — with the snapshot plane on
    (``runtime.snapshot_interval``), the relaunch also restores the
    replay buffer contents, so learning resumes at most one snapshot
    interval behind where it died;
  * SIGTERM/SIGINT (preemption) forwards to the child, whose clean-stop
    path writes the final checkpoint + replay snapshot; the supervisor
    then exits WITHOUT relaunching — a preemption is not a crash;
  * repeated failures ride the SAME WorkerHealth policy the actor fleet
    uses (one slot, no heartbeat board): exponential backoff between
    relaunches, and the crash-loop breaker turns a doomed run into one
    loud error instead of an infinite relaunch mill.

The child's restart ordinal crosses the spawn boundary in the
``R2D2_SUPERVISOR_RESTARTS`` env var, which the learner's recovery
telemetry block surfaces — the ``recovery_loop`` alert rule reads it.

The child pid is published to ``{save_dir}/learner.pid`` (rewritten per
spawn) so the kill drill (tools/chaos.py --kill-learner) can SIGKILL the
actual training process, not the supervisor.
"""

import logging
import os
import signal
import time
from typing import Optional

log = logging.getLogger(__name__)

RESTARTS_ENV = "R2D2_SUPERVISOR_RESTARTS"


def _pid_path(save_dir: str) -> str:
    return os.path.join(save_dir or ".", "learner.pid")


def _child_entry(cfg_dict: dict, actor_mode: str,
                 max_steps: Optional[int], max_seconds: Optional[float],
                 restarts: int) -> None:
    """Spawn target for one training incarnation (module-level: the
    ``spawn`` start method pickles by reference). The restart ordinal is
    exported BEFORE the heavy imports so everything in the child —
    including the recovery telemetry block — sees it."""
    os.environ[RESTARTS_ENV] = str(restarts)
    from r2d2_tpu.config import Config
    from r2d2_tpu.runtime.orchestrator import train
    from r2d2_tpu.utils import pin_platform
    pin_platform()
    cfg = Config.from_dict(cfg_dict)

    def log_fn(record: dict) -> None:
        print(" | ".join(f"{k}={v}" for k, v in record.items()
                         if v is not None), flush=True)

    train(cfg, max_training_steps=max_steps, max_seconds=max_seconds,
          actor_mode=actor_mode, log_fn=log_fn)


def supervise_train(cfg, *, actor_mode: str = "process",
                    max_steps: Optional[int] = None,
                    max_seconds: Optional[float] = None) -> int:
    """Run training under supervision; returns the number of relaunches
    performed. Blocks until the run completes, a stop signal arrives, or
    the crash-loop breaker trips (which raises — a run that cannot stay
    up is an error, not a silent exit)."""
    import multiprocessing as mp

    from r2d2_tpu.runtime.checkpoint import latest_checkpoint
    from r2d2_tpu.runtime.feeder import WorkerHealth

    if cfg.mesh.multihost and cfg.mesh.num_processes > 1:
        raise NotImplementedError(
            "runtime.auto_resume supervises the single-host train() child; "
            "multihost jobs are supervised by their cluster scheduler — "
            "rely on runtime.resume + the rank-0 snapshot twin instead")

    ctx = mp.get_context("spawn")
    # ONE slot, no heartbeat board: the learner child has no heartbeat
    # row — liveness IS process liveness; the ladder/breaker knobs are
    # the same runtime.* fields the actor fleet uses
    health = WorkerHealth.from_runtime(1, None, cfg.runtime)
    save_dir = cfg.runtime.save_dir or "."
    # the checkpoint namespace this supervisor resumes from: player 0,
    # or the one player this job runs under per-player-job composition
    player = (cfg.multiplayer.player_id
              if (cfg.multiplayer.enabled and cfg.multiplayer.player_id >= 0)
              else 0)
    deadline = time.time() + max_seconds if max_seconds else None

    state = {"child": None, "stopping": False}

    def _forward(signum, frame):
        # preemption path: relay the stop to the child (whose clean-stop
        # path writes the final checkpoint + replay snapshot) and stop
        # relaunching — a requested stop is not a crash
        state["stopping"] = True
        child = state["child"]
        if child is not None and child.pid is not None:
            try:
                os.kill(child.pid, signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass

    prev_handlers = {}
    import threading
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, _forward)
            except (ValueError, OSError):
                pass

    cfg_dict = cfg.to_dict()
    restarts = 0
    pid_file = _pid_path(save_dir)
    try:
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
            # the child is NOT a daemon: it spawns the actor fleet (a
            # daemonic process may not have children)
            child = ctx.Process(
                target=_child_entry,
                args=(cfg_dict, actor_mode, max_steps, remaining, restarts),
                name=f"learner-child-{restarts}")
            child.start()
            state["child"] = child
            os.makedirs(save_dir, exist_ok=True)
            with open(pid_file, "w") as f:
                f.write(str(child.pid))
            while child.is_alive():
                child.join(timeout=0.25)
            code = child.exitcode
            if state["stopping"]:
                log.info("supervisor: stop requested; child exited %s — "
                         "not relaunching", code)
                break
            if code == 0:
                break                       # run completed
            # crash: negative exitcode = killed by signal
            now = time.time()
            log.warning(
                "supervisor: learner child died (exitcode %s) after %d "
                "prior restart(s) — routing through relaunch", code,
                restarts)
            health.on_failure(0, now)
            if health.is_parked(0):
                raise RuntimeError(
                    f"learner crash-loop breaker tripped: "
                    f"{restarts + 1} failures within "
                    f"{cfg.runtime.restart_window_s:.0f}s — giving up "
                    f"(last exitcode {code})")
            while not health.respawn_due(0, time.time()):
                if state["stopping"]:
                    break
                time.sleep(0.05)
            if state["stopping"]:
                break
            health.on_spawn(0)
            restarts += 1
            # relaunch from the newest checkpoint; the restore path also
            # reloads the replay snapshot (runtime.restore_replay). No
            # checkpoint yet (died during warm-up) = fresh start.
            ckpt = latest_checkpoint(save_dir, cfg.env.game_name, player)
            cfg_dict = cfg.to_dict()
            cfg_dict["runtime"]["resume"] = ckpt or ""
            cfg_dict["runtime"]["pretrain"] = ""
            log.warning("supervisor: relaunch %d resuming from %s",
                        restarts, ckpt or "<no checkpoint — fresh start>")
    finally:
        child = state["child"]
        if child is not None and child.is_alive():
            child.terminate()
            child.join(timeout=10.0)
            if child.is_alive():
                child.kill()
                child.join(timeout=2.0)
        try:
            os.remove(pid_file)
        except OSError:
            pass
        for sig, handler in prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass
    return restarts
