"""Weight service: learner → actors parameter distribution.

The reference publishes CPU state_dicts into Ray's plasma object store every
2 learner steps and actors ray.get them every 400 env steps
(/root/reference/worker.py:286-290,567-576). Here the transport is a
POSIX shared-memory segment with a seqlock header — single writer (learner),
many readers (actor processes), zero RPCs, torn reads detected by version
mismatch and retried.

Layout: [u64 version][u64 crc32][f32 payload...] where payload is the ravel
of the param pytree (jax.flatten_util.ravel_pytree order). Version is odd
while a write is in flight; readers spin until they observe the same even
version before and after the copy.

Torn-read impossibility, by architecture:

* x86/amd64 (TSO): stores retire in program order and loads are not
  reordered with other loads, so a reader that observes the same EVEN
  version before and after its copy cannot have copied a half-written
  payload — the classic seqlock argument. The crc32 word is unused
  (written once as 0) so the hot publish path stays a plain memcpy.
* weakly-ordered hosts (ARM, POWER): CPython emits no fences, so the
  version stores may become visible before/after the payload stores and
  the seqlock argument fails. There, every publish also stores
  ``crc32(payload) ^ version`` and every read validates the copied
  payload against the header crc AT the observed version before accepting
  it. Binding the version into the crc rejects both failure shapes: a
  torn copy (payload bytes mismatch the crc) and a consistent-but-STALE
  copy (new version visible before the new payload/crc — the old crc no
  longer matches under the new version, so the reader retries instead of
  recording last_version against data it never received). A wrong accept
  needs a crc32 collision (~2**-32 per poll, transient: the next poll
  re-reads). Validation is keyed off ``platform.machine()`` at import,
  identical in writer and readers because shm is same-host by nature.

``InProcWeightStore`` is the thread-mode twin (tests, single-process runs).

Quantized inference (ISSUE 14): when ``network.inference_dtype`` is
"bf16"/"int8" the published TREE is the inference bundle
(models/network.py ``make_inference_bundle`` — f32 params + the
quantized twin + the publication stamp), built ONCE per publish by the
``make_publish_preparer`` wrapper below and shipped through the exact
same publisher/subscriber machinery: ``ravel_pytree`` promotes the
mixed int8/f32 bundle to one f32 payload and the unravel restores every
leaf's dtype exactly (int8 values are integers ≤ 127, so the f32
round-trip is lossless — tested). Readers therefore receive a
publish-time twin and never requantize on the hot path.
"""

import platform
import threading
import zlib
from multiprocessing import shared_memory
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.flatten_util import ravel_pytree

# x86-TSO machines where the bare seqlock ordering argument holds; anything
# else pays the crc32 validation path (see module docstring)
_TSO_MACHINES = ("x86_64", "amd64", "i386", "i686", "x86")
_NEEDS_CHECKSUM = platform.machine().lower() not in _TSO_MACHINES
_HEADER_BYTES = 16                      # u64 version + u64 crc32


def untrack_attached_shm(shm: shared_memory.SharedMemory) -> None:
    """De-register an ATTACHED segment from this process's resource
    tracker. On Python < 3.13 attaching registers the segment too, and a
    child's tracker UNLINKS it when that child exits — which would destroy
    the parent's live segment under actor restarts
    (``SharedMemory(track=False)`` only exists from 3.13). Shared by the
    weight subscriber and the shm block ring (shm_feeder.py)."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def _flatten(params) -> Tuple[np.ndarray, Any]:
    flat, unravel = ravel_pytree(params)
    return np.asarray(jax.device_get(flat), np.float32), unravel


def make_publish_preparer(net):
    """The ONE publish-time quantization hook (ISSUE 14), shared by the
    single-host orchestrator and the multihost trainer so the two
    cannot drift: None when ``net.config.inference_dtype == "f32"``
    (callers publish raw params — byte-identical plumbing); otherwise a
    jitted ``prepare(params, stamp) -> bundle`` building the inference
    bundle (f32 + quantized twin + stamp) exactly once per publication.
    Callers stamp ``publish_count + 1`` (the publication the bundle
    rides in) so twin staleness is testable end-to-end."""
    if net.config.inference_dtype == "f32":
        return None
    import jax as _jax

    from r2d2_tpu.models.network import make_inference_bundle

    @_jax.jit
    def prepare(params, stamp):
        return make_inference_bundle(net, params, stamp)

    return lambda params, stamp: prepare(params, np.int32(stamp))


def wrap_publish(publish, preparer, publish_count_fn):
    """Compose a store/publisher ``publish`` with the quantization
    preparer: the learner keeps calling ``publish(params)`` and the twin
    is built + stamped here, once per publication. Identity when
    ``preparer`` is None."""
    if preparer is None:
        return publish

    def publish_bundle(params):
        publish(preparer(params, publish_count_fn() + 1))

    return publish_bundle


class WeightPublisher:
    """Learner-side writer. Owns (creates/destroys) the shm segment."""

    def __init__(self, params, name: Optional[str] = None):
        flat, self._unravel = _flatten(params)
        self.num_weights = flat.shape[0]
        nbytes = _HEADER_BYTES + 4 * self.num_weights
        self.shm = shared_memory.SharedMemory(create=True, size=nbytes, name=name)
        self.name = self.shm.name
        self._version = np.ndarray((1,), np.uint64, self.shm.buf, 0)
        self._crc = np.ndarray((1,), np.uint64, self.shm.buf, 8)
        self._payload = np.ndarray((self.num_weights,), np.float32,
                                   self.shm.buf, _HEADER_BYTES)
        self._version[0] = 0
        self._crc[0] = 0
        self.publish(params)

    def publish(self, params) -> None:
        # Ordering: see the module docstring — the bare version/payload/
        # version protocol is sound under x86-TSO; on weakly-ordered hosts
        # readers additionally validate the crc stored here.
        flat = np.asarray(jax.device_get(ravel_pytree(params)[0]), np.float32)
        v = int(self._version[0])
        self._version[0] = v + 1       # odd: write in flight
        if _NEEDS_CHECKSUM:
            # bind the FINAL even version into the crc (see module
            # docstring: rejects consistent-but-stale reads, not just torn)
            self._crc[0] = zlib.crc32(flat) ^ ((v + 2) & 0xFFFFFFFF)
        self._payload[:] = flat
        self._version[0] = v + 2       # even: stable

    @property
    def publish_count(self) -> int:
        """Monotonic publication counter (seqlock versions are 2 per
        publish) — the learner-side clock for staleness accounting: block
        generation stamps and sample ages are measured in these units."""
        return int(self._version[0]) // 2

    def close(self) -> None:
        self.shm.close()
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


class WeightSubscriber:
    """Actor-side reader. ``template`` provides the pytree structure."""

    def __init__(self, name: str, template):
        flat, self._unravel = _flatten(template)
        self.num_weights = flat.shape[0]
        self.shm = shared_memory.SharedMemory(name=name)
        untrack_attached_shm(self.shm)
        self._version = np.ndarray((1,), np.uint64, self.shm.buf, 0)
        self._crc = np.ndarray((1,), np.uint64, self.shm.buf, 8)
        self._payload = np.ndarray((self.num_weights,), np.float32,
                                   self.shm.buf, _HEADER_BYTES)
        self.last_version = 0

    def poll(self):
        """Return fresh params, or None if unchanged / write in flight."""
        v1 = int(self._version[0])
        if v1 == self.last_version or v1 % 2 == 1:
            return None
        for _ in range(64):             # seqlock retry loop
            buf = self._payload.copy()
            crc = int(self._crc[0])
            v2 = int(self._version[0])
            if v1 == v2 and v2 % 2 == 0 and (
                    not _NEEDS_CHECKSUM
                    or (zlib.crc32(buf) ^ (v2 & 0xFFFFFFFF)) == crc):
                self.last_version = v2
                return self._unravel(buf)
            v1 = int(self._version[0])
        return None

    @property
    def publish_count(self) -> int:
        """Publication counter of the params this reader last adopted
        (0 = still on its locally-initialized copy) — what the actor
        stamps into each emitted block's weight_version."""
        return self.last_version // 2

    def close(self) -> None:
        self.shm.close()


class InProcWeightStore:
    """Thread-mode store: one process, no shm. Same poll() contract."""

    def __init__(self, params):
        self._lock = threading.Lock()
        self._params = jax.device_get(params)
        self._version = 1
        self._reader_versions = {}

    def publish(self, params) -> None:
        with self._lock:
            self._params = jax.device_get(params)
            self._version += 1

    @property
    def publish_count(self) -> int:
        """Current publication counter (the construction params count as
        publication 1) — same staleness clock as WeightPublisher's."""
        with self._lock:
            return self._version

    def reader_version(self, reader_id: int = 0) -> int:
        """Publication counter of the params reader ``reader_id`` last
        adopted. A reader that never polled holds the construction params
        (version 1) — thread actors are spawned with exactly those."""
        with self._lock:
            return self._reader_versions.get(reader_id, 1)

    def poll(self, reader_id: int = 0):
        with self._lock:
            if self._reader_versions.get(reader_id) == self._version:
                return None
            self._reader_versions[reader_id] = self._version
            return self._params

    def current(self, reader_id: Optional[int] = None):
        """The CURRENT published tree, without the poll's seen-version
        gate — what a (re)spawned thread actor starts from: a respawn's
        dead predecessor already consumed the slot's reader version, so
        its first poll() returns None and construction from anything
        but the live tree would act on stale weights until the next
        publish. Passing ``reader_id`` also marks the version adopted
        (the constructor took exactly this tree)."""
        with self._lock:
            if reader_id is not None:
                self._reader_versions[reader_id] = self._version
            return self._params
