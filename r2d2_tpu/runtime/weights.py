"""Weight service: learner → actors parameter distribution.

The reference publishes CPU state_dicts into Ray's plasma object store every
2 learner steps and actors ray.get them every 400 env steps
(/root/reference/worker.py:286-290,567-576). Here the transport is a
POSIX shared-memory segment with a seqlock header — single writer (learner),
many readers (actor processes), zero RPCs, torn reads detected by version
mismatch and retried.

Layout: [u64 version][f32 payload...] where payload is the ravel of the param
pytree (jax.flatten_util.ravel_pytree order). Version is odd while a write is
in flight; readers spin until they observe the same even version before and
after the copy.

``InProcWeightStore`` is the thread-mode twin (tests, single-process runs).
"""

import threading
from multiprocessing import shared_memory
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.flatten_util import ravel_pytree


def untrack_attached_shm(shm: shared_memory.SharedMemory) -> None:
    """De-register an ATTACHED segment from this process's resource
    tracker. On Python < 3.13 attaching registers the segment too, and a
    child's tracker UNLINKS it when that child exits — which would destroy
    the parent's live segment under actor restarts
    (``SharedMemory(track=False)`` only exists from 3.13). Shared by the
    weight subscriber and the shm block ring (shm_feeder.py)."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def _flatten(params) -> Tuple[np.ndarray, Any]:
    flat, unravel = ravel_pytree(params)
    return np.asarray(jax.device_get(flat), np.float32), unravel


class WeightPublisher:
    """Learner-side writer. Owns (creates/destroys) the shm segment."""

    def __init__(self, params, name: Optional[str] = None):
        flat, self._unravel = _flatten(params)
        self.num_weights = flat.shape[0]
        nbytes = 8 + 4 * self.num_weights
        self.shm = shared_memory.SharedMemory(create=True, size=nbytes, name=name)
        self.name = self.shm.name
        self._version = np.ndarray((1,), np.uint64, self.shm.buf, 0)
        self._payload = np.ndarray((self.num_weights,), np.float32, self.shm.buf, 8)
        self._version[0] = 0
        self.publish(params)

    def publish(self, params) -> None:
        # Seqlock ordering note: the version/payload/version stores have no
        # explicit memory barriers — readers are correct under x86-TSO store
        # ordering (this deployment). On weakly-ordered hosts (ARM) a reader
        # could observe an even version with a partially updated payload;
        # add a fence (e.g. write payload via a memoryview + os.write-style
        # flush, or an atomic version word) before targeting ARM.
        flat = np.asarray(jax.device_get(ravel_pytree(params)[0]), np.float32)
        self._version[0] += 1          # odd: write in flight
        self._payload[:] = flat
        self._version[0] += 1          # even: stable

    def close(self) -> None:
        self.shm.close()
        try:
            self.shm.unlink()
        except FileNotFoundError:
            pass


class WeightSubscriber:
    """Actor-side reader. ``template`` provides the pytree structure."""

    def __init__(self, name: str, template):
        flat, self._unravel = _flatten(template)
        self.num_weights = flat.shape[0]
        self.shm = shared_memory.SharedMemory(name=name)
        untrack_attached_shm(self.shm)
        self._version = np.ndarray((1,), np.uint64, self.shm.buf, 0)
        self._payload = np.ndarray((self.num_weights,), np.float32, self.shm.buf, 8)
        self.last_version = 0

    def poll(self):
        """Return fresh params, or None if unchanged / write in flight."""
        v1 = int(self._version[0])
        if v1 == self.last_version or v1 % 2 == 1:
            return None
        for _ in range(64):             # seqlock retry loop
            buf = self._payload.copy()
            v2 = int(self._version[0])
            if v1 == v2 and v2 % 2 == 0:
                self.last_version = v2
                return self._unravel(buf)
            v1 = int(self._version[0])
        return None

    def close(self) -> None:
        self.shm.close()


class InProcWeightStore:
    """Thread-mode store: one process, no shm. Same poll() contract."""

    def __init__(self, params):
        self._lock = threading.Lock()
        self._params = jax.device_get(params)
        self._version = 1
        self._reader_versions = {}

    def publish(self, params) -> None:
        with self._lock:
            self._params = jax.device_get(params)
            self._version += 1

    def poll(self, reader_id: int = 0):
        with self._lock:
            if self._reader_versions.get(reader_id) == self._version:
                return None
            self._reader_versions[reader_id] = self._version
            return self._params
